"""Sparse tensors (reference: paddle/phi/core/sparse_coo_tensor.h /
sparse_csr_tensor.h, kernels paddle/phi/kernels/sparse/, Python
python/paddle/sparse/).

TPU design: wraps jax.experimental.sparse BCOO (TPU-lowerable; XLA turns
sparse@dense matmuls into gather/scatter + MXU tiles). CSR is kept as a
view-format conversion — BCOO is the compute format on TPU.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from ..enforce import InvalidTypeError
from ..enforce import enforce, enforce_eq
import numpy as np
from jax.experimental import sparse as jsparse

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_sparse", "to_dense", "to_sparse_coo", "to_sparse_csr",
           "add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul", "mv", "addmm", "nnz", "coalesce", "transpose",
           "reshape", "sum", "softmax", "is_same_shape", "mask_as",
           "relu", "relu6", "leaky_relu", "tanh", "sin", "sinh", "asin",
           "asinh", "tan", "atan", "atanh", "sqrt", "square", "log1p",
           "expm1", "abs", "neg", "pow", "cast", "deg2rad", "rad2deg",
           "isnan", "nn"]

SparseCooTensor = jsparse.BCOO


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True):
    """indices: [ndim, nnz] (reference layout); values: [nnz]."""
    del place, stop_gradient
    indices = jnp.asarray(indices, jnp.int32).T  # BCOO wants [nnz, ndim]
    values = jnp.asarray(values, dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in jnp.max(indices, axis=0))
    return jsparse.BCOO((values, indices), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """Build from CSR triplets; stored as BCOO (the TPU compute format)."""
    crows = np.asarray(crows)
    cols = np.asarray(cols)
    values = jnp.asarray(values, dtype)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.asarray(np.stack([rows, cols]), jnp.int32).T
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def is_sparse(x) -> bool:
    return isinstance(x, jsparse.JAXSparse)


def to_dense(x):
    return x.todense() if is_sparse(x) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim: Optional[int] = None):
    del sparse_dim
    return jsparse.BCOO.fromdense(jnp.asarray(x))


def nnz(x) -> int:
    return int(x.nse)


def add(a, b):
    if is_sparse(a) and is_sparse(b):
        # true COO add: concatenate coordinate lists and merge duplicates —
        # O(nnz_a + nnz_b), the dense round trip the reference's COO
        # kernels avoid (round-2 fell back to todense here)
        from ..enforce import enforce_eq
        enforce_eq(tuple(a.shape), tuple(b.shape),
                   f"sparse.add shape mismatch: {tuple(a.shape)} vs "
                   f"{tuple(b.shape)}", op="sparse.add")
        dt = jnp.result_type(a.data.dtype, b.data.dtype)
        data = jnp.concatenate([a.data.astype(dt), b.data.astype(dt)])
        idx = jnp.concatenate([a.indices, b.indices])
        out = jsparse.BCOO((data, idx), shape=a.shape)
        import jax as _jax
        if isinstance(out.data, _jax.core.Tracer):
            # under jit nse must be static: bound = nnz_a + nnz_b (tail
            # padded with sentinel indices per BCOO semantics)
            return out.sum_duplicates(nse=a.nse + b.nse)
        # eager: exact nse so nnz()/indices expose no sentinel padding
        return out.sum_duplicates()
    return to_dense(a) + to_dense(b)


def matmul(a, b):
    """sparse @ dense (or dense @ sparse) — XLA lowers the gather/dot."""
    return a @ b


def masked_matmul(a, b, mask):
    """(a @ b) sampled at mask's sparsity pattern (reference:
    paddle.sparse.masked_matmul) — a REAL SDDMM: gathers the mask's row of
    `a` and column of `b` per nonzero and contracts, O(nnz * K) compute
    and memory; the dense [M, N] product is never materialized (round-2
    computed it and sampled)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    idx = mask.indices  # [nnz, 2]
    a_rows = a[idx[:, 0], :]            # [nnz, K]
    b_cols = b[:, idx[:, 1]].T          # [nnz, K]
    vals = jnp.sum(a_rows * b_cols, axis=-1)
    return jsparse.BCOO((vals, mask.indices), shape=mask.shape)


def _unary(fn):
    def op(x):
        if is_sparse(x):
            return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape)
        return fn(jnp.asarray(x))
    return op


relu = _unary(jax.nn.relu)
tanh = _unary(jnp.tanh)


# ---------------------------------------------------------------------------
# round-2 surface (reference: python/paddle/sparse/{unary,binary}.py —
# values-only elementwise ops, CSR conversions, reductions, softmax)
# ---------------------------------------------------------------------------

def to_sparse_csr(x):
    """CSR view of a 2-D sparse/dense tensor: (crows, cols, values) with
    BCOO as the compute format (reference Tensor.to_sparse_csr)."""
    coo = x if is_sparse(x) else to_sparse_coo(x)
    coo = coalesce(coo)
    idx = np.asarray(coo.indices)
    order = np.lexsort((idx[:, 1], idx[:, 0]))
    rows, cols = idx[order, 0], idx[order, 1]
    crows = np.zeros(coo.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return (jnp.asarray(crows), jnp.asarray(cols),
            jnp.asarray(np.asarray(coo.data)[order]))


def coalesce(x, name=None):
    """Merge duplicate indices (reference sparse.coalesce)."""
    return jsparse.bcoo_sum_duplicates(x) if hasattr(
        jsparse, "bcoo_sum_duplicates") else x.sum_duplicates()


def transpose(x, perm, name=None):
    if is_sparse(x):
        return jsparse.bcoo_transpose(x, permutation=tuple(perm))
    return jnp.transpose(x, perm)


def reshape(x, shape, name=None):
    if is_sparse(x):
        return jsparse.bcoo_reshape(x, new_sizes=tuple(shape))
    return jnp.reshape(x, shape)


def _linearize(indices, shape):
    """[nnz, nd] coordinate rows -> scalar keys (row-major). Keys use the
    widest available integer; without jax x64 a shape whose element count
    exceeds int32 cannot be keyed — raise instead of silently wrapping."""
    numel = int(np.prod([int(s) for s in shape])) if len(shape) else 1
    if numel > np.iinfo(np.int32).max and not jax.config.jax_enable_x64:
        raise OverflowError(
            f"sparse merge over shape {tuple(shape)} needs int64 linear "
            "keys; enable jax_enable_x64")
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    mult = np.cumprod([1] + [int(s) for s in shape[::-1]][:-1])[::-1]
    return indices.astype(dt) @ jnp.asarray(mult.copy(), dt)


def _delinearize(keys, shape):
    """Scalar keys -> [m, nd] coordinate rows (row-major)."""
    cols = []
    for s in [int(s) for s in shape[::-1]]:
        cols.append(keys % s)
        keys = keys // s
    return jnp.stack(cols[::-1], axis=1).astype(jnp.int32)


def _aligned_union(a, b):
    """Coalesced union merge of two same-shape COO tensors: returns
    (union_indices [m, nd], va, vb, in_a, in_b) with zero fill on the
    absent side — the O(nnz log nnz) COO merge the reference's
    elementwise kernels walk (paddle/phi/kernels/sparse/
    elementwise_kernel.h Merge). Eager-only: the union size is
    data-dependent, exactly like the reference's out_nnz."""
    from ..enforce import enforce_eq
    enforce_eq(tuple(a.shape), tuple(b.shape),
               f"sparse elementwise shape mismatch: {tuple(a.shape)} vs "
               f"{tuple(b.shape)}", op="sparse.elementwise")
    a, b = coalesce(a), coalesce(b)
    dt = jnp.result_type(a.data.dtype, b.data.dtype)
    ka = _linearize(a.indices, a.shape)
    kb = _linearize(b.indices, b.shape)
    keys = jnp.unique(jnp.concatenate([ka, kb]))
    m = keys.shape[0]
    pa = jnp.searchsorted(keys, ka)
    pb = jnp.searchsorted(keys, kb)
    va = jnp.zeros((m,), dt).at[pa].set(a.data.astype(dt))
    vb = jnp.zeros((m,), dt).at[pb].set(b.data.astype(dt))
    in_a = jnp.zeros((m,), bool).at[pa].set(True)
    in_b = jnp.zeros((m,), bool).at[pb].set(True)
    return _delinearize(keys, a.shape), va, vb, in_a, in_b


def _sample_at(x_sparse, dense):
    """dense values gathered at the sparse operand's coordinates (the
    dense side is broadcast to the sparse shape first, so lower-rank and
    0-d operands keep numpy broadcasting semantics)."""
    dense = jnp.broadcast_to(jnp.asarray(dense), tuple(x_sparse.shape))
    idx = x_sparse.indices
    return dense[tuple(idx[:, d] for d in range(idx.shape[1]))]


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Reduction over a sparse tensor via segment ops on the stored
    values — the dense array is never built (reference:
    paddle/phi/kernels/sparse/cpu/sum_kernel.cc; returns a sparse
    tensor like paddle.sparse.sum, shape [1] for axis=None)."""
    if not is_sparse(x):
        out = jnp.sum(jnp.asarray(x), axis=axis, keepdims=keepdim)
        return out.astype(dtype) if dtype is not None else out
    if isinstance(x.data, jax.core.Tracer):
        raise InvalidTypeError(
            "sparse.sum is eager-only (the output nnz is data-dependent, "
            "like the reference kernel's out_nnz) — call it outside jit, "
            "or densify the input explicitly first")
    xc = coalesce(x)
    vals = xc.data
    if dtype is None and vals.dtype in (jnp.bool_, jnp.int32):
        # reference promotes bool/int32 sums to int64; under 32-bit jax
        # that truncates back, so promote only when x64 is live
        dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    if axis is None:
        total = jnp.sum(vals, dtype=dtype)[None]
        shape = (1,) * x.ndim if keepdim else (1,)
        idx = jnp.zeros((1, len(shape)), jnp.int32)
        return jsparse.BCOO((total, idx), shape=shape)
    axes = sorted({int(a) % x.ndim
                   for a in (axis if isinstance(axis, (list, tuple))
                             else [axis])})
    kept = [d for d in range(x.ndim) if d not in axes]
    kept_shape = [int(x.shape[d]) for d in kept]
    key = (_linearize(xc.indices[:, kept], kept_shape) if kept
           else jnp.zeros((xc.nse,), jnp.int32))
    ukeys, inv = jnp.unique(key, return_inverse=True)
    out_vals = jax.ops.segment_sum(vals.astype(dtype) if dtype else vals,
                                   inv.reshape(-1),
                                   num_segments=int(ukeys.shape[0]))
    kept_idx = _delinearize(ukeys, kept_shape) if kept else \
        jnp.zeros((int(ukeys.shape[0]), 0), jnp.int32)
    if keepdim:
        cols = []
        k = 0
        for d in range(x.ndim):
            if d in axes:
                cols.append(jnp.zeros((kept_idx.shape[0],), jnp.int32))
            else:
                cols.append(kept_idx[:, k])
                k += 1
        out_idx = jnp.stack(cols, axis=1)
        out_shape = tuple(1 if d in axes else int(x.shape[d])
                          for d in range(x.ndim))
    else:
        out_idx = kept_idx
        out_shape = tuple(kept_shape)
        if not kept:
            out_idx = jnp.zeros((1, 1), jnp.int32)
            out_shape = (1,)
    return jsparse.BCOO((out_vals, out_idx), shape=out_shape)


def softmax(x, axis=-1, name=None):
    """Row softmax over the SPARSITY PATTERN (reference:
    sparse/nn/functional/activation.py softmax — only stored values
    participate; zeros stay zero). 2-D, last axis."""
    enforce(axis in (-1, x.ndim - 1), "sparse softmax: last axis only",
            op="sparse.softmax", axis=axis)
    enforce_eq(x.ndim, 2, "sparse softmax supports 2-D tensors",
               op="sparse.softmax")
    xc = coalesce(x) if is_sparse(x) else to_sparse_coo(x)
    rows = xc.indices[:, 0]
    vals = xc.data.astype(jnp.float32)
    # segment softmax over rows
    nrows = xc.shape[0]
    row_max = jax.ops.segment_max(vals, rows, num_segments=nrows)
    p = jnp.exp(vals - row_max[rows])
    denom = jax.ops.segment_sum(p, rows, num_segments=nrows)
    out = (p / denom[rows]).astype(xc.data.dtype)
    return jsparse.BCOO((out, xc.indices), shape=xc.shape)


def subtract(a, b, name=None):
    return add(a, jsparse.BCOO((-b.data, b.indices), shape=b.shape)
               if is_sparse(b) else -jnp.asarray(b))


def multiply(a, b, name=None):
    """Elementwise multiply ON THE PATTERN — never densified:
    sparse*sparse computes on the INTERSECTION of the two patterns (the
    product is zero anywhere either operand is an implicit zero);
    sparse*dense samples the dense at the sparse coordinates;
    sparse*scalar scales values. Reference:
    paddle/phi/kernels/sparse/elementwise_kernel.h."""
    if is_sparse(a) and jnp.isscalar(b):
        return jsparse.BCOO((a.data * b, a.indices), shape=a.shape)
    if jnp.isscalar(a) and is_sparse(b):
        return jsparse.BCOO((a * b.data, b.indices), shape=b.shape)
    if is_sparse(a) and is_sparse(b):
        idx, va, vb, in_a, in_b = _aligned_union(a, b)
        both = in_a & in_b
        return jsparse.BCOO(((va * vb)[both], idx[both]), shape=a.shape)
    if is_sparse(a):
        return jsparse.BCOO((a.data * _sample_at(a, jnp.asarray(b)),
                             a.indices), shape=a.shape)
    if is_sparse(b):
        return jsparse.BCOO((_sample_at(b, jnp.asarray(a)) * b.data,
                             b.indices), shape=b.shape)
    return jnp.asarray(a) * jnp.asarray(b)


def divide(a, b, name=None):
    """Elementwise divide ON THE PATTERN: sparse/sparse computes on the
    UNION of the patterns (a-only positions -> a/0 = ±inf, b-only ->
    0/b = 0, matching dense semantics at every stored coordinate).
    Positions in NEITHER pattern stay implicit zeros — dense math calls
    those 0/0 = nan; the reference's CPU kernel expands the divisor to
    the full coordinate space to store them (elementwise_kernel.cc
    is_divide b_full_index), which is a dense-sized result in sparse
    clothing. We keep the union contract instead; densify explicitly if
    nan-at-empty semantics are needed."""
    if is_sparse(a) and jnp.isscalar(b):
        return jsparse.BCOO((a.data / b, a.indices), shape=a.shape)
    if is_sparse(a) and is_sparse(b):
        idx, va, vb, _, _ = _aligned_union(a, b)
        return jsparse.BCOO((va / vb, idx), shape=a.shape)
    if is_sparse(a):
        return jsparse.BCOO((a.data / _sample_at(a, jnp.asarray(b)),
                             a.indices), shape=a.shape)
    # dense / sparse is dense everywhere (x/0 = inf at every implicit
    # zero) — inherently a dense-sized result; keep the sparse return
    # type for API continuity
    return jsparse.BCOO.fromdense(jnp.asarray(a) / to_dense(b))


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (reference sparse.mv)."""
    return x @ vec


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x (reference sparse.addmm)."""
    return beta * to_dense(input) + alpha * (x @ y)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def mask_as(x, mask, name=None):
    """Sample dense x at mask's sparsity pattern (reference
    sparse.mask_as)."""
    xd = to_dense(x)
    m = coalesce(mask) if is_sparse(mask) else to_sparse_coo(mask)
    idx = m.indices
    vals = xd[tuple(idx[:, d] for d in range(idx.shape[1]))]
    return jsparse.BCOO((vals, m.indices), shape=m.shape)


# values-only elementwise surface (zero-preserving fns; reference unary.py)
sin = _unary(jnp.sin)
sinh = _unary(jnp.sinh)
asin = _unary(jnp.arcsin)
asinh = _unary(jnp.arcsinh)
tan = _unary(jnp.tan)
atan = _unary(jnp.arctan)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
isnan = _unary(jnp.isnan)
relu6 = _unary(jax.nn.relu6)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _unary(lambda v: jax.nn.leaky_relu(v, negative_slope))(x)


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if not is_sparse(x):
        return jnp.asarray(x, value_dtype)
    vals = x.data.astype(value_dtype) if value_dtype is not None else x.data
    idx = x.indices.astype(index_dtype) if index_dtype is not None \
        else x.indices
    return jsparse.BCOO((vals, idx), shape=x.shape)


from . import nn  # noqa: E402,F401  (sparse.nn layer shims)
