"""Two-process validation workload (`python -m
paddle_tpu.distributed.mp_smoke`).

The multi-host analogue of the driver's single-process dry run: spawned as 2
jax processes x N/2 virtual CPU devices each (reference pattern:
test/legacy_test/test_dist_base.py:1206 _run_cluster), it builds the hybrid
ICI/DCN mesh (dp across processes, mp intra-process), runs a few hybrid
dp x mp train steps, and prints the loss curve as JSON for the launcher to
compare against the identical single-process run.

`run_training(mesh, steps)` is imported by the parent for the golden run;
`spawn_and_check(n_devices)` is the launcher half used by
__graft_entry__.dryrun_multichip and by tests.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np

__all__ = ["run_training", "run_training_resilient", "run_training_fleet",
           "spawn_cluster", "spawn_and_check", "elastic_restart_check",
           "fleet_telemetry_check", "main", "ClusterUnsupported"]


class ClusterUnsupported(RuntimeError):
    """The platform cannot run the spawned multi-process cluster at all
    (as opposed to the workload failing): the jax build lacks
    cross-process CPU collectives, the coordinator cannot bind, etc.
    Tests catch this to SKIP with the reason instead of erroring."""


# worker-output signatures that mean "this platform can't do multiprocess
# jax", not "the workload is broken" — deliberately NARROW: rendezvous
# timeouts / hangs / init-path tracebacks stay hard failures (a deadlock
# regression must not report as "platform cannot spawn")
_UNSUPPORTED_MARKERS = (
    "Multiprocess computations aren't implemented",
    "multi-process computations are not supported",
)

# env that would leak the parent's jax/launcher identity into workers
_SCRUB_ENV = ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID", "JAX_COORDINATOR_ADDRESS",
              "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
              "PADDLE_TRAINER_ENDPOINTS", "PADDLE_CURRENT_ENDPOINT",
              "PADDLE_LOCAL_RANK", "PADDLE_VIRTUAL_DEVICES_PER_PROC")


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def spawn_cluster(argv, nproc: int, devices_per_proc: int,
                  sentinel: str, extra_env=None, timeout: float = 300.0,
                  ok_returncodes=(0,)):
    """Spawn `nproc` jax worker processes of `argv` (2-process rendezvous on
    a fresh port, `devices_per_proc` virtual CPU devices each), wait, and
    return the JSON payload following `sentinel` on each worker's stdout —
    the shared launcher half of the reference subprocess-spawn pattern
    (test_dist_base.py:1206 _run_cluster).

    ok_returncodes: exit codes that count as success — the elastic leg
    EXPECTS its workers to die with faults.FAULT_EXIT_CODE mid-run.
    Workers that died on purpose print no sentinel; their slot in the
    returned list is None."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    repo = _repo_root()
    procs, outs = [], []
    for pid in range(nproc):
        env = {k: v for k, v in os.environ.items() if k not in _SCRUB_ENV}
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PADDLE_VIRTUAL_DEVICES_PER_PROC": str(devices_per_proc),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(nproc),
            "JAX_PROCESS_ID": str(pid),
            "PADDLE_TRAINER_ID": str(pid),
            "PADDLE_TRAINERS_NUM": str(nproc),
            "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""),
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            argv, env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    timed_out = False
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                # kill and STILL collect the output: when one rank dies
                # with the unsupported marker mid-rendezvous, its peer
                # sometimes wedges in the dead rendezvous instead of
                # crashing — the marker (in the dead sibling's output)
                # is what distinguishes that from a real deadlock
                timed_out = True
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        for marker in _UNSUPPORTED_MARKERS:
            if marker in out:
                raise ClusterUnsupported(
                    f"platform cannot run the {nproc}-process cluster "
                    f"({marker!r}):\n{out[-1500:]}")
    if timed_out:
        # no unsupported marker anywhere: a genuine hang stays a HARD
        # failure (a deadlock regression must not report as a skip)
        raise RuntimeError(
            f"worker(s) exceeded the {timeout}s timeout with no "
            f"unsupported-platform marker:\n" +
            "\n".join(o[-1500:] for o in outs))
    for p, out in zip(procs, outs):
        if p.returncode not in ok_returncodes:
            raise RuntimeError(f"worker failed (rc={p.returncode}):\n"
                               f"{out[-4000:]}")
    results = []
    for out in outs:
        line = next((l for l in out.splitlines()
                     if l.startswith(sentinel)), None)
        results.append(None if line is None
                       else json.loads(line[len(sentinel):]))
    return results


def run_training(mesh, steps: int = 4, return_params: bool = False,
                 num_microbatches: int = 1, schedule: str = "1F1B",
                 zero1: bool = False, virtual_pp: int = 1,
                 moe: bool = False, zero_stage: int = 0):
    """Seed-deterministic tiny-GPT hybrid train loop over `mesh` (axes dp /
    pp / mp, plus ep for the MoE leg); every process computes identical
    host inputs. The ONE copy of the parity workload — the launcher
    golden, the spawned workers and the reference-pattern tests
    (tests/mp_worker.py) all import it, so they can never drift apart.

    moe=True runs the GPT-MoE config (switch FFN on alternating layers,
    experts sharded over the mesh's 'ep' axis, index dispatch) so the
    dispatch/combine all-to-alls cross whatever boundary the mesh puts
    the ep axis on."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as G

    if moe:
        cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, max_seq_len=16, dtype=jnp.float32,
                          moe_num_experts=4, moe_capacity_factor=4.0)
    else:
        # the interleaved schedule needs num_layers % (pp * vpp) == 0
        nl = 2 * max(int(virtual_pp), 1)
        cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=nl,
                          num_heads=4, max_seq_len=16, dtype=jnp.float32)
    params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    # zero1 mode also carries the axes-aware global-norm clip so the
    # cross-process parity covers the whole round-5 stage-1 path
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2,
        grad_clip=(paddle.nn.ClipGradByGlobalNorm(0.5)
                   if (zero1 or zero_stage) else None))
    kw = {}
    if moe:
        from .comm_overlap import MoeDispatchConfig
        kw["moe_dispatch"] = MoeDispatchConfig(index=True)
    stage = int(zero_stage) if zero_stage else (1 if zero1 else 0)
    step, shard_params, init_state = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=num_microbatches,
        schedule=schedule, zero_stage=stage, virtual_pp=virtual_pp, **kw)
    params = shard_params(params)
    state = init_state(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state, tokens, labels,
                                   jnp.float32(1e-2))
        losses.append(float(jax.device_get(loss)))
    return (losses, params) if return_params else losses


def run_training_resilient(mesh, steps: int, ckpt_dir: str):
    """The SAME seed-deterministic tiny-GPT workload as `run_training`,
    driven through the resilient runner with a per-step crash-safe commit
    and elastic layout metadata (FLAGS_ckpt_reshard) — the one copy of the
    elastic-restart workload shared by the 2-process workers, the
    single-process resume and the golden run. Returns (losses, info):
    losses keyed by global step (a resumed run only reports the steps it
    actually executed)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as G
    from .resilience import run_resilient

    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=16, dtype=jnp.float32)
    params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2)
    step, shard_params, init_state = G.build_hybrid_train_step(
        cfg, mesh, opt)
    params = shard_params(params)
    state = {"params": params, "opt": init_state(params)}
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))

    def step_fn(st, i):
        del i
        p, s, loss = step(st["params"], st["opt"], tokens, labels,
                          jnp.float32(1e-2))
        return {"params": p, "opt": s}, loss

    losses = {}
    _, info = run_resilient(step_fn, state, steps=steps, ckpt_dir=ckpt_dir,
                            ckpt_every=1,
                            layout_extra=init_state.layout_extra,
                            on_step=lambda i, l: losses.__setitem__(i, l))
    return losses, info


def run_training_fleet(mesh, steps: int, store, rank: int, world: int,
                       slow_ms: float = 0.0, interval: int = 4):
    """The seed-deterministic tiny-GPT workload driven through
    ``run_resilient(aggregator=)`` with a fleet TelemetryAggregator over
    the shared TCP store — the fleet-telemetry leg's one workload copy.
    ``slow_ms`` injects a per-step stall on THIS rank (the synthetic
    straggler the detector must flag). Returns run_resilient's info;
    rank 0's ``info["fleet"]`` carries the last aggregate report
    (per-host medians/p95, skew, stragglers)."""
    import tempfile
    import time as _time
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as G
    from paddle_tpu.observability import TelemetryAggregator
    from .resilience import run_resilient

    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=16, dtype=jnp.float32)
    params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2)
    step, shard_params, init_state = G.build_hybrid_train_step(
        cfg, mesh, opt)
    params = shard_params(params)
    state = {"params": params, "opt": init_state(params)}
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))

    def step_fn(st, i):
        del i
        if slow_ms > 0:
            _time.sleep(slow_ms / 1e3)  # the injected straggler stall
        p, s, loss = step(st["params"], st["opt"], tokens, labels,
                          jnp.float32(1e-2))
        return {"params": p, "opt": s}, loss

    agg = TelemetryAggregator(rank=rank, world_size=world, store=store,
                              host=rank, interval=interval, window=16,
                              straggler_factor=1.35, gather_timeout_s=60.0)
    ckpt_dir = tempfile.mkdtemp(prefix="fleet_ck_")
    try:
        _, info = run_resilient(step_fn, state, steps=steps,
                                ckpt_dir=ckpt_dir, ckpt_every=0,
                                resume=False, aggregator=agg)
    finally:
        import shutil
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return info


def fleet_telemetry_check(n_devices: int, timeout: float = 300.0,
                          steps: int = 8, slow_ms: float = 80.0) -> dict:
    """Fleet-telemetry leg: a 2-process dp2 x mp(n/2) cluster trains with
    a TelemetryAggregator over a real cross-process TCP store; rank 1 is
    artificially slowed by `slow_ms` per step, and rank 0's aggregate
    MUST flag exactly host 1 as the straggler (skew above the 1.35
    factor) with the straggler_detected event emitted. Returns a summary
    dict for the dryrun record."""
    from .. import _native
    if _native.load() is None:
        raise ClusterUnsupported(
            "fleet telemetry leg needs the native TCPStore (cross-process "
            "KV); the pure-Python fallback store is in-process only")
    assert n_devices % 2 == 0 and n_devices >= 4, n_devices
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    store_port = s.getsockname()[1]
    s.close()
    results = spawn_cluster(
        [sys.executable, "-m", "paddle_tpu.distributed.mp_smoke"],
        nproc=2, devices_per_proc=n_devices // 2, sentinel="MPSMOKE ",
        timeout=timeout,
        extra_env={"MPSMOKE_MODE": "fleet",
                   "MPSMOKE_STORE_PORT": str(store_port),
                   "MPSMOKE_STEPS": str(steps),
                   "MPSMOKE_SLOW_MS": str(slow_ms)})
    r0 = next((r for r in results if r and r.get("rank") == 0), None)
    assert r0 is not None and r0.get("fleet"), results
    rep = r0["fleet"]
    if rep["stragglers"] != [1]:
        raise AssertionError(
            f"straggler detector flagged {rep['stragglers']}, expected "
            f"[1] (rank 1 slowed by {slow_ms} ms/step); report: {rep}")
    if not rep["skew"] or rep["skew"] <= 1.35:
        raise AssertionError(f"skew {rep['skew']} not above the 1.35 "
                             f"straggler factor; report: {rep}")
    return {"stragglers": rep["stragglers"],
            "skew": round(rep["skew"], 2),
            "fleet_median_ms": round(rep["fleet_median_ms"], 2),
            "hosts": {h: round(st["median_ms"], 2)
                      for h, st in rep["hosts"].items()}}


def elastic_restart_check(n_devices: int, ckpt_dir: str, devices=None,
                          timeout: float = 300.0, steps: int = 6,
                          kill_at: int = 3) -> dict:
    """Elastic-restart leg: a 2-process dp2 x mp(n/2) cluster trains with
    per-step commits and is HARD-KILLED (fault injection, exit 41) before
    step `kill_at`+1; the launcher then resumes the run on a 1-process
    dp1 x mp(n/2) mesh — half the chips, the preemption-shrink shape —
    where the resilient driver detects the recorded mesh mismatch and
    reshards on load. The full trajectory must match an uninterrupted
    single-process mesh-B golden (same 5e-5 budget as the other
    cross-process parity legs). Returns a summary dict for the dryrun
    record."""
    import jax
    import paddle_tpu as paddle
    from .topology import build_mesh
    from .resilience import faults
    from .resilience.commit import checkpoint_step, latest_checkpoint

    assert n_devices % 2 == 0 and n_devices >= 4, n_devices
    devices = devices if devices is not None else jax.devices()
    mesh_b = build_mesh({"dp": 1, "pp": 1, "mp": n_devices // 2},
                        devices=devices[:n_devices // 2])
    golden_dir = ckpt_dir + ".golden"
    old = paddle.get_flags(["FLAGS_ckpt_reshard"])
    paddle.set_flags({"FLAGS_ckpt_reshard": True})
    try:
        golden, _ = run_training_resilient(mesh_b, steps, golden_dir)
        # phase 1: 2-process mesh A (dp across the process boundary),
        # killed by injection entering step kill_at (commits 1..kill_at)
        spawn_cluster(
            [sys.executable, "-m", "paddle_tpu.distributed.mp_smoke"],
            nproc=2, devices_per_proc=n_devices // 2, sentinel="MPSMOKE ",
            timeout=timeout,
            ok_returncodes=(faults.FAULT_EXIT_CODE,),
            extra_env={"MPSMOKE_MODE": "elastic",
                       "MPSMOKE_CKPT": ckpt_dir,
                       "FLAGS_ckpt_reshard": "1",
                       "FLAGS_fault_inject":
                           f"loop/before_step:{kill_at + 1}:kill"})
        ck = latest_checkpoint(ckpt_dir, gc=False)
        assert ck is not None and checkpoint_step(ck) == kill_at, ck
        # phase 2: resume on the shrunken 1-process mesh B
        resumed, info = run_training_resilient(mesh_b, steps, ckpt_dir)
        assert info["resumed_from"] == ck, info
        assert info.get("resharded") is True, info
        assert sorted(resumed) == list(range(kill_at, steps)), resumed
        for i, l in resumed.items():
            if abs(l - golden[i]) > 5e-5:
                raise AssertionError(
                    f"elastic resume loss diverged at step {i}: {l} vs "
                    f"golden {golden[i]}")
        return {"killed_at_step": kill_at, "resumed_steps": len(resumed),
                "resharded": True,
                "max_loss_diff": max(abs(resumed[i] - golden[i])
                                     for i in resumed)}
    finally:
        paddle.set_flags(old)
        import shutil
        shutil.rmtree(golden_dir, ignore_errors=True)


# "dpmp" is the hybrid
# dp-across-processes layout; the pp modes put the PIPELINE axis on the
# process boundary — each stage lives on its own process and the
# 1F1B/ZBH1/interleaved ppermute hops cross it, the reference's dominant
# multi-node integration (fleet/meta_parallel/pp_utils/
# p2p_communication.py:570 cross-node p2p). "ppvpp" is the interleaved
# virtual-pipeline schedule over the same boundary (each rank's V chunk
# wrap rides the circular permute across processes). "epmoe" puts the
# EXPERT-parallel axis on the process boundary: the GPT-MoE
# dispatch/combine all-to-alls (index dispatch) cross it every layer.
# "sepring" runs ring attention with the SEP axis spanning both
# processes — the ring's neighbor hops at the process edges are
# cross-process ppermutes (2 of n hops with the contiguous hybrid
# layout; the long-context DCN path at this box's fidelity).
# mode -> dict(dims builder, microbatches, schedule, zero1, vpp, moe)
_MODES = {
    "dpmp": dict(dims=lambda n: {"dp": 2, "pp": 1, "mp": n // 2}),
    # zero1 stage-1 over the dp axis that SPANS the two processes: the
    # grad reduce-scatter and param all-gather hops cross the boundary
    "z1dpmp": dict(dims=lambda n: {"dp": 2, "pp": 1, "mp": n // 2},
                   zero1=True),
    # zero3 over the dp axis that SPANS the two processes: the per-block
    # param all-gathers (and their reduce-scatter transposes) cross the
    # boundary every layer of every step
    "z3dpmp": dict(dims=lambda n: {"dp": 2, "pp": 1, "mp": n // 2},
                   zero_stage=3),
    "pp1f1b": dict(dims=lambda n: {"pp": 2, "dp": 1, "mp": n // 2}, m=4),
    "ppzbh1": dict(dims=lambda n: {"pp": 2, "dp": 1, "mp": n // 2}, m=4,
                   schedule="ZBH1"),
    "ppvpp": dict(dims=lambda n: {"pp": 2, "dp": 1, "mp": n // 2}, m=4,
                  vpp=2),
    "epmoe": dict(dims=lambda n: {"ep": 2, "dp": 1, "pp": 1,
                                  "mp": n // 2}, moe=True),
    "sepring": dict(dims=lambda n: {"sep": n}),
}


def _mode_training_kwargs(mode_cfg):
    return dict(num_microbatches=mode_cfg.get("m", 1),
                schedule=mode_cfg.get("schedule", "1F1B"),
                zero1=mode_cfg.get("zero1", False),
                zero_stage=mode_cfg.get("zero_stage", 0),
                virtual_pp=mode_cfg.get("vpp", 1),
                moe=mode_cfg.get("moe", False))


def run_ring(mesh, steps: int = 3):
    """Seed-deterministic ring-attention fwd+grad over the mesh's 'sep'
    axis (einsum tier — portable to the gloo CPU backend); returns a
    per-step scalar series every rank can compare against the
    single-process golden."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .fleet.meta_parallel import ring_attention
    from ..utils import shard_map

    B, S, H, D = 2, 8 * mesh.devices.size, 2, 8
    rng = np.random.RandomState(0)
    qkv = [jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
           for _ in range(3)]

    def loss(q, k, v):
        out = ring_attention(q, k, v, axis="sep", causal=True,
                             impl="einsum")
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), "sep")

    spec = P(None, "sep")
    f = shard_map(loss, mesh=mesh, in_specs=(spec,) * 3, out_specs=P())
    gfn = jax.jit(jax.grad(f))  # descend on q only
    vals = []
    q, k, v = qkv
    for _ in range(steps):
        q = q - 0.05 * gfn(q, k, v)
        vals.append(float(jax.device_get(f(q, k, v))))
    return vals


def main():
    from . import env as dist_env
    from .topology import build_mesh

    dist_env.init_parallel_env()
    import jax

    mode = os.environ.get("MPSMOKE_MODE", "dpmp")
    n = len(jax.devices())
    if mode == "fleet":
        # fleet-telemetry worker: dp spans the two processes; every rank
        # publishes its step-time window + prom snapshot through the
        # launcher's TCP store, rank 0 aggregates and must flag the
        # slowed rank as a straggler
        from .store import MasterStore
        rank = jax.process_index()
        mesh = build_mesh({"dp": 2, "pp": 1, "mp": n // 2})
        store = MasterStore(
            f"127.0.0.1:{os.environ['MPSMOKE_STORE_PORT']}", 2, rank,
            timeout=60.0)  # bounded: a dead sibling must not wedge us
        #                    past the launcher's spawn timeout
        slow = (float(os.environ.get("MPSMOKE_SLOW_MS", "0"))
                if rank == 1 else 0.0)
        info = run_training_fleet(
            mesh, steps=int(os.environ.get("MPSMOKE_STEPS", "8")),
            store=store, rank=rank, world=2, slow_ms=slow)
        print("MPSMOKE " + json.dumps(
            {"rank": rank, "mode": mode, "fleet": info.get("fleet")}),
            flush=True)
        # rank 0 hosts the store server: linger briefly so a slower peer
        # can finish its last publish before the server dies with us
        if rank == 0:
            import time as _time
            _time.sleep(1.0)
        return
    if mode == "elastic":
        # elastic-restart worker: dp spans the two processes, per-step
        # crash-safe commits with layout metadata; the launcher arms
        # loop/before_step:N:kill so both ranks die mid-run and later
        # resumes the checkpoint on a 1-process mesh
        mesh = build_mesh({"dp": 2, "pp": 1, "mp": n // 2})
        losses, info = run_training_resilient(
            mesh, steps=int(os.environ.get("MPSMOKE_STEPS", "6")),
            ckpt_dir=os.environ["MPSMOKE_CKPT"])
        print("MPSMOKE " + json.dumps(
            {"rank": jax.process_index(), "mode": mode,
             "losses": {str(k): v for k, v in losses.items()},
             "resumed_from": info["resumed_from"]}), flush=True)
        return
    mode_cfg = _MODES[mode]
    mesh = build_mesh(mode_cfg["dims"](n))
    if mode == "sepring":
        # the sep ring must CROSS the process boundary somewhere: count
        # neighbor pairs (incl. the wraparound) on different processes
        procs = [d.process_index for d in mesh.devices]
        crossings = sum(procs[i] != procs[(i + 1) % len(procs)]
                        for i in range(len(procs)))
        assert crossings >= 2, procs  # contiguous layout: 2 of n hops
        vals = run_ring(mesh)
        print("MPSMOKE " + json.dumps(
            {"rank": jax.process_index(), "mode": mode, "losses": vals}),
            flush=True)
        return
    ax = dict(zip(mesh.axis_names, range(len(mesh.axis_names))))
    if mode == "epmoe":
        # ep across the PROCESS boundary (the dispatch/combine
        # all-to-alls cross it), mp intra-process
        dev = np.moveaxis(mesh.devices, (ax["ep"], ax["mp"]), (0, -1))
        dev = dev.reshape(2, -1)
        for e in range(2):
            assert len({d.process_index for d in dev[e]}) == 1, mode
        assert dev[0, 0].process_index != dev[1, 0].process_index, mode
    else:
        dev = np.moveaxis(mesh.devices,
                          (ax["dp"], ax["pp"], ax["mp"]), (0, 1, 2))
        if mode in ("dpmp", "z1dpmp"):
            # hybrid-layout invariant: mp intra-process, dp across
            # processes
            assert len({d.process_index for d in dev[0, 0, :]}) == 1
            assert dev[0, 0, 0].process_index != dev[1, 0, 0].process_index
        else:
            # pp across the PROCESS boundary: each stage entirely on one
            # process, stages on different processes
            for s in range(2):
                assert len({d.process_index for d in dev[0, s, :]}) == 1, \
                    mode
            assert dev[0, 0, 0].process_index != dev[0, 1, 0].process_index
    losses = run_training(mesh, **_mode_training_kwargs(mode_cfg))
    print("MPSMOKE " + json.dumps(
        {"rank": jax.process_index(), "mode": mode, "losses": losses}),
        flush=True)


def spawn_and_check(n_devices: int, golden, timeout: float = 300.0,
                    mode: str = "dpmp") -> None:
    """Spawn the 2-process cluster (n_devices/2 virtual CPU devices per
    process) and assert its loss curve matches `golden` (the single-process
    run of `run_training` on the same mesh shape)."""
    assert n_devices % 2 == 0 and n_devices >= 4, n_devices
    results = spawn_cluster(
        [sys.executable, "-m", "paddle_tpu.distributed.mp_smoke"],
        nproc=2, devices_per_proc=n_devices // 2, sentinel="MPSMOKE ",
        timeout=timeout, extra_env={"MPSMOKE_MODE": mode})
    for res in results:
        if not np.allclose(res["losses"], golden, rtol=0, atol=5e-5):
            raise AssertionError(
                f"2-process ({mode}) loss curve {res['losses']} != "
                f"single-process {golden}")


def golden_for(n_devices: int, mode: str = "dpmp", devices=None):
    """Single-process golden loss curve for a spawn mode (same mesh dims,
    same schedule, one process)."""
    from .topology import build_mesh
    mode_cfg = _MODES[mode]
    mesh = build_mesh(mode_cfg["dims"](n_devices), devices=devices)
    if mode == "sepring":
        return run_ring(mesh)
    return run_training(mesh, **_mode_training_kwargs(mode_cfg))


if __name__ == "__main__":
    main()
