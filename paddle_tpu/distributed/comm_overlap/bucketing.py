"""Size-targeted gradient buckets for overlapped dp collectives.

Reference: the eager DataParallel reducer's grad bucketing
(fleet/meta_parallel — EagerReducer groups grads into comm buffers of
``comm_buffer_size_MB`` and all-reduces each buffer as soon as its grads
are ready). T3 (arXiv:2401.16677) shows the same fine-grained
decomposition is what lets a compiler overlap collectives with backward
compute.

TPU design: a :class:`BucketPlan` is pure shape metadata, computed once at
trace time (works on tracers — only ``shape``/``dtype`` are read). Leaves
are ordered in REVERSE pytree-flatten order by default: parameter trees
flatten roughly in forward order, so the reverse approximates the order in
which backward finishes each gradient — the first bucket issued is the one
whose grads complete first, maximizing the window the latency-hiding
scheduler has to overlap its collective with remaining backward compute.

Each bucket is reduced as ONE fused flat buffer (pack → collective →
unpack), so small leaves (biases, norms) never pay per-tensor collective
latency.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LeafSlot", "Bucket", "BucketPlan", "build_bucket_plan",
           "pack_bucket", "unpack_bucket", "local_shape"]


@dataclasses.dataclass(frozen=True)
class LeafSlot:
    """One gradient leaf's position inside a bucket's flat buffer."""
    leaf_index: int          # position in the tree's flat leaf list
    shape: Tuple[int, ...]
    dtype: Any
    offset: int              # element offset inside the bucket flat buffer

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


@dataclasses.dataclass(frozen=True)
class Bucket:
    index: int
    slots: Tuple[LeafSlot, ...]

    @property
    def size(self) -> int:
        return sum(s.size for s in self.slots)

    @property
    def nbytes(self) -> int:
        return sum(s.size * jnp.dtype(s.dtype).itemsize for s in self.slots)


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    buckets: Tuple[Bucket, ...]
    n_leaves: int            # total leaves in the source tree (incl. None)

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def local_sizes(self) -> List[int]:
        return [b.size for b in self.buckets]


def build_bucket_plan(leaves: Sequence[Any], bucket_bytes: float,
                      reverse: bool = True) -> BucketPlan:
    """Partition `leaves` (arrays / ShapeDtypeStructs / tracers; None
    entries are skipped — frozen params produce None grads) into buckets
    of at least `bucket_bytes` each (greedy fill in completion order).
    bucket_bytes <= 0 fuses everything into one bucket."""
    order = range(len(leaves) - 1, -1, -1) if reverse else range(len(leaves))
    buckets: List[Bucket] = []
    cur: List[LeafSlot] = []
    cur_bytes = 0
    cur_off = 0

    def close():
        nonlocal cur, cur_bytes, cur_off
        if cur:
            buckets.append(Bucket(index=len(buckets), slots=tuple(cur)))
            cur, cur_bytes, cur_off = [], 0, 0

    for i in order:
        leaf = leaves[i]
        if leaf is None:
            continue
        shape = tuple(int(d) for d in leaf.shape)
        dtype = leaf.dtype
        slot = LeafSlot(leaf_index=i, shape=shape, dtype=dtype,
                        offset=cur_off)
        cur.append(slot)
        cur_off += slot.size
        cur_bytes += slot.size * jnp.dtype(dtype).itemsize
        if bucket_bytes > 0 and cur_bytes >= bucket_bytes:
            close()
    close()
    return BucketPlan(buckets=tuple(buckets), n_leaves=len(leaves))


def pack_bucket(leaves: Sequence[Any], bucket: Bucket,
                dtype=None) -> jax.Array:
    """Concatenate the bucket's leaves into one flat 1-D buffer. `dtype`
    None picks the highest-precision leaf dtype in the bucket (so a mixed
    bf16/fp32 bucket reduces in fp32 rather than truncating)."""
    if dtype is None:
        dtype = jnp.result_type(*[leaves[s.leaf_index].dtype
                                  for s in bucket.slots])
    parts = [jnp.ravel(leaves[s.leaf_index]).astype(dtype)
             for s in bucket.slots]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unpack_bucket(flat: jax.Array, bucket: Bucket,
                  cast_back: bool = True) -> List[Tuple[int, jax.Array]]:
    """Split a bucket's flat buffer back into (leaf_index, leaf) pairs in
    the bucket's slot layout (inverse of pack_bucket)."""
    out = []
    for s in bucket.slots:
        piece = jax.lax.dynamic_slice_in_dim(flat, s.offset, s.size, 0)
        piece = piece.reshape(s.shape)
        if cast_back:
            piece = piece.astype(s.dtype)
        out.append((s.leaf_index, piece))
    return out


def local_shape(shape: Sequence[int], spec, mesh) -> Tuple[int, ...]:
    """Per-device shard shape of a GLOBAL leaf under a PartitionSpec (what
    the leaf looks like INSIDE shard_map) — used to size bucket plans and
    error-feedback residuals before any tracing happens."""
    out = list(int(d) for d in shape)
    for d, entry in enumerate(tuple(spec)[:len(out)]):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for a in axes:
            out[d] //= mesh.shape[a]
    return tuple(out)
