"""Spawned worker half of the resilience tests (launcher half in
tests/test_resilience.py). Runs a tiny deterministic training loop under
run_resilient and reports losses/state as a RESULT json line, so the parent
can kill it (SIGTERM or an armed ``:kill`` fault site), respawn it, and
assert the resumed run matches the uninterrupted golden bitwise.

Usage: python resilience_worker.py <mode> <ckpt_dir> [steps]
  mode 'train': pure-jnp SGD steps; crash points come from
                FLAGS_fault_inject in the environment.
  mode 'slow':  python-side steps with a sleep — a SIGTERM target whose
                step cadence is fast enough to preempt mid-run.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def train_step(state, i):
    """Deterministic SGD on sum((w - x_i)^2); x_i derived from the step
    index, so losses are a pure function of (initial state, step)."""
    import jax
    import jax.numpy as jnp
    x = jax.random.normal(jax.random.PRNGKey(i), (4,), dtype=jnp.float32)
    w = state["w"]
    loss = jnp.sum((w - x) ** 2)
    return {"w": w - 0.1 * 2.0 * (w - x)}, loss


def initial_state():
    import jax.numpy as jnp
    return {"w": jnp.zeros((4,), jnp.float32)}


def main():
    mode, ckpt_dir = sys.argv[1], sys.argv[2]
    steps = int(sys.argv[3]) if len(sys.argv) > 3 else 9
    from paddle_tpu.distributed.resilience import run_resilient

    losses = {}

    def on_step(i, loss):
        losses[i] = loss

    if mode == "train":
        state, info = run_resilient(
            train_step, initial_state(), steps=steps, ckpt_dir=ckpt_dir,
            ckpt_every=3, on_step=on_step)
        result = {"losses": losses, "w": np.asarray(state["w"]).tolist(),
                  "completed": info["completed_steps"],
                  "resumed_from": info["resumed_from"]}
    elif mode == "slow":
        import time

        def slow_step(state, i):
            time.sleep(0.05)
            w = state["w"] * np.float32(0.999)
            return {"w": w}, float(w.sum())

        print("READY", flush=True)
        state, info = run_resilient(
            slow_step, {"w": np.ones((4,), np.float32)}, steps=steps,
            ckpt_dir=ckpt_dir, ckpt_every=0, grace_s=15.0, on_step=on_step)
        result = {"preempted": info["preempted"],
                  "completed": info["completed_steps"],
                  "final": info["final_checkpoint"],
                  "grace_used_s": info.get("grace_used_s")}
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    print("RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
