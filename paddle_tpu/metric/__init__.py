"""Metrics (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Optional pre-processing hook run on device outputs before update."""
        return pred, label


def accuracy(input, label, k=1):
    """Functional top-k accuracy (reference: python/paddle/metric/metrics.py
    accuracy)."""
    import jax.numpy as jnp
    from jax import lax
    _, topk_idx = lax.top_k(input, k)
    label = jnp.asarray(label)
    if label.ndim == input.ndim:
        label = label.squeeze(-1)
    correct = (topk_idx == label[..., None]).any(axis=-1)
    return correct.astype(jnp.float32).mean()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = np.asarray(pred)
        label = np.asarray(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        return (idx == label[..., None]).astype(np.float32)

    def update(self, correct):
        correct = np.asarray(correct)
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(-1).sum()
            self.count[i] += correct.shape[0] if correct.ndim > 1 else 1
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return float(accs[0]) if len(self.topk) == 1 else [float(a) for a in accs]

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]
        labels = np.asarray(labels).reshape(-1).astype(bool)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        nbins = self.num_thresholds + 1
        self._stat_pos += np.bincount(idx[labels], minlength=nbins)
        self._stat_neg += np.bincount(idx[~labels], minlength=nbins)

    def accumulate(self):
        tot_pos = tot_neg = auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * self._stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)
