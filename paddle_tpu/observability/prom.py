"""Prometheus text-format scrape surface (serving + fleet telemetry).

A dependency-free subset of the Prometheus client: counters, gauges,
summaries (sum+count pairs) and bucketed histograms rendered in text
exposition format 0.0.4, plus a tiny threaded HTTP server exposing
``/metrics``. The serving engine keeps a :class:`PromRegistry` per
process and updates it inside ``ServingEngine.step``; the fleet
:class:`~paddle_tpu.observability.aggregate.TelemetryAggregator` gathers
per-process ``snapshot()``s into rank-0 gauges; ops point a scraper (or
curl) at the port.

Observation metrics (summaries and histograms) additionally keep a
bounded *recent window* of raw observations so callers can read live
quantiles (``quantile(name, 0.95)``) instead of the lifetime mean — a
summary's mean never decays, so one slow startup wave would otherwise
bias adaptive control (the serving TTFT/SLO mix) forever.

No pull-time device work: every metric is a host float updated on the
engine's own schedule, so a scrape can never add a TPU dispatch.
"""

from __future__ import annotations

import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["PromRegistry", "MetricsServer", "serve_registry",
           "DEFAULT_BUCKETS", "nearest_rank"]


def nearest_rank(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank quantile of a non-empty SORTED sequence (q in
    [0, 1]): the ceil(q*N)-th order statistic — the median of 2 values
    is the LOWER one. The ONE copy shared by the registry's window
    quantiles and the fleet straggler detector (aggregate.percentile),
    so adaptive serving control and straggler verdicts can never compute
    different quantiles for the same q."""
    import math
    q = min(max(float(q), 0.0), 1.0)
    idx = min(max(math.ceil(q * len(sorted_vals)) - 1, 0),
              len(sorted_vals) - 1)
    return sorted_vals[idx]

_TYPES = ("counter", "gauge", "summary", "histogram")

# latency-ish default buckets (seconds or ms — caller's unit), upper
# bounds of the cumulative le="" series; +Inf is implicit
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0)


class _Metric:
    __slots__ = ("name", "mtype", "help", "value", "sum", "count",
                 "buckets", "bucket_counts", "window")

    def __init__(self, name: str, mtype: str, help_: str,
                 buckets: Optional[Sequence[float]] = None,
                 window: int = 64):
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.value = 0.0   # counter/gauge
        self.sum = 0.0     # summary/histogram
        self.count = 0
        self.buckets: Tuple[float, ...] = ()
        self.bucket_counts: list = []
        if mtype == "histogram":
            self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
            self.bucket_counts = [0] * len(self.buckets)
        # recent raw observations (summary/histogram) for live quantiles
        self.window: Optional[deque] = (
            deque(maxlen=max(int(window), 1))
            if mtype in ("summary", "histogram") else None)


class PromRegistry:
    def __init__(self, namespace: str = "paddle_tpu", window: int = 64):
        self.namespace = namespace
        self.window = max(int(window), 1)
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, mtype: str, help_: str,
             buckets: Optional[Sequence[float]] = None,
             window: Optional[int] = None) -> _Metric:
        assert mtype in _TYPES, mtype
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = _Metric(
                    name, mtype, help_, buckets=buckets,
                    window=window if window is not None else self.window)
            elif m.mtype != mtype:
                raise ValueError(f"metric {name} is a {m.mtype}, "
                                 f"not {mtype}")
            return m

    # -- update surface ------------------------------------------------------
    def counter_inc(self, name: str, amount: float = 1.0, help: str = ""):
        m = self._get(name, "counter", help)
        with self._lock:
            m.value += amount

    def gauge_set(self, name: str, value: float, help: str = ""):
        m = self._get(name, "gauge", help)
        with self._lock:
            m.value = float(value)

    def gauge_max(self, name: str, value: float, help: str = ""):
        """Set-if-greater — peak gauges (e.g. peak pool utilization)."""
        m = self._get(name, "gauge", help)
        with self._lock:
            m.value = max(m.value, float(value))

    def summary_observe(self, name: str, value: float, help: str = "",
                        window: Optional[int] = None):
        m = self._get(name, "summary", help, window=window)
        with self._lock:
            m.sum += float(value)
            m.count += 1
            m.window.append(float(value))

    def histogram_observe(self, name: str, value: float, help: str = "",
                          buckets: Optional[Sequence[float]] = None,
                          window: Optional[int] = None):
        """Bucketed histogram observation (cumulative le="" series in the
        exposition). `buckets` fixes the upper bounds at first touch;
        later calls reuse the metric's buckets."""
        m = self._get(name, "histogram", help, buckets=buckets,
                      window=window)
        v = float(value)
        with self._lock:
            m.sum += v
            m.count += 1
            m.window.append(v)
            for i, ub in enumerate(m.buckets):
                if v <= ub:  # per-bucket count; render() cumulates
                    m.bucket_counts[i] += 1
                    break

    def _metric(self, name: str) -> Optional[_Metric]:
        prefix = f"{self.namespace}_"
        if self.namespace and name.startswith(prefix):
            name = name[len(prefix):]
        return self._metrics.get(name)

    def get(self, name: str) -> Optional[float]:
        """Current value (summaries/histograms: mean of observations);
        None if the metric was never touched. Accepts the bare or
        namespaced name."""
        m = self._metric(name)
        if m is None:
            return None
        if m.mtype in ("summary", "histogram"):
            return m.sum / m.count if m.count else None
        return m.value

    def quantile(self, name: str, q: float) -> Optional[float]:
        """Recent-window quantile of a summary/histogram (q in [0, 1],
        nearest-rank over the last `window` raw observations). None when
        the metric does not exist, has no observations yet, or is not an
        observation type. This is the live-control read — the serving
        adaptive mix and the fleet router want p95-of-recent, not the
        lifetime mean the summary exposes."""
        m = self._metric(name)
        if m is None or m.window is None or not m.window:
            return None
        with self._lock:
            vals = sorted(m.window)
        return nearest_rank(vals, q)

    def snapshot(self) -> Dict[str, float]:
        """Flat {name: value} view for cross-process aggregation (the
        fleet TelemetryAggregator ships this through the distributed
        store). Counters/gauges export their value; observation metrics
        export `<name>_count`, `<name>_mean` and recent-window
        `<name>_p50`/`<name>_p95`."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, float] = {}
        for m in metrics:
            if m.mtype in ("counter", "gauge"):
                out[m.name] = m.value
                continue
            out[m.name + "_count"] = float(m.count)
            if m.count:
                out[m.name + "_mean"] = m.sum / m.count
            for q, tag in ((0.5, "_p50"), (0.95, "_p95")):
                v = self.quantile(m.name, q)
                if v is not None:
                    out[m.name + tag] = v
        return out

    # -- exposition ----------------------------------------------------------
    def render(self) -> str:
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        ns = self.namespace
        for m in metrics:
            full = f"{ns}_{m.name}" if ns else m.name
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            lines.append(f"# TYPE {full} {m.mtype}")
            if m.mtype == "summary":
                lines.append(f"{full}_sum {_fmt(m.sum)}")
                lines.append(f"{full}_count {m.count}")
            elif m.mtype == "histogram":
                cum = 0
                for ub, c in zip(m.buckets, m.bucket_counts):
                    cum += c
                    lines.append(f'{full}_bucket{{le="{_fmt(ub)}"}} {cum}')
                lines.append(f'{full}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{full}_sum {_fmt(m.sum)}")
                lines.append(f"{full}_count {m.count}")
            else:
                lines.append(f"{full} {_fmt(m.value)}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class MetricsServer:
    """Threaded /metrics endpoint over a registry (or any render()-able).
    port=0 binds an ephemeral port; read it back from ``.port``.

    health_fn: optional zero-arg callable returning a readiness state
    string (the serving engine's ``loading/ready/draining/degraded``) —
    when set, the server also answers ``/healthz`` with a JSON body
    ``{"state": ...}``: HTTP 200 iff the state is ``ready``, 503
    otherwise, so a fleet router/load-balancer can gate traffic on it
    without parsing metrics."""

    def __init__(self, registry: PromRegistry, port: int = 0,
                 host: str = "127.0.0.1", health_fn=None):
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if health_fn is not None and \
                        self.path.rstrip("/") == "/healthz":
                    try:
                        state = str(health_fn())
                    except Exception:  # readiness must never 500 opaquely
                        state = "degraded"
                    body = ('{"state": "%s"}' % state).encode("utf-8")
                    self.send_response(200 if state == "ready" else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = reg.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                del a

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="paddle-tpu-metrics",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def serve_registry(registry: PromRegistry,
                   port: Optional[int] = None,
                   health_fn=None) -> Optional[MetricsServer]:
    """Start a scrape endpoint; port None reads
    FLAGS_telemetry_prometheus_port (0 = disabled -> None). health_fn
    adds the /healthz readiness route (see MetricsServer)."""
    if port is None:
        from ..flags import flag
        port = int(flag("telemetry_prometheus_port"))
        if port <= 0:
            return None
    return MetricsServer(registry, port=max(port, 0), health_fn=health_fn)
