"""DistModel / dist.to_static tests (reference analog:
test/auto_parallel/hybrid_strategy/ semi-auto to_static runs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed import Shard, Replicate
from paddle_tpu.distributed.auto_parallel import to_static
from paddle_tpu.distributed.auto_parallel.process_mesh import ProcessMesh


@pytest.fixture
def mesh2d():
    return ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])


def test_dist_model_trains_with_sharded_params(mesh2d):
    layer = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    # Megatron-ish: first weight column-sharded over 'y', second row-sharded
    dist.shard_tensor(layer[0].weight, mesh2d, [Replicate(), Shard(1)])
    dist.shard_tensor(layer[2].weight, mesh2d, [Replicate(), Shard(0)])

    opt = paddle.optimizer.AdamW(1e-2)
    model = to_static(layer, loss=nn.functional.cross_entropy,
                      optimizer=opt, mesh=mesh2d)
    X = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.int64)
    losses = [float(model(X, y)) for _ in range(15)]
    assert losses[-1] < losses[0], losses

    # params kept their shard_tensor shardings through the steps
    sd = model.state_dict()
    w0 = next(v for k, v in sd.items() if k.endswith("0.weight"))
    w2 = next(v for k, v in sd.items() if k.endswith("2.weight"))
    from jax.sharding import PartitionSpec as JP
    assert w0.sharding.spec == JP(None, "y"), w0.sharding
    assert w2.sharding.spec == JP("y"), w2.sharding

    # eval mode returns outputs
    model.eval()
    out = model(X)
    assert out.shape == (32, 4)


def test_dist_model_uses_global_hcg_when_no_mesh():
    from paddle_tpu.distributed import fleet
    fleet.init(is_collective=True)
    layer = nn.Linear(8, 2)
    model = to_static(layer, loss=nn.functional.cross_entropy,
                      optimizer=paddle.optimizer.SGD(0.1))
    X = np.random.RandomState(1).randn(16, 8).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.int64)
    l0 = float(model(X, y))
    for _ in range(10):
        l = float(model(X, y))
    assert l < l0


def test_dist_model_frozen_params_and_buffers(mesh2d):
    """stop_gradient params must not receive updates; BatchNorm running
    stats must thread through train steps."""
    layer = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8), nn.ReLU(),
                          nn.Linear(8, 2))
    layer[0].weight.trainable = False
    layer[0].weight.stop_gradient = True
    model = to_static(layer, loss=nn.functional.cross_entropy,
                      optimizer=paddle.optimizer.SGD(0.1), mesh=mesh2d)
    frozen_before = np.asarray(next(
        v for k, v in model.state_dict().items() if k.endswith("0.weight")))
    X = np.random.RandomState(3).randn(16, 8).astype(np.float32) + 2.0
    y = (X.sum(1) > 16).astype(np.int64)
    for _ in range(3):
        model(X, y)
    sd = model.state_dict()
    frozen_after = np.asarray(next(
        v for k, v in sd.items() if k.endswith("0.weight")))
    np.testing.assert_array_equal(frozen_before, frozen_after)
    # BN running mean moved toward the (shifted) input statistics
    rm = next((v for k, v in sd.items() if "_mean" in k), None)
    assert rm is not None, list(sd)
    assert float(np.abs(np.asarray(rm)).sum()) > 0, "BN stats never updated"


def test_dist_model_state_roundtrip(mesh2d):
    layer = nn.Linear(8, 4)
    model = to_static(layer, loss=nn.functional.cross_entropy,
                      optimizer=paddle.optimizer.SGD(0.1), mesh=mesh2d)
    X = np.random.RandomState(2).randn(8, 8).astype(np.float32)
    y = np.zeros(8, np.int64)
    model(X, y)
    sd = model.state_dict()
    model2 = to_static(nn.Linear(8, 4), loss=nn.functional.cross_entropy,
                       optimizer=paddle.optimizer.SGD(0.1), mesh=mesh2d)
    model2.set_state_dict(sd)
    model.eval(); model2.eval()
    np.testing.assert_allclose(np.asarray(model(X)), np.asarray(model2(X)),
                               rtol=1e-6)