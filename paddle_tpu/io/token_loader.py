"""Native token-stream loader for LM pretraining (reference: the C++ data
feed under paddle/fluid/framework + python/paddle/io DataLoader workers —
here the reader thread is C++ (csrc/native_runtime.cpp TokenReader) filling
a C++ ring buffer off-GIL; Python only wraps batches into arrays).

File format: a flat binary stream of little-endian int32 token ids. Each
batch is [batch_size, seq_len+1] consecutive windows; tokens = [:, :-1],
labels = [:, 1:] (next-token prediction).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Iterator, Optional, Tuple

import numpy as np
from ..enforce import InvalidTypeError

from .. import _native

__all__ = ["TokenFileLoader"]


class TokenFileLoader:
    """Iterate (tokens, labels) int32 batches from a flat token file.

    Uses the native C++ reader+ring when available; otherwise a Python
    thread with numpy memmap (same semantics, GIL-bound)."""

    def __init__(self, path: str, batch_size: int, seq_len: int,
                 epochs: int = 1, stride: Optional[int] = None,
                 buffer_batches: int = 8):
        import os
        if not os.path.exists(path):
            # the native reader cannot raise across the ABI — fail here so a
            # mistyped path errors identically with and without the toolchain
            raise FileNotFoundError(path)
        self.path = path
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.epochs = epochs
        self.stride = seq_len if stride is None else stride
        self.buffer_batches = buffer_batches
        self._lib = _native.load()

    # -- native path ---------------------------------------------------------
    def _iter_native(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        lib = self._lib
        rb = lib.ptn_rb_create(self.buffer_batches)
        reader = lib.ptn_reader_start(
            self.path.encode(), self.batch_size, self.seq_len, self.epochs,
            self.stride, rb)
        window = self.seq_len + 1
        try:
            while True:
                out_len = ctypes.c_uint64()
                ptr = lib.ptn_rb_pop(rb, ctypes.byref(out_len), -1)
                if not ptr:
                    break  # reader finished and ring drained
                raw = _native.take_bytes(lib, ptr, out_len.value)
                # bytearray keeps the array writable, matching the fallback
                arr = np.frombuffer(bytearray(raw), dtype=np.int32).reshape(
                    self.batch_size, window)
                yield arr[:, :-1], arr[:, 1:]
        finally:
            lib.ptn_reader_stop(reader)
            lib.ptn_rb_destroy(rb)

    # -- fallback path -------------------------------------------------------
    def _iter_python(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        data = np.memmap(self.path, dtype=np.int32, mode="r")
        n = data.shape[0]
        window = self.seq_len + 1
        import itertools
        import queue
        q: "queue.Queue" = queue.Queue(self.buffer_batches)
        DONE = object()
        stop = threading.Event()

        # epochs < 0 = infinite, matching the native reader's contract
        epoch_iter = (itertools.count() if self.epochs < 0
                      else range(self.epochs))

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            for _ in epoch_iter:
                pos = 0
                while not stop.is_set():
                    rows = []
                    ok = True
                    for b in range(self.batch_size):
                        off = pos + b * self.stride
                        if off + window > n:
                            ok = False
                            break
                        rows.append(np.asarray(data[off:off + window]))
                    if not ok:
                        break
                    if not put(np.stack(rows)):
                        return
                    pos += self.batch_size * self.stride
                if stop.is_set():
                    return
            put(DONE)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    break
                yield item[:, :-1], item[:, 1:]
        finally:
            stop.set()  # abandoning the iterator must not strand the thread

    def __iter__(self):
        if self._lib is not None:
            return self._iter_native()
        return self._iter_python()

    def __len__(self):
        if self.epochs < 0:
            raise InvalidTypeError("TokenFileLoader with epochs<0 is an infinite "
                            "stream and has no length")
        data_len = np.memmap(self.path, dtype=np.int32, mode="r").shape[0]
        window = self.seq_len + 1
        per_step = self.batch_size * self.stride
        steps = 0
        pos = 0
        while pos + (self.batch_size - 1) * self.stride + window <= data_len:
            steps += 1
            pos += per_step
        return steps * self.epochs
