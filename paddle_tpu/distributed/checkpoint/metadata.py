"""Checkpoint metadata types (reference:
python/paddle/distributed/checkpoint/metadata.py:20-41 —
LocalTensorMetadata / LocalTensorIndex / Metadata).

A distributed checkpoint is a set of data files (one per writing process)
plus one metadata file describing, for every tensor key, which global-offset
chunks exist and which file holds each chunk. Loading reshards by computing
chunk↔target-shard overlaps, so the saving and loading parallelism configs
are independent.

Schema versions:

* **v1** — chunk index only (state_dict_metadata / storage_metadata /
  flat_mapping / misc). Enough to reshard-on-load when the TARGET
  template fully describes the destination layout.
* **v2** — v1 plus a ``SavedLayout``: the SOURCE topology (mesh axis
  sizes, per-leaf partition specs, global logical shapes, replication
  factors, zero1 shard dims) and caller-supplied ``extra`` hints (pp/vpp
  block layout, comm_ef bucket-plan fingerprint, carry policies). This
  is what lets an elastic restart DETECT a mesh change and remap the
  non-parameter carries instead of failing (``checkpoint.reshard``).

Compat discipline: v2 fields are dataclass attributes WITH class-level
defaults, and the writer drops them from the instance ``__dict__`` when no
layout is recorded — so flags-off pickles are byte-identical to the v1
format, and v1 pickles load into this class with attribute access falling
back to the class defaults (``schema_version == 1``, ``layout is None``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

SCHEMA_VERSION = 2  # written when a layout is recorded; plain saves stay v1


@dataclass(frozen=True)
class LocalTensorMetadata:
    """Shape/offset/dtype of one saved chunk of a global tensor."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Identity of a chunk: (tensor key, global offset). Hashable — used as
    the storage-map key."""

    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class SavedLayout:
    """Source-topology description recorded alongside the chunk index
    (schema v2). Everything here is derived from the arrays' shardings at
    save time except ``extra``, which carries model-level hints the arrays
    cannot express (pp/vpp stacked-block layout, comm_ef bucket plan,
    carry remap policies — see ``models.hybrid_engine`` and
    ``checkpoint.reshard``)."""

    # mesh axis name -> size (dp/pp/mp/... of the SAVING job)
    mesh: Dict[str, int] = field(default_factory=dict)
    # flat key -> partition-spec entries as plain tuples (axis name, None,
    # or a tuple of axis names per dim) — picklable, no jax objects
    specs: Dict[str, Tuple] = field(default_factory=dict)
    # flat key -> global logical shape
    global_shapes: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    # flat key -> how many ranks held a full copy of this leaf
    replication: Dict[str, int] = field(default_factory=dict)
    # number of saving processes (files 0_0.distcp .. n-1_0.distcp)
    process_count: int = 1
    # caller hints: {"pp": {...}, "comm_plan": {...}, "carries": {...},
    # "zero1": bool, "zero_stage": int (ZeRO stage 0-3 of the saving
    # build; stage-3 checkpoints hold dp-sharded params whose chunks
    # reassemble onto any stage), ...}
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Metadata:
    # tensor key -> every chunk that exists for it (across all files)
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(
        default_factory=dict)
    # chunk identity -> data file that holds it
    storage_metadata: Dict[LocalTensorIndex, str] = field(default_factory=dict)
    # flattened key -> original nested key-path (for unflatten on load)
    flat_mapping: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    # non-tensor leaves (python scalars etc.) stored inline
    misc: Dict[str, Any] = field(default_factory=dict)
    # -- schema v2 (class-attr defaults double as the v1 compat path) -------
    schema_version: int = 1
    layout: Optional[SavedLayout] = None
