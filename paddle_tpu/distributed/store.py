"""TCPStore: rank-0 TCP KV rendezvous store (reference:
paddle/phi/core/distributed/store/tcp_store.h:121 — set/get/add/wait/
barrier used to exchange NCCL unique ids and synchronize bootstrap).

The server and client hot paths are native C++ (csrc/native_runtime.cpp,
loaded via ctypes); a pure-Python socketserver fallback keeps the API alive
when no toolchain is present. On TPU pods the coordination service normally
plays this role, but a framework-owned store is still needed for
launcher-level rendezvous and elastic membership (reference launcher master
KV, launch/controllers/master.py:73).
"""

from __future__ import annotations

import ctypes
import random
import struct
import threading
import time
from typing import Callable, Dict, Optional, TypeVar

from .. import _native

__all__ = ["TCPStore", "MasterStore", "StoreError", "TransientStoreError",
           "StoreTimeout", "retry_stats", "reset_retry_stats"]


class StoreError(RuntimeError):
    """Base class for KV-store failures."""


class TransientStoreError(StoreError):
    """Transient fault — connection refused, io error, dropped socket.
    IDEMPOTENT ops (connect/set/get/wait) retry these internally with
    exponential backoff + jitter (flags FLAGS_store_retry_{max,base_s,
    max_s}) before letting one propagate. add/compare_set raise it with NO
    retry: the mutation may have been applied server-side before the reply
    was lost, so callers must treat a caught TransientStoreError from those
    as INDETERMINATE, not safely re-callable."""


class StoreTimeout(StoreError, TimeoutError):
    """Deadline expired (key never appeared / peer unreachable within the
    timeout). NOT retried: the deadline already budgeted the waiting."""


_RETRY_STATS: Dict[str, int] = {}
_RETRY_LOCK = threading.Lock()
_T = TypeVar("_T")


def retry_stats() -> Dict[str, int]:
    """Per-op transient-retry counts (op -> retries), for tests and ops
    dashboards."""
    with _RETRY_LOCK:
        return dict(_RETRY_STATS)


def reset_retry_stats() -> None:
    with _RETRY_LOCK:
        _RETRY_STATS.clear()


def _with_retry(op: str, attempt: Callable[[], _T],
                deadline: Optional[float] = None) -> _T:
    """Run `attempt`, retrying TransientStoreError with exponential backoff
    and +/-50% jitter (decorrelates a fleet of workers hammering a
    just-restarted master). StoreTimeout and other errors pass through.
    `deadline` (time.monotonic) caps the WHOLE retry sequence so a
    timeout-bearing op never exceeds its caller's budget — without it, a
    transient fault near the deadline would restart the full wait."""
    from ..flags import flag
    attempts = max(1, int(flag("store_retry_max")))
    delay = float(flag("store_retry_base_s"))
    cap = float(flag("store_retry_max_s"))
    for k in range(attempts):
        try:
            return attempt()
        except TransientStoreError as e:
            with _RETRY_LOCK:
                _RETRY_STATS[op] = _RETRY_STATS.get(op, 0) + 1
            if k == attempts - 1:
                raise
            if deadline is not None and time.monotonic() >= deadline:
                raise StoreTimeout(
                    f"TCPStore.{op} deadline expired during transient-fault "
                    f"retry: {e}") from e
            time.sleep(min(cap, delay) * (0.5 + random.random()))
            delay *= 2


class _PyStoreServer:
    """Pure-Python fallback: an in-process KV shared by all TCPStore
    instances that name the same port (single-host tests without g++).
    A framed-protocol socket server is deliberately not reimplemented —
    real multi-process use requires the native build."""

    _registry = {}
    _registry_lock = threading.Lock()
    _next_port = [50000]

    def __init__(self):
        self.data = {}
        self.cond = threading.Condition()

    @classmethod
    def for_port(cls, port: int, create: bool):
        with cls._registry_lock:
            if port == 0 and create:
                cls._next_port[0] += 1
                port = cls._next_port[0]
            if port not in cls._registry:
                cls._registry[port] = cls()
            return port, cls._registry[port]


class TCPStore:
    """KV store client; rank 0 (is_master=True) also hosts the server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 world_size: int = 1, is_master: bool = False,
                 timeout: Optional[float] = None):
        self._lib = _native.load()
        if timeout is None:
            from ..flags import flag
            timeout = float(flag("tcp_store_timeout_s"))
        self._timeout_ms = int(timeout * 1000)
        self._server = None
        self._client = None
        self._fallback: Optional[_PyStoreServer] = None
        self.host = host
        self.world_size = world_size
        self.is_master = is_master

        if self._lib is None:
            # in-process fallback: only valid when all participants share the
            # process (unit tests); real multi-proc needs the native build
            self.port, self._fallback = _PyStoreServer.for_port(
                port, create=is_master)
            return

        if is_master:
            self._server = self._lib.pts_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            self.port = self._lib.pts_server_port(self._server)
        else:
            self.port = port

        connect_deadline = time.monotonic() + self._timeout_ms / 1000

        def connect():
            from .resilience import faults
            faults.maybe_fail("store/connect", exc=TransientStoreError)
            rem_ms = max(1, int((connect_deadline - time.monotonic())
                                * 1000))
            client = self._lib.pts_client_connect(
                host.encode(), self.port, rem_ms)
            if not client:
                raise TransientStoreError(
                    f"TCPStore: cannot reach {host}:{self.port}")
            return client
        try:
            self._client = _with_retry("connect", connect,
                                       deadline=connect_deadline)
        except TransientStoreError as e:
            # exhausted retries: surface as a timeout (the historical
            # contract — callers catch TimeoutError on rendezvous failure)
            raise StoreTimeout(str(e)) from e

    # -- API (reference surface) --------------------------------------------
    # set/get/wait/connect are idempotent, so transient io faults retry
    # with backoff (_with_retry); add/compare_set are NOT safely retryable
    # (the server may have applied an attempt whose reply was lost) and
    # only get typed errors.
    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()

        def attempt():
            from .resilience import faults
            faults.maybe_fail("store/set", exc=TransientStoreError)
            if self._fallback is not None:
                with self._fallback.cond:
                    self._fallback.data[key] = bytes(value)
                    self._fallback.cond.notify_all()
                return
            rc = self._lib.pts_client_set(self._client, key.encode(), value,
                                          len(value))
            if rc != 0:
                raise TransientStoreError(
                    f"TCPStore.set({key!r}) failed rc={rc}")
        _with_retry("set", attempt)

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        to_ms = self._timeout_ms if timeout is None else int(timeout * 1000)
        # one deadline for the whole call, retries included: each attempt
        # only gets the REMAINING budget
        deadline = time.monotonic() + to_ms / 1000

        def attempt():
            from .resilience import faults
            faults.maybe_fail("store/get", exc=TransientStoreError)
            rem_ms = max(1, int((deadline - time.monotonic()) * 1000))
            if self._fallback is not None:
                with self._fallback.cond:
                    while key not in self._fallback.data:
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            raise StoreTimeout(
                                f"TCPStore.get({key!r}) timed out")
                        self._fallback.cond.wait(rem)
                    return self._fallback.data[key]
            out = ctypes.c_void_p()
            out_len = ctypes.c_uint64()
            rc = self._lib.pts_client_get(self._client, key.encode(),
                                          rem_ms, ctypes.byref(out),
                                          ctypes.byref(out_len))
            if rc == -1:
                raise StoreTimeout(f"TCPStore.get({key!r}) timed out")
            if rc != 0:
                raise TransientStoreError(
                    f"TCPStore.get({key!r}) failed rc={rc}")
            return _native.take_bytes(self._lib, out.value, out_len.value)
        return _with_retry("get", attempt, deadline=deadline)

    def add(self, key: str, amount: int = 1) -> int:
        if self._fallback is not None:
            with self._fallback.cond:
                raw = self._fallback.data.get(key, b"")
                # match the native server: anything not exactly 8 bytes
                # counts as 0 rather than erroring
                cur = struct.unpack("<q", raw)[0] if len(raw) == 8 else 0
                now = cur + amount
                self._fallback.data[key] = struct.pack("<q", now)
                self._fallback.cond.notify_all()
                return now
        rc = self._lib.pts_client_add(self._client, key.encode(), amount)
        if rc == -(2 ** 63):
            # not retried: the increment may have been applied server-side
            # before the reply was lost
            raise TransientStoreError(f"TCPStore.add({key!r}) io error")
        return int(rc)

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            if self._fallback is not None:
                self.get(k, timeout)
                continue
            to_ms = (self._timeout_ms if timeout is None
                     else int(timeout * 1000))
            deadline = time.monotonic() + to_ms / 1000

            def attempt(k=k, deadline=deadline):
                from .resilience import faults
                faults.maybe_fail("store/wait", exc=TransientStoreError)
                rem_ms = max(1, int((deadline - time.monotonic()) * 1000))
                rc = self._lib.pts_client_wait(self._client, k.encode(),
                                               rem_ms)
                if rc == -1:
                    raise StoreTimeout(f"TCPStore.wait({k!r}) timed out")
                if rc != 0:
                    raise TransientStoreError(
                        f"TCPStore.wait({k!r}) failed rc={rc}")
            _with_retry("wait", attempt, deadline=deadline)

    def delete_key(self, key: str) -> bool:
        if self._fallback is not None:
            with self._fallback.cond:
                return self._fallback.data.pop(key, None) is not None
        return self._lib.pts_client_delete(self._client, key.encode()) > 0

    def num_keys(self) -> int:
        if self._fallback is not None:
            with self._fallback.cond:
                return len(self._fallback.data)
        return int(self._lib.pts_client_num_keys(self._client))

    def compare_set(self, key: str, expected: bytes, desired: bytes) -> bytes:
        if isinstance(expected, str):
            expected = expected.encode()
        if isinstance(desired, str):
            desired = desired.encode()
        if self._fallback is not None:
            with self._fallback.cond:
                cur = self._fallback.data.get(key, b"")
                if cur == expected or (not cur and not expected):
                    self._fallback.data[key] = desired
                    self._fallback.cond.notify_all()
                    return desired
                return cur
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        rc = self._lib.pts_client_compare_set(
            self._client, key.encode(), expected, len(expected), desired,
            len(desired), ctypes.byref(out), ctypes.byref(out_len))
        if rc != 0:
            # not retried: the swap may have landed before the reply died
            raise TransientStoreError(f"TCPStore.compare_set({key!r}) rc={rc}")
        return _native.take_bytes(self._lib, out.value, out_len.value)

    def barrier(self, name: str = "barrier", timeout: Optional[float] = None):
        """All world_size participants arrive before any leaves. Reusable:
        each instance tracks a per-name round so repeated barriers on the
        same name synchronize independently (all participants must call the
        same barriers in the same order)."""
        rounds = self.__dict__.setdefault("_barrier_rounds", {})
        r = rounds.get(name, 0)
        rounds[name] = r + 1
        n = self.add(f"__barrier/{name}/{r}/count", 1)
        if n == self.world_size:
            self.set(f"__barrier/{name}/{r}/go", b"1")
        self.wait([f"__barrier/{name}/{r}/go"], timeout)

    def close(self):
        if self._lib is None:
            return
        if self._client:
            self._lib.pts_client_close(self._client)
            self._client = None
        if self._server:
            self._lib.pts_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def MasterStore(endpoint: str, world_size: int, rank: int,
                timeout: float = 300.0) -> TCPStore:
    """Build a store from a 'host:port' endpoint (launcher convention:
    rank 0 hosts)."""
    host, port = endpoint.rsplit(":", 1)
    return TCPStore(host, int(port), world_size, is_master=(rank == 0),
                    timeout=timeout)
