"""The measurement loop (ISSUE 11): profile capture + attribution, fleet
telemetry with straggler detection, and the hang flight recorder.

Anchor contracts:

* **census ground truth** — the while-trip-aware compiled-HLO census
  counts loop-body collectives trip times (XLA's cost_analysis does
  not), and on a dp-only hybrid step its wire bytes match the planner's
  analytic dp model almost exactly;
* **profile -> planner loop** — a profile captured by the new pipeline
  on the CPU smoke mesh feeds ``auto_tuner plan --profile <json>`` end
  to end, and measured hide overrides change CostModel scoring;
* **straggler detection** — synthetic skewed windows flag exactly the
  slow host; a two-rank aggregation through the store emits the
  ``straggler_detected`` event into the JSONL log;
* **flight recorder** — an injected ``watchdog/hang`` stall makes the
  watchdog fire and leaves a bundle containing the telemetry ring tail,
  recent events and the open spans; with the flag off the recorder is
  inert and compiled programs are untouched (bitwise HLO).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import observability as obs
from paddle_tpu.distributed.auto_tuner import planner as PL
from paddle_tpu.distributed.auto_tuner.sweep import profile_candidate
from paddle_tpu.observability import profile_reader as PR
from paddle_tpu.utils import shard_map


# ---------------------------------------------------------------------------
# HLO census
# ---------------------------------------------------------------------------
def _scan_psum_program(mesh, length=3):
    def f(x, w):
        def body(c, _):
            return jax.lax.psum(c @ w, "mp") * 0.5, ()
        out, _ = jax.lax.scan(body, x, None, length=length)
        return jax.lax.pmean(jnp.sum(out), "dp")

    return jax.jit(shard_map(f, mesh=mesh, in_specs=(P("dp", "mp"),
                                                     P("mp", None)),
                             out_specs=P()))


def test_hlo_census_counts_loop_collectives():
    """Collectives inside a lax.scan count trip times; dot FLOPs too.
    The outer pmean adds one more all-reduce at multiplier 1."""
    mesh = dist.build_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
    j = _scan_psum_program(mesh, length=3)
    x = jnp.ones((8, 16))
    w = jnp.ones((16, 8))
    text = j.lower(x, w).compile().as_text()
    c = PR.hlo_census(text, default_group=4)
    assert c.collectives["all_reduce"]["count"] == 4  # 3 in-loop + 1
    # in-loop payload [4,8] f32 = 128 B over a 2-group: 2*128*(1/2) each;
    # the outer scalar pmean adds 2*4*(1/2)
    assert c.collectives["all_reduce"]["wire_bytes"] == 3 * 128 + 4
    # dot [4,8]x[8,8] = 512 flops, 3 trips
    assert c.dot_flops == 3 * 512
    assert not c.notes


def test_hlo_census_beats_cost_analysis_on_loops():
    """The reason the census exists: XLA cost_analysis reports loop
    bodies once, the census multiplies by the trip count."""
    mesh = dist.build_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
    j = _scan_psum_program(mesh, length=5)
    x = jnp.ones((8, 16))
    w = jnp.ones((16, 8))
    compiled = j.lower(x, w).compile()
    c = PR.hlo_census(compiled.as_text(), default_group=4)
    ca = compiled.cost_analysis()
    ca_flops = float((ca if isinstance(ca, dict) else ca[0])["flops"])
    assert c.dot_flops == 5 * 512
    assert ca_flops < c.dot_flops  # cost_analysis undercounts the loop


def test_attribution_math():
    """attribute_window: exposed clamps to [0, wire], hidden is the
    remainder, residual beyond compute+wire lands in overhead."""
    census = PR.Census(
        collectives={"all_reduce": {"count": 4.0, "wire_bytes": 4e6}},
        dot_flops=2e9, n_while=0, notes=[])
    rates = PR.MeasuredRates(rate_flops=1e12, ici_gbs=1.0, launch_s=1e-3)
    # wire = 4e6/1e9 + 4*1e-3 = 8 ms; compute = 2 ms
    att = PR.attribute_window(census, 0.006, rates)
    assert att["compute_s"] == pytest.approx(0.002)
    assert att["total_wire_s"] == pytest.approx(0.008)
    assert att["exposed_comm_s"] == pytest.approx(0.004)
    assert att["hidden_comm_s"] == pytest.approx(0.004)
    assert att["overhead_s"] == pytest.approx(0.0)
    assert att["hidable_fraction"] == pytest.approx(0.5)
    # step longer than compute + wire: the excess is overhead, nothing
    # is hidden
    att2 = PR.attribute_window(census, 0.015, rates)
    assert att2["exposed_comm_s"] == pytest.approx(0.008)
    assert att2["hidden_comm_s"] == pytest.approx(0.0)
    assert att2["overhead_s"] == pytest.approx(0.005)


def test_cost_model_hide_overrides():
    """Measured hide overrides WIN over the table and the
    overlap_capable zeroing (they are the measurement)."""
    import dataclasses
    from paddle_tpu.models.gpt import gpt_tiny
    cfg = gpt_tiny()
    spec = PL.ModelSpec.from_config(cfg, "gpt")
    cand = PL.PlanCandidate(dp=4, mp=2)
    base = PL.KNOWN_PROFILES["cpu"]  # overlap_capable=False
    cm0 = PL.CostModel(spec, base, global_batch=16, seq=64)
    exp0, wire0 = cm0.exposed_comm_s(cand)
    prof = dataclasses.replace(base, hide={"mp:allreduce": 1.0},
                               source="measured")
    cm1 = PL.CostModel(spec, prof, global_batch=16, seq=64)
    exp1, wire1 = cm1.exposed_comm_s(cand)
    assert wire1 == wire0  # wire model unchanged
    bw = base.ici_gbs * 1e9
    # the mp term is fully hidden now; dp stays exposed
    assert exp1 == pytest.approx(wire0["dp"] / bw, rel=1e-9)
    assert exp1 < exp0
    assert cm1.hide_fractions(cand)["mp"] == 1.0
    assert cm0.hide_fractions(cand)["mp"] == 0.0


def test_capture_profile_and_plan_cli(tmp_path):
    """Acceptance: `auto_tuner plan --profile <json>` runs end-to-end on
    a profile produced by the capture pipeline on the CPU smoke mesh."""
    mesh = dist.build_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
    j = _scan_psum_program(mesh, length=4)
    x = jnp.ones((8, 16))
    w = jnp.ones((16, 8))
    win = PR.capture_step_profile(j, (x, w), steps=2, label="smoke",
                                  mode="mp:allreduce", mesh=mesh)
    assert win.steps == 2 and win.step_time_s > 0
    assert win.census.collectives["all_reduce"]["count"] == 5
    prof = PR.derive_hardware_profile([win],
                                      base=PL.KNOWN_PROFILES["cpu"])
    assert prof.source == "measured"
    assert "mp:allreduce" in prof.hide
    path = str(tmp_path / "measured.json")
    PR.save_profile_json(path, prof, [win])
    # the CLI consumes it directly
    from paddle_tpu.distributed.auto_tuner.__main__ import main as cli
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli(["plan", "--model", "gpt_tiny", "--mesh", "2x4",
                  "--profile", path, "--json"])
    assert rc == 0
    report = json.loads(buf.getvalue())
    assert report["profile"]["source"] == "measured"
    assert report["profile"]["hide"]["mp:allreduce"] == pytest.approx(
        win.hidable_fraction, abs=1e-3)
    assert report["ranked"], report


def test_profile_reader_golden_dp2mp2():
    """Satellite golden: attribute a known dp2·mp2 CPU-smoke hybrid step
    — the census wire bytes must agree with the planner's analytic wire
    model within the documented tolerance (census counts remat replays
    of forward collectives + engine-internal reductions the useful-work
    model excludes, so the ratio sits in [0.9, 2.5])."""
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=16, dtype=jnp.float32)
    cand = PL.PlanCandidate(dp=2, mp=2)
    rates = PR.MeasuredRates(rate_flops=1e11, ici_gbs=1.0, launch_s=1e-4)
    win = profile_candidate(cfg, cand, global_batch=8, seq=16, steps=2,
                            rates=rates, mode="mp:allreduce")
    spec = PL.ModelSpec.from_config(cfg, "gpt")
    cm = PL.CostModel(spec, PL.KNOWN_PROFILES["cpu"], global_batch=8,
                      seq=16)
    analytic = sum(cm.predict(cand).wire.values())
    ratio = win.census.total_wire_bytes / analytic
    assert 0.9 <= ratio <= 2.5, (
        f"census {win.census.total_wire_bytes:.0f} B vs analytic "
        f"{analytic:.0f} B (ratio {ratio:.2f}) outside the documented "
        f"[0.9, 2.5] tolerance")
    # attribution is complete: the step splits into compute + exposed +
    # hidden + overhead exactly
    total = win.compute_s + win.exposed_comm_s + win.overhead_s
    assert total == pytest.approx(win.step_time_s, rel=1e-6)
    assert win.census.collectives["all_reduce"]["count"] > 0


@pytest.mark.slow
def test_profile_attribution_gate():
    """The slow-tier acceptance gate: measured exposed-comm attribution
    vs the analytic wire models across 3 planner configs + one
    deliberately-bad-overlap config on the CPU smoke mesh. Documented
    tolerance: census/analytic wire-byte ratio in [0.5, 2.5] for the
    scored configs; the bad config is exempt from the ratio but MUST
    attribute the worst exposed comm."""
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                      num_heads=4, max_seq_len=128, dtype=jnp.float32)
    B, S = 16, 128
    spec = PL.ModelSpec.from_config(cfg, "gpt")
    cm = PL.CostModel(spec, PL.KNOWN_PROFILES["cpu"], global_batch=B,
                      seq=S)
    flat = dist.build_mesh({"dp": 8})
    bw, launch = PR.measure_collective_rates(flat)
    rates = PR.MeasuredRates(rate_flops=PR.measure_compute_rate(),
                             ici_gbs=bw, launch_s=launch)
    Pc = PL.PlanCandidate
    gated = [(Pc(dp=8), "dp:monolithic"),
             (Pc(dp=8, comm_bucket_mb=4.0), "dp:bucketed"),
             (Pc(dp=4, mp=2), "mp:allreduce")]
    # the bad-overlap config: ring collective-matmul pays 4*(mp-1)
    # collectives per GEMM pair for overlap this backend cannot deliver —
    # the measured-worst config of the round-6 CPU proxy (BASELINE.md)
    bad = Pc(dp=2, mp=4, mp_overlap="collective_matmul")
    host_params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    windows, exposed = [], {}
    for cand, mode in gated + [(bad, None)]:
        win = profile_candidate(cfg, cand, global_batch=B, seq=S,
                                steps=3, rates=rates, mode=mode,
                                host_params=host_params)
        windows.append(win)
        exposed[str(cand)] = win.exposed_comm_s
        analytic = sum(cm.predict(cand).wire.values())
        ratio = win.census.total_wire_bytes / max(analytic, 1.0)
        if mode is not None:  # the bad config is ratio-exempt
            assert 0.5 <= ratio <= 2.5, (cand, ratio)
    # the bad-overlap config must attribute the WORST exposed comm
    assert exposed[str(bad)] == max(exposed.values()), exposed
    # the derived measured profile drives a full plan end-to-end
    prof = PR.derive_hardware_profile(windows,
                                      base=PL.KNOWN_PROFILES["cpu"])
    report = PL.plan(cfg, world=8, global_batch=B, seq=S, family="gpt",
                     profile=prof)
    assert report.ranked
    assert report.profile.source == "measured"


# ---------------------------------------------------------------------------
# Fleet telemetry + stragglers
# ---------------------------------------------------------------------------
def test_detect_stragglers_synthetic():
    fast = [10.0 + 0.1 * i for i in range(16)]
    slow = [25.0 + 0.1 * i for i in range(16)]
    det = obs.detect_stragglers({0: fast, 1: fast, 2: slow, 3: []},
                                factor=1.5)
    assert det["stragglers"] == [2]
    assert det["missing"] == [3]
    assert det["skew"] == pytest.approx(
        det["hosts"][2]["median_ms"] / det["fleet_median_ms"])
    assert det["hosts"][2]["p95_ms"] >= det["hosts"][2]["median_ms"]
    # under a looser factor nothing is flagged
    det2 = obs.detect_stragglers({0: fast, 1: fast, 2: slow}, factor=3.0)
    assert det2["stragglers"] == []


def test_detect_stragglers_no_false_flags_on_uniform_noise():
    rng = np.random.RandomState(0)
    windows = {h: list(10.0 + rng.rand(32)) for h in range(4)}
    det = obs.detect_stragglers(windows, factor=1.5)
    assert det["stragglers"] == []
    assert det["skew"] < 1.2


def test_aggregator_two_ranks_through_store(tmp_path):
    """Two aggregator instances (world 2) over a shared in-process
    store: rank 0's aggregate flags the slowed host, exports per-host
    p50/p95 gauges and emits straggler_detected into the JSONL log."""
    from paddle_tpu.distributed.store import TCPStore
    store0 = TCPStore(port=0, world_size=2, is_master=True)
    store1 = TCPStore(port=store0.port, world_size=2)
    log = obs.EventLog(str(tmp_path / "fleet.jsonl"))
    a0 = obs.TelemetryAggregator(rank=0, world_size=2, store=store0,
                                 host=0, window=8, interval=4,
                                 straggler_factor=1.5, event_log=log)
    a1 = obs.TelemetryAggregator(rank=1, world_size=2, store=store1,
                                 host=1, window=8, interval=4,
                                 straggler_factor=1.5)
    report = None
    for i in range(4):
        a0.note_step(10.0)
        a1.note_step(30.0)
        a1.tick(i)  # publish first so rank 0's gather never waits
        r = a0.tick(i)
        if r is not None:
            report = r
    assert report is not None
    assert report["stragglers"] == [1]
    assert report["skew"] == pytest.approx(3.0)
    assert a0.prom.get("step_ms_p95_host1") == pytest.approx(30.0)
    assert a0.prom.get("step_ms_p50_host0") == pytest.approx(10.0)
    assert a0.prom.get("stragglers") == 1
    assert a0.prom.get("step_time_skew") == pytest.approx(3.0)
    # the prom snapshot crossed the wire with the payload
    assert "step_ms_p95" in report["prom"][1]
    log.close()
    recs = [json.loads(l) for l in
            open(log.path, encoding="utf-8").read().splitlines()]
    ev = [r for r in recs if r["event"] == "straggler_detected"]
    assert len(ev) == 1  # flagged once per episode, not per round
    assert ev[0]["straggler_host"] == 1
    assert ev[0]["fleet_median_ms"] == pytest.approx(
        report["fleet_median_ms"])


def test_aggregator_in_run_resilient(tmp_path):
    """Single-process wiring: run_resilient(aggregator=) feeds step
    times and lands the final fleet report in info['fleet']."""
    from paddle_tpu.distributed.resilience import run_resilient
    agg = obs.TelemetryAggregator(rank=0, world_size=1, host=0,
                                  window=8, interval=2,
                                  straggler_factor=1.5)
    state = {"w": jnp.zeros((4,))}

    def step_fn(st, i):
        return {"w": st["w"] + 1.0}, jnp.float32(1.0)

    _, info = run_resilient(step_fn, state, steps=6,
                            ckpt_dir=str(tmp_path / "ck"), ckpt_every=0,
                            resume=False, aggregator=agg)
    assert info["fleet"] is not None
    assert info["fleet"]["stragglers"] == []
    assert 0 in info["fleet"]["hosts"]
    assert agg.prom.get("step_ms_count") is None  # histogram, not gauge
    snap = agg.prom.snapshot()
    assert snap["step_ms_count"] == 6.0


@pytest.mark.slow
def test_two_process_fleet_telemetry():
    """The mp_smoke fleet leg: 2 spawned processes aggregate over a real
    TCP store; the slowed rank must be flagged. Skips where the platform
    cannot run the spawned cluster."""
    from paddle_tpu.distributed import mp_smoke
    try:
        out = mp_smoke.fleet_telemetry_check(8, timeout=120, steps=8,
                                             slow_ms=80.0)
    except mp_smoke.ClusterUnsupported as e:
        pytest.skip(str(e))
    assert out["stragglers"] == [1]
    assert out["skew"] > 1.35


# ---------------------------------------------------------------------------
# prom histogram + quantiles
# ---------------------------------------------------------------------------
def test_prom_histogram_render_and_quantile():
    reg = obs.PromRegistry(namespace="t")
    for v in (0.002, 0.02, 0.02, 0.2):
        reg.histogram_observe("lat", v, buckets=(0.01, 0.1, 1.0))
    text = reg.render()
    assert '# TYPE t_lat histogram' in text
    assert 't_lat_bucket{le="0.01"} 1' in text
    assert 't_lat_bucket{le="0.1"} 3' in text
    assert 't_lat_bucket{le="1"} 4' in text
    assert 't_lat_bucket{le="+Inf"} 4' in text
    assert "t_lat_count 4" in text
    assert reg.quantile("lat", 0.5) == pytest.approx(0.02)
    assert reg.quantile("lat", 0.95) == pytest.approx(0.2)
    assert reg.get("lat") == pytest.approx((0.002 + 0.04 + 0.2) / 4)


def test_prom_summary_window_quantile_recent_only():
    """The window forgets: a slow startup wave stops biasing p95 once
    enough recent observations displace it (the TTFT/SLO fix)."""
    reg = obs.PromRegistry(namespace="t")
    for _ in range(4):
        reg.summary_observe("ttft", 5.0, window=8)
    for _ in range(8):
        reg.summary_observe("ttft", 0.1, window=8)
    assert reg.quantile("ttft", 0.95) == pytest.approx(0.1)
    # the lifetime mean still remembers — that is exactly why adaptive
    # control must not read it
    assert reg.get("ttft") > 1.0
    snap = reg.snapshot()
    assert snap["ttft_count"] == 12.0
    assert snap["ttft_p95"] == pytest.approx(0.1)


def test_serving_pick_burst_reads_window_p95():
    """The adaptive mix reads the registry's recent-window p95, not the
    lifetime mean: once recent TTFTs sit below the SLO the burst
    recovers even though the mean stays above it."""
    from paddle_tpu.inference.serving import ServingEngine
    eng = ServingEngine.__new__(ServingEngine)  # scheduler-only surface
    eng.adaptive_mix = True
    eng.decode_burst = 8
    eng.ttft_slo_s = 1.0
    eng._ttft_window = 4
    eng._prom = obs.PromRegistry(namespace="paddle_tpu_serving")
    for _ in range(6):  # slow startup wave
        eng._prom.summary_observe("ttft_seconds", 5.0, window=4)
    assert eng._pick_burst(1) < eng.decode_burst  # over SLO: shortened
    for _ in range(4):  # recent window recovers
        eng._prom.summary_observe("ttft_seconds", 0.05, window=4)
    with_pressure = eng._pick_burst(1)
    eng._prom.gauge_set("queue_depth", 0)
    assert eng._pick_burst(0) == eng.decode_burst  # no pressure: full
    assert with_pressure >= 8 // 4  # only the prefill pressure divides


# ---------------------------------------------------------------------------
# events: rotation, host/role, merge
# ---------------------------------------------------------------------------
def test_eventlog_rotation_and_attribution_fields(tmp_path):
    # size one record first, then cap for EXACTLY one rotation over 10
    # records, so the .1 generation + live file together hold every line
    probe = obs.EventLog(str(tmp_path / "probe.jsonl"), host=3)
    probe.emit("tick", i=0, pad="x" * 64)
    probe.close()
    rec_bytes = os.path.getsize(probe.path)
    path = str(tmp_path / "ev.jsonl")
    log = obs.EventLog(path, role="trainer", host=3,
                       max_mb=6.5 * rec_bytes / (1 << 20))
    for i in range(10):
        log.emit("tick", i=i, pad="x" * 64)
    log.close()
    assert log.rotations == 1
    assert os.path.exists(path + ".1")  # rotated generation
    recs = [json.loads(l) for l in
            open(path, encoding="utf-8").read().splitlines()]
    assert recs[0]["event"] == "jsonl_rotated"
    assert recs[0]["rotated_to"] == path + ".1"
    for r in recs:
        assert r["host"] == 3 and r["role"] == "trainer"
    # no line was lost across the rotation
    old = [json.loads(l) for l in
           open(path + ".1", encoding="utf-8").read().splitlines()]
    ticks = [r["i"] for r in old + recs if r["event"] == "tick"]
    assert ticks == list(range(10))


def test_eventlog_tail(tmp_path):
    log = obs.EventLog(str(tmp_path / "t.jsonl"))
    for i in range(20):
        log.emit("e", i=i)
    tail = log.tail(5)
    assert [r["i"] for r in tail] == [15, 16, 17, 18, 19]
    log.close()


def test_merge_event_streams(tmp_path):
    """One role-tagged timeline over a trainer loop and a serving
    engine's streams — ordered by ts, every record attributable."""
    trainer = obs.EventLog(str(tmp_path / "trainer.jsonl"),
                           role="trainer")
    serving = obs.EventLog(str(tmp_path / "serving.jsonl"),
                           role="serving")
    trainer.emit("step", i=0)
    serving.emit("serving_admit", rid=0)
    trainer.emit("step", i=1)
    serving.emit("serving_complete", rid=0)
    merged = obs.merge_event_streams(
        trainer, serving, out_path=str(tmp_path / "merged.jsonl"))
    assert len(merged) == 4
    assert [r["ts"] for r in merged] == sorted(r["ts"] for r in merged)
    roles = {r["event"]: r["role"] for r in merged}
    assert roles["step"] == "trainer"
    assert roles["serving_admit"] == "serving"
    written = [json.loads(l) for l in
               open(tmp_path / "merged.jsonl",
                    encoding="utf-8").read().splitlines()]
    assert written == merged


def test_merge_event_streams_training_plus_serving_engine(tmp_path):
    """The RL-loop pre-work end to end: a real training step loop and a
    real ServingEngine write separate logs; the merged stream carries
    both halves role-tagged."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import gpt as G
    t_log = obs.EventLog(str(tmp_path / "train.jsonl"), role="trainer")
    prev = obs.set_event_log(t_log)
    try:
        w = jnp.zeros((8, 8))
        x = jnp.ones((4, 8))

        @jax.jit
        def step(w):
            return w - 0.1 * jax.grad(
                lambda w: jnp.sum((x @ w) ** 2))(w)

        for i in range(3):
            w = step(w)
            obs.emit_event("train_step", step=i)
        # swap the process log to the serving stream and run the engine
        s_log = obs.EventLog(str(tmp_path / "serve.jsonl"),
                             role="serving")
        obs.set_event_log(s_log)
        cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, max_seq_len=64, dtype=jnp.float32)
        params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
        eng = ServingEngine(params, cfg, max_batch=2, block_size=16,
                            num_blocks=16, chunk=8, decode_burst=2,
                            adaptive_burst=False)
        eng.add_request(np.arange(4) % 64, max_new_tokens=3)
        eng.run(max_steps=20)
    finally:
        obs.set_event_log(prev)
    merged = obs.merge_event_streams(t_log, s_log)
    roles = {(r["role"], r["event"]) for r in merged}
    assert ("trainer", "train_step") in roles
    assert ("serving", "serving_admit") in roles
    assert ("serving", "serving_complete") in roles


def test_serving_steps_land_on_span_timeline(tmp_path):
    """The small fix: serving shows up on the same host timeline as
    training — engine steps open RecordEvent spans the profiler's
    collector sees."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=64, dtype=jnp.float32)
    params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(params, cfg, max_batch=2, block_size=16,
                        num_blocks=16, chunk=8, decode_burst=2,
                        adaptive_burst=False)
    eng.add_request(np.arange(4) % 64, max_new_tokens=3)
    with obs.capture_spans() as cap:
        eng.run(max_steps=20)
    names = {e.name for e in cap.events}
    assert "serving_step" in names
    assert ("serving_decode_dispatch" in names
            or "serving_prefill_dispatch" in names)
    path = obs.write_chrome_trace(str(tmp_path / "t.json"), cap.events)
    trace = json.load(open(path))
    assert any(ev["name"] == "serving_step"
               for ev in trace["traceEvents"])


# ---------------------------------------------------------------------------
# faults: the hang clause
# ---------------------------------------------------------------------------
def test_fault_hang_clause_stalls_then_continues():
    from paddle_tpu.distributed.resilience import faults
    faults.configure("x/slow:2:hang0.2")
    try:
        t0 = time.perf_counter()
        faults.maybe_fail("x/slow")          # hit 1: no stall
        assert time.perf_counter() - t0 < 0.15
        t0 = time.perf_counter()
        faults.maybe_fail("x/slow")          # hit 2: stalls, no raise
        assert time.perf_counter() - t0 >= 0.2
        faults.maybe_fail("x/slow")          # one-shot: done
        assert faults.hits()["x/slow"] == 3
    finally:
        faults.configure("")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def _drive_telemetry_host():
    """A TelemetryHost with two decoded rows, so crash bundles have a
    real ring tail."""
    tcfg = obs.TelemetryConfig(interval=2)
    host = obs.TelemetryHost(tcfg)
    buf = obs.init_buffer(tcfg)
    buf = obs.update_buffer(buf, tcfg, {"loss": jnp.float32(1.5)})
    buf = obs.update_buffer(buf, tcfg, {"loss": jnp.float32(1.25)})
    host.poll({"telemetry": buf}, 1)
    return host


def test_flight_recorder_bundle_on_injected_hang(tmp_path):
    """Acceptance: an injected watchdog/hang fault produces a bundle
    containing the telemetry tail, recent events and open spans — and
    the run CONTINUES after the stall (a hang is not a crash)."""
    from paddle_tpu.distributed.resilience import run_resilient
    log = obs.EventLog(str(tmp_path / "ev.jsonl"))
    prev_log = obs.set_event_log(log)
    rec = obs.FlightRecorder(str(tmp_path / "crash"), max_events=50,
                             keep=4, min_interval_s=0.0)
    prev_rec = obs.set_flight_recorder(rec)
    tele_host = _drive_telemetry_host()
    paddle.set_flags({"FLAGS_fault_inject": "watchdog/hang:2:hang1.2"})
    try:
        from paddle_tpu.distributed.watchdog import CommWatchdog
        state = {"w": jnp.zeros((4,))}

        def step_fn(st, i):
            return {"w": st["w"] + 1.0}, jnp.float32(float(i))

        wd = CommWatchdog(poll_interval=0.1)
        _, info = run_resilient(step_fn, state, steps=3,
                                ckpt_dir=str(tmp_path / "ck"),
                                ckpt_every=0, resume=False, watchdog=wd,
                                step_timeout=0.3)
        assert info["completed_steps"] == 3      # stalled, not aborted
        assert info["watchdog"]["timeout_count"] == 1
    finally:
        paddle.set_flags({"FLAGS_fault_inject": ""})
        obs.set_flight_recorder(prev_rec)
        obs.set_event_log(prev_log)
        wd.stop()
    assert rec.last_bundle is not None
    bundle = rec.last_bundle
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["reason"].startswith("watchdog_timeout")
    assert "resilient_step" in manifest["reason"]
    assert manifest["watchdog"]["active"] >= 1    # the hung span, open
    # recent events: the run lifecycle up to the hang is in the tail
    tail = [json.loads(l) for l in
            open(os.path.join(bundle, "events_tail.jsonl"),
                 encoding="utf-8").read().splitlines()]
    assert any(r["event"] == "resilience_run_start" for r in tail)
    # open spans: the wedged step's watchdog span with its age
    spans = json.load(open(os.path.join(bundle, "open_spans.json")))
    pend = {s["tag"]: s["age_s"] for s in spans["watchdog_pending"]}
    assert "resilient_step" in pend and pend["resilient_step"] >= 0.3
    # telemetry ring tail: the live host's decoded rows (other tests'
    # hosts may still be alive in the registry — find ours)
    tele = json.load(open(os.path.join(bundle, "telemetry_tail.json")))
    assert any(ring["series"]["loss"] == [1.5, 1.25]
               for ring in tele.values())
    # the report carries the thread-stack dump
    report = open(os.path.join(bundle, "report.txt")).read()
    assert "exceeded its deadline" in report
    # the dump announced itself in the JSONL stream
    recs = [json.loads(l) for l in
            open(log.path, encoding="utf-8").read().splitlines()]
    assert any(r["event"] == "flight_recorder_dump" for r in recs)
    del tele_host


def test_flight_recorder_sigterm_dump(tmp_path):
    """The resilience SIGTERM path dumps a bundle during the drain."""
    import signal
    from paddle_tpu.distributed.resilience import run_resilient
    rec = obs.FlightRecorder(str(tmp_path / "crash"), min_interval_s=0.0)
    prev_rec = obs.set_flight_recorder(rec)
    try:
        state = {"w": jnp.zeros((2,))}

        def step_fn(st, i):
            if i == 1:
                os.kill(os.getpid(), signal.SIGTERM)
            return st, jnp.float32(1.0)

        _, info = run_resilient(step_fn, state, steps=10,
                                ckpt_dir=str(tmp_path / "ck"),
                                ckpt_every=0, resume=False, grace_s=5.0)
        assert info["preempted"]
    finally:
        obs.set_flight_recorder(prev_rec)
    assert rec.last_bundle is not None
    manifest = json.load(
        open(os.path.join(rec.last_bundle, "manifest.json")))
    assert manifest["reason"] == "sigterm"


def test_flight_recorder_bounded(tmp_path):
    """The crash dir stays bounded: keep-N pruning + rate limit."""
    rec = obs.FlightRecorder(str(tmp_path / "crash"), keep=2,
                             min_interval_s=0.0)
    for i in range(5):
        assert rec.dump(f"r{i}") is not None
    bundles = [e for e in os.listdir(tmp_path / "crash")
               if e.startswith("flight_")]
    assert len(bundles) == 2
    limited = obs.FlightRecorder(str(tmp_path / "crash2"), keep=2,
                                 min_interval_s=60.0)
    assert limited.dump("a") is not None
    assert limited.dump("b") is None  # rate-limited


def test_flight_recorder_off_is_inert_and_hlo_unchanged(tmp_path):
    """Telemetry-off no-op stays intact (the established HLO-assert
    pattern): arming the flight recorder flag changes NOTHING in a
    compiled program — it is host-side only — and with the flag empty
    maybe_dump is a no-op."""
    from paddle_tpu.observability.flight_recorder import maybe_dump
    assert obs.get_flight_recorder() is None  # flag empty
    assert maybe_dump("nothing") is None

    @jax.jit
    def step(w):
        return w * 2.0
    w = jnp.ones((8,))
    base = step.lower(w).as_text()
    paddle.set_flags(
        {"FLAGS_flight_recorder_dir": str(tmp_path / "crash")})
    try:
        assert obs.get_flight_recorder() is not None
        armed = step.lower(w).as_text()
    finally:
        paddle.set_flags({"FLAGS_flight_recorder_dir": ""})
    assert base == armed


def test_active_spans_registry():
    from paddle_tpu.profiler import RecordEvent, active_spans
    ev = RecordEvent("hanging_op")
    ev.begin()
    try:
        spans = active_spans()
        mine = [s for s in spans if s["name"] == "hanging_op"]
        assert mine and mine[0]["age_s"] >= 0.0
    finally:
        ev.end()
    assert not [s for s in active_spans() if s["name"] == "hanging_op"]
