"""Fused RMSNorm (Pallas).

TPU-native equivalent of the reference's fused CUDA RMSNorm
(reference: paddle/phi/kernels/gpu/rms_norm_kernel.cu; Python surface
paddle.incubate.nn.functional.fused_rms_norm).

One pass over rows in VMEM: mean-square, rsqrt, scale — the normalized
activation never round-trips to HBM. Backward fuses the dx recurrence in a
second row-blocked kernel; dw is a cross-row reduction left to XLA (it
fuses into a single segment-sum over the saved rstd).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import LANES as _LANES
from ._common import interpret as _interpret

__all__ = ["rms_norm", "supported"]


def _pick_rows(n: int, hidden: int) -> int:
    # target ~2MB of fp32 rows in VMEM
    r = max(1, min(n, (1 << 19) // max(hidden, 1)))
    while n % r:
        r -= 1
    return r


def supported(x, weight, epsilon=1e-6, **kwargs) -> bool:
    return x.ndim >= 2 and x.shape[-1] == weight.shape[-1]


def _fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)  # [rows, 1]
    y_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[:] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _bwd_kernel(x_ref, w_ref, rstd_ref, dy_ref, dx_ref):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:][:, 0][:, None]  # [rows, 1]
    h = x.shape[-1]
    dyw = dy * w
    dot = jnp.sum(dyw * x, axis=-1, keepdims=True)
    dx = rstd * dyw - (rstd ** 3) * x * dot / h
    dx_ref[:] = dx.astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, epsilon=1e-6):
    """y = x / sqrt(mean(x^2) + eps) * weight over the last axis."""
    y, _ = _rms_fwd(x, weight, epsilon)
    return y


def _rms_fwd(x, weight, epsilon):
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    rows = _pick_rows(n, h)
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=epsilon),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x.dtype),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, weight.reshape(1, h))
    return y.reshape(shape), (x2, weight, rstd, shape)


def _rms_bwd(epsilon, res, g):
    x2, weight, rstd, shape = res
    h = shape[-1]
    dy = g.reshape(-1, h)
    n = x2.shape[0]
    rows = _pick_rows(n, h)
    dx = pl.pallas_call(
        _bwd_kernel,
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x2.dtype),
        interpret=_interpret(),
    )(x2, weight.reshape(1, h), rstd, dy)
    # dw: cross-row reduction — a single fused XLA reduce over saved rstd
    xf = x2.astype(jnp.float32)
    dw = jnp.sum(dy.astype(jnp.float32) * xf * rstd[:, :1], axis=0)
    return dx.reshape(shape), dw.astype(weight.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)
