"""Fused LayerNorm (Pallas).

TPU-native equivalent of the reference's fused LayerNorm kernel
(reference: paddle/phi/kernels/gpu/layer_norm_kernel.cu; the BERT-era
fused_attention/fused_feedforward kernels fold the same residual+LN
pattern, fusion/gpu/fused_attention_kernel.cu).

One row-blocked pass: mean, variance, normalize, affine — x is read once
and the [rows] statistics live in VMEM. The XLA-composed fallback
(nn/functional/norm.py layer_norm) emits separate convert_reduce fusions
for the stats that run at ~84 GB/s on bf16 rows (measured on the BERT-base
step, round 4); this kernel removes that round trip. Backward fuses the dx
recurrence in a second row-blocked kernel; dw/db are cross-row reductions
left to one fused XLA reduce (same split as rms_norm.py).

Relation to kernels/pallas/primitives.py layer_norm: that one is the
KPS-primitives teaching tier (in-kernel dg/db accumulation over a
sequential grid, bias required); this module is the dispatch tier wired
into the op registry (optional bias, cross-row reductions delegated to
XLA so the grid stays embarrassingly parallel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import LANES as _LANES
from ._common import interpret as _interpret

__all__ = ["layer_norm", "supported"]


def _pick_rows(n: int, hidden: int) -> int:
    # ~2MB of fp32 rows per block; the grid uses pl.cdiv with a masked
    # edge block, so no exact-divisor hunt (a prime row count would
    # otherwise degrade to 1-row tiles at 1/8 sublane utilization).
    # Mosaic wants the sublane block divisible by 8 (or == the array dim).
    r = min(n, (1 << 19) // max(hidden, 1))
    if r < n:
        r = max(8, (r // 8) * 8)
    return r


def supported(x, weight, epsilon=1e-5, **kwargs) -> bool:
    return x.ndim >= 2 and x.shape[-1] == weight.shape[-1]


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, stat_ref, *, eps, has_bias):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)        # [rows, 1]
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * w_ref[:].astype(jnp.float32)
    if has_bias:
        y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    # stats lane-replicated: [rows, 128] x (mean, rstd) interleaved as two
    # outputs would double the launches; pack mean in [:, :64]? No —
    # keep it simple: stat_ref is [rows, 2*LANES] = [mean | rstd] halves
    stat_ref[:, :_LANES] = jnp.broadcast_to(mean, (x.shape[0], _LANES))
    stat_ref[:, _LANES:] = jnp.broadcast_to(rstd, (x.shape[0], _LANES))


def _bwd_kernel(x_ref, w_ref, stat_ref, dy_ref, dx_ref):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    mean = stat_ref[:, :1]               # [rows, 1]
    rstd = stat_ref[:, _LANES:_LANES + 1]
    h = x.shape[-1]
    xhat = (x - mean) * rstd
    dxhat = dy * w
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)


def layer_norm(x, weight, bias=None, epsilon=1e-5):
    """y = (x - mean) / sqrt(var + eps) * weight (+ bias) over the last
    axis, output in x.dtype (the mixed-precision contract of the composed
    path)."""
    x = jnp.asarray(x)
    weight = jnp.asarray(weight)
    bias = None if bias is None else jnp.asarray(bias)
    return _ln(x, weight, bias, epsilon)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln(x, weight, bias, epsilon):
    y, _ = _ln_fwd(x, weight, bias, epsilon)
    return y


def _ln_fwd(x, weight, bias, epsilon):
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    rows = _pick_rows(n, h)
    has_bias = bias is not None
    b_in = (bias.reshape(1, h) if has_bias
            else jnp.zeros((1, h), weight.dtype))
    y, stat = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=epsilon, has_bias=has_bias),
        grid=(pl.cdiv(n, rows),),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((rows, 2 * _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x.dtype),
            jax.ShapeDtypeStruct((n, 2 * _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, weight.reshape(1, h), b_in)
    return y.reshape(shape), (x2, weight, has_bias, stat, shape)


def _ln_bwd(epsilon, res, g):
    x2, weight, has_bias, stat, shape = res
    h = shape[-1]
    dy = g.reshape(-1, h)
    n = x2.shape[0]
    rows = _pick_rows(n, h)
    dx = pl.pallas_call(
        _bwd_kernel,
        grid=(pl.cdiv(n, rows),),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((rows, 2 * _LANES), lambda i: (i, 0)),
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x2.dtype),
        interpret=_interpret(),
    )(x2, weight.reshape(1, h), stat, dy)
    # dw/db: cross-row reductions — one fused XLA reduce over the saved
    # stats (xhat recomputed elementwise, fuses into the reduction)
    xf = x2.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - stat[:, :1]) * stat[:, _LANES:_LANES + 1]
    dw = jnp.sum(dyf * xhat, axis=0).astype(weight.dtype)
    db = jnp.sum(dyf, axis=0).astype(weight.dtype) if has_bias else None
    return dx.reshape(shape), dw, db


_ln.defvjp(_ln_fwd, _ln_bwd)
