"""Fleet distributed-training facade (reference:
python/paddle/distributed/fleet/ — fleet.init/distributed_model/
distributed_optimizer at fleet/fleet.py:151,218).

Populated incrementally: layers/ (TP), utils/ (SP), recompute/, meta_parallel/
(pipeline, sharding). The top-level fleet API object lives in fleet.py.
"""

from . import layers, meta_parallel, recompute, utils  # noqa: F401

__all__ = ["layers", "meta_parallel", "recompute", "utils"]
