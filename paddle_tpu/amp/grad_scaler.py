"""Dynamic loss scaling for float16 AMP.

Reference: python/paddle/amp/grad_scaler.py (GradScaler: scale/unscale_/
step/update/minimize with dynamic loss scaling and found_inf skip).

TPU design: bf16 — the native policy — needs no scaler; this exists for
fp16 parity. Two surfaces:

* functional (jit/pjit-safe): explicit scaler state pytree threaded through
  the train step. `found_inf` is a traced scalar; the skip is a jnp.where
  select so the whole step stays one compiled program (no host sync, which
  would stall the TPU pipeline the way the reference's GPU found_inf D2H
  copy does).
* eager: paddle-style scale(loss)/step(optimizer)/update() over Parameter
  .grad slots for hapi/dygraph-style loops.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["GradScaler", "OptimizerState", "nonfinite_report"]

OptimizerState = Dict[str, Any]


def nonfinite_report(tree, max_leaves: int = 16) -> str:
    """Per-leaf non-finite diagnostic: one line per floating leaf that
    contains nan/inf, with its key path, shape and counts. The loop-level
    extension of the scaler's found_inf bit — when a run aborts after too
    many skipped steps, this names WHICH tensors went bad instead of a bare
    'loss is nan' (consumed by distributed.resilience run_resilient)."""
    import numpy as np
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    lines = []
    for path, leaf in flat:
        try:
            arr = np.asarray(leaf)
        except Exception:
            continue
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        if n_nan or n_inf:
            lines.append(f"  {jax.tree_util.keystr(path)}: shape="
                         f"{tuple(arr.shape)} nan={n_nan} inf={n_inf}")
    if not lines:
        return "  (all leaves finite)"
    shown = lines[:max_leaves]
    if len(lines) > max_leaves:
        shown.append(f"  ... and {len(lines) - max_leaves} more leaves")
    return "\n".join(shown)


def _tree_finite(tree) -> jax.Array:
    leaves = [x for x in jax.tree.leaves(tree) if x is not None]
    if not leaves:
        return jnp.bool_(True)
    oks = [jnp.all(jnp.isfinite(x)) for x in leaves
           if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not oks:
        return jnp.bool_(True)
    return jnp.stack(oks).all()


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 16,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 2000, decr_every_n_nan_or_inf: int = 1,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = bool(enable)
        self._init_scale = float(init_loss_scaling)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every = int(incr_every_n_steps)
        self._decr_every = int(decr_every_n_nan_or_inf)
        self._dynamic = bool(use_dynamic_loss_scaling)
        # eager-mode state
        self._eager = self.init_state()
        self._eager_found_inf = False
        self._unscaled = False

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    # ---------------- functional surface ----------------
    def init_state(self) -> Dict[str, jax.Array]:
        return {
            "scale": jnp.float32(self._init_scale if self._enable else 1.0),
            "good_steps": jnp.int32(0),
            "bad_steps": jnp.int32(0),
        }

    def scale(self, loss, state: Optional[Dict] = None):
        """loss * loss_scaling. Without `state`, uses the eager state."""
        if not self._enable:
            return loss
        s = (state or self._eager)["scale"]
        # promote to fp32: demoting the scale to fp16 overflows at the
        # default 2**16 (> fp16 max) and would flag every step as inf
        return jnp.asarray(loss).astype(jnp.float32) * s

    def unscale(self, grads, state: Dict):
        """Returns (unscaled_grads, found_inf). Pure; jit-safe."""
        if not self._enable:
            return grads, jnp.bool_(False)
        inv = (1.0 / state["scale"]).astype(jnp.float32)
        un = jax.tree.map(
            lambda g: None if g is None
            else (g.astype(jnp.float32) * inv).astype(g.dtype), grads,
            is_leaf=lambda x: x is None)
        found_inf = ~_tree_finite(un)
        return un, found_inf

    def update_state(self, state: Dict, found_inf) -> Dict:
        """Dynamic loss-scaling bookkeeping (reference semantics: grow scale
        by incr_ratio after incr_every_n_steps clean steps; shrink by
        decr_ratio after decr_every_n_nan_or_inf bad steps)."""
        if not (self._enable and self._dynamic):
            return state
        found_inf = jnp.asarray(found_inf)
        good = jnp.where(found_inf, 0, state["good_steps"] + 1)
        bad = jnp.where(found_inf, state["bad_steps"] + 1, 0)
        grow = good >= self._incr_every
        shrink = bad >= self._decr_every
        scale = state["scale"]
        scale = jnp.where(grow, scale * self._incr_ratio, scale)
        scale = jnp.where(shrink, jnp.maximum(scale * self._decr_ratio, 1.0),
                          scale)
        good = jnp.where(grow, 0, good)
        bad = jnp.where(shrink, 0, bad)
        return {"scale": scale, "good_steps": good, "bad_steps": bad}

    def step(self, optimizer, params, grads, opt_state: OptimizerState,
             scaler_state: Dict, lr=None):
        """Unscale, conditionally apply the optimizer (skip on inf), update
        scaling. Returns (params, opt_state, scaler_state, found_inf).
        One compiled program — the inf-skip is a select, not a host branch."""
        grads, found_inf = self.unscale(grads, scaler_state)
        new_p, new_s = optimizer.apply(params, grads, opt_state, lr)
        keep = lambda old, new: old if new is None else (
            new if old is None else jnp.where(found_inf, old, new))
        params = jax.tree.map(keep, params, new_p,
                              is_leaf=lambda x: x is None)
        opt_state = jax.tree.map(keep, opt_state, new_s,
                                 is_leaf=lambda x: x is None)
        scaler_state = self.update_state(scaler_state, found_inf)
        return params, opt_state, scaler_state, found_inf

    # ---------------- eager surface (paddle parity) ----------------
    def unscale_(self, optimizer):
        """Divide Parameter.grad slots by the scale; record found_inf."""
        if not self._enable or self._unscaled:
            return
        import numpy as np
        inv = 1.0 / float(self._eager["scale"])
        found = False
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            g = np.asarray(p.grad, dtype=np.float32) * inv
            if not np.isfinite(g).all():
                found = True
            p.grad = g.astype(np.asarray(p.grad).dtype)
        self._eager_found_inf = found
        self._unscaled = True

    def eager_step(self, optimizer):
        self.unscale_(optimizer)
        if not self._eager_found_inf:
            optimizer.step()

    def update(self):
        self._eager = jax.tree.map(
            jnp.asarray,
            self.update_state(self._eager, jnp.bool_(self._eager_found_inf)))
        self._eager_found_inf = False
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        del scaled_loss  # backward already ran in the eager flow
        self.eager_step(optimizer)
        self.update()

    # ---------------- scale accessors / checkpoint ----------------
    def get_loss_scaling(self):
        return float(self._eager["scale"])

    def set_init_loss_scaling(self, v: float):
        self._init_scale = float(v)
        self._eager["scale"] = jnp.float32(v)

    def state_dict(self) -> Dict[str, Any]:
        import numpy as np
        return {
            "scale": np.asarray(self._eager["scale"]),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "good_steps": int(self._eager["good_steps"]),
            "bad_steps": int(self._eager["bad_steps"]),
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, sd: Dict[str, Any]):
        self._eager = {
            "scale": jnp.float32(sd["scale"]),
            "good_steps": jnp.int32(sd.get("good_steps", 0)),
            "bad_steps": jnp.int32(sd.get("bad_steps", 0)),
        }
        self._incr_ratio = float(sd.get("incr_ratio", self._incr_ratio))
        self._decr_ratio = float(sd.get("decr_ratio", self._decr_ratio))
        self._incr_every = int(sd.get("incr_every_n_steps", self._incr_every))
        self._decr_every = int(sd.get("decr_every_n_nan_or_inf",
                                      self._decr_every))
