"""Megatron-style sequence parallelism (reference:
python/paddle/distributed/fleet/utils/sequence_parallel_utils.py —
ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers :85-137,
ColumnSequenceParallelLinear/RowSequenceParallelLinear :427+).

Activations between TP blocks are sharded on the SEQUENCE dim over the 'mp'
axis; entering a TP block all-gathers the sequence, leaving it
reduce-scatters — replacing the identity/allreduce pair of plain TP with an
allgather/reduce-scatter pair of equal bandwidth but 1/mp activation memory.

Explicit-mode (shard_map) ops, paired fwd/bwd via custom_vjp exactly as the
reference's PyLayers; sequence dim is axis 0 ([s, b, h] layout) to match the
reference's convention.

The hybrid engines use the generalized (any seq dim) versions in
``distributed.comm_overlap.collective_matmul`` — scatter_seq/ag_seq/rs_seq
plus the ring collective-matmul entry points ``ag_matmul``/``matmul_rs``
(FLAGS_mp_seq_parallel / FLAGS_mp_collective_matmul); this module keeps the
reference-shaped layer surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ....enforce import enforce
from jax import lax

from ....nn.layer.layers import Layer
from ....nn.initializer import XavierNormal
from ..layers.mpu import mp_ops

__all__ = ["scatter", "all_gather", "reduce_scatter", "mark_as_sequence_parallel_parameter",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "GatherOp", "ScatterOp", "AllGatherOp", "ReduceScatterOp"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter(x, axis: str = "mp"):
    """Split seq dim across mp ranks; bwd all-gathers (ScatterOp :85)."""
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    size = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=0)


def _scatter_fwd(x, axis):
    return scatter(x, axis), None


def _scatter_bwd(axis, res, g):
    return (lax.all_gather(g, axis, axis=0, tiled=True),)


scatter.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def all_gather(x, axis: str = "mp"):
    """Gather seq dim; bwd reduce-scatters (AllGatherOp :118)."""
    return lax.all_gather(x, axis, axis=0, tiled=True)


def _all_gather_fwd(x, axis):
    return all_gather(x, axis), None


def _all_gather_bwd(axis, res, g):
    return (lax.psum_scatter(g, axis, scatter_dimension=0, tiled=True),)


all_gather.defvjp(_all_gather_fwd, _all_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter(x, axis: str = "mp"):
    """Sum + split seq dim; bwd all-gathers (ReduceScatterOp :137)."""
    return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)


def _reduce_scatter_fwd(x, axis):
    return reduce_scatter(x, axis), None


def _reduce_scatter_bwd(axis, res, g):
    return (lax.all_gather(g, axis, axis=0, tiled=True),)


reduce_scatter.defvjp(_reduce_scatter_fwd, _reduce_scatter_bwd)

# Reference PyLayer-name aliases
ScatterOp = scatter
GatherOp = all_gather
AllGatherOp = all_gather
ReduceScatterOp = reduce_scatter


def mark_as_sequence_parallel_parameter(parameter):
    """Tag a parameter (e.g. LayerNorm weight) as replicated-but-SP so its
    grads get allreduced over mp (reference:
    register_sequence_parallel_allreduce_hooks :192). Under GSPMD the psum
    is automatic; the tag is kept for explicit-mode engines."""
    parameter.sequence_parallel = True
    return parameter


class ColumnSequenceParallelLinear(Layer):
    """all_gather(seq) -> local column matmul (reference :427)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None, name=None):
        super().__init__()
        from ..layers.mpu.mp_layers import _mp_info, _annotate
        from jax.sharding import PartitionSpec as P
        self.mesh, self.axis, self.world_size, self.rank = _mp_info(mp_group)
        enforce(out_features % self.world_size == 0,
                "out_features must be divisible by the mp world size",
                op="ColumnSequenceParallelLinear",
                out_features=out_features, world=self.world_size)
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr,
                                            default_initializer=XavierNormal())
        self.weight.placements = None
        _annotate(self.weight, self.mesh, P(None, "mp"))
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        if self.bias is not None:
            _annotate(self.bias, self.mesh, P("mp"))

    def forward(self, x):
        axis = mp_ops.explicit_axis() or "mp"
        if mp_ops.in_explicit_mode() and self.world_size > 1:
            x = all_gather(x, axis)
        y = jnp.matmul(x, jnp.asarray(self.weight))
        if self.bias is not None:
            y = y + jnp.asarray(self.bias)
        return y


class RowSequenceParallelLinear(Layer):
    """local row matmul -> reduce_scatter(seq) (reference :427+)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None, name=None):
        super().__init__()
        from ..layers.mpu.mp_layers import _mp_info, _annotate
        from jax.sharding import PartitionSpec as P
        self.mesh, self.axis, self.world_size, self.rank = _mp_info(mp_group)
        enforce(in_features % self.world_size == 0,
                "in_features must be divisible by the mp world size",
                op="RowSequenceParallelLinear", in_features=in_features,
                world=self.world_size)
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr,
                                            default_initializer=XavierNormal())
        _annotate(self.weight, self.mesh, P("mp", None))
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        if self.bias is not None:
            _annotate(self.bias, self.mesh, P())

    def forward(self, x):
        axis = mp_ops.explicit_axis() or "mp"
        y = jnp.matmul(x, jnp.asarray(self.weight))
        if mp_ops.in_explicit_mode() and self.world_size > 1:
            y = reduce_scatter(y, axis)
        if self.bias is not None:
            y = y + jnp.asarray(self.bias)
        return y
