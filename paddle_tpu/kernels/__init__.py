"""Fused-kernel tier (TPU-native equivalent of paddle/phi/kernels/fusion/).

The reference ships 92.8k LoC of hand-written CUDA fusion kernels
(fused_attention, fused_rope, rms_norm, fused_multi_transformer, ...).
On TPU the same tier is a small set of Pallas kernels that XLA cannot fuse
on its own — attention (O(S) VMEM tiling), normalization (single-pass
row reductions), rotary embedding — registered as fast-path overrides of
the XLA-composed reference ops via ``paddle_tpu.ops.register_pallas_impl``.
"""

from . import pallas  # noqa: F401
