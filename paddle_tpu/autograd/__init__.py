"""paddle.autograd equivalent (reference: python/paddle/autograd/ —
PyLayer custom autograd py_layer.py, paddle.grad double-grad,
saved_tensors_hooks; functional jacobian/hessian/vjp/jvp).

TPU design: autodiff is jax's transform stack, so PyLayer lowers onto
jax.custom_vjp (higher-order works for free), grad/jacobian/hessian/vjp/jvp
are thin functional wrappers, and saved_tensors_hooks packs/unpacks the
residuals PyLayer saves (the reference hooks the tape's TensorWrappers;
here the ctx is the tape)."""

from .py_layer import PyLayer, PyLayerContext, saved_tensors_hooks
from .functional import grad, hessian, jacobian, jvp, vjp

__all__ = ["PyLayer", "PyLayerContext", "saved_tensors_hooks",
           "grad", "jacobian", "hessian", "vjp", "jvp"]
