from . import lr  # noqa: F401
from .gradient_merge import GradientMergeOptimizer
from .lbfgs import LBFGS, minimize_lbfgs
from .optimizer import (Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Lars,
                        Momentum,
                        NAdam, Optimizer, RAdam, RMSProp, SGD)

__all__ = ["lr", "Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta",
           "RMSProp", "Adam", "AdamW", "Adamax", "Lamb", "Lars", "NAdam", "RAdam",
           "LBFGS", "minimize_lbfgs", "GradientMergeOptimizer"]
