"""SPMD pipeline tests (reference pattern:
test/collective/fleet/hybrid_parallel_pp_*.py — pipeline output/grad parity
vs the unpartitioned model)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.utils import shard_map
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_parallel.pp_utils.spmd_pipeline import (
    pipeline_last_stage_value, spmd_pipeline)

PP = 4          # pipeline stages
L_PER = 2       # blocks per stage
M = 8           # microbatches
MB, H = 2, 16   # microbatch size, hidden


def _block(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stage_fn(stage_params, x):
    # scan over this rank's stacked blocks
    def body(h, p):
        return _block(p, h), None
    h, _ = jax.lax.scan(body, x, stage_params)
    return h


def _dense_forward(params, x):
    # params stacked [L, ...] — run all blocks sequentially
    def body(h, p):
        return _block(p, h), None
    h, _ = jax.lax.scan(body, x, params)
    return h


@pytest.fixture
def pipeline_setup():
    mesh = dist.build_mesh({"pp": PP, "rest": 8 // PP})
    rng = np.random.RandomState(0)
    L = PP * L_PER
    params = {
        "w": jnp.asarray(rng.randn(L, H, H).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(L, H).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(M, MB, H).astype(np.float32))
    return mesh, params, x


def test_pipeline_forward_matches_dense(pipeline_setup):
    mesh, params, x = pipeline_setup

    def run(params, x):
        # reshape local [L/P, ...] params
        local = jax.tree.map(lambda a: a, params)
        return spmd_pipeline(_stage_fn, local, x, axis="pp")

    fn = shard_map(run, mesh=mesh,
                   in_specs=({"w": P("pp"), "b": P("pp")}, P()),
                   out_specs=P())
    out = jax.jit(fn)(params, x)
    ref = jax.vmap(lambda xi: _dense_forward(params, xi))(x)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_pipeline_grads_match_dense(pipeline_setup):
    mesh, params, x = pipeline_setup
    y = jnp.asarray(np.random.RandomState(1).randn(M, MB, H).astype(np.float32))

    def pp_loss_grads(params, x, y):
        def loss(params):
            out = spmd_pipeline(_stage_fn, params, x, axis="pp")
            return jnp.mean((out - y) ** 2)
        l, g = jax.value_and_grad(loss)(params)
        return l, g

    fn = shard_map(pp_loss_grads, mesh=mesh,
                   in_specs=({"w": P("pp"), "b": P("pp")}, P(), P()),
                   out_specs=(P(), {"w": P("pp"), "b": P("pp")}))
    l_pp, g_pp = jax.jit(fn)(params, x, y)

    def dense_loss(params):
        out = jax.vmap(lambda xi: _dense_forward(params, xi))(x)
        return jnp.mean((out - y) ** 2)

    l_ref, g_ref = jax.value_and_grad(dense_loss)(params)
    assert abs(float(l_pp) - float(l_ref)) < 1e-6
    for k in g_ref:
        assert np.allclose(np.asarray(g_pp[k]), np.asarray(g_ref[k]),
                           atol=1e-5), k


def test_pipeline_with_dp_axis(pipeline_setup):
    """pp x dp hybrid: batch sharded over dp, blocks over pp."""
    mesh, params, x = pipeline_setup  # axes pp=4, rest=2 (use as dp)

    def run(params, x):
        out = spmd_pipeline(_stage_fn, params, x, axis="pp")
        return jnp.mean(out ** 2)

    fn = shard_map(run, mesh=mesh,
                   in_specs=({"w": P("pp"), "b": P("pp")}, P(None, "rest")),
                   out_specs=P())
    # mean over dp shards needs a psum — wrap:
    def run2(params, x):
        out = spmd_pipeline(_stage_fn, params, x, axis="pp")
        return jax.lax.pmean(jnp.mean(out ** 2), "rest")

    fn2 = shard_map(run2, mesh=mesh,
                    in_specs=({"w": P("pp"), "b": P("pp")}, P(None, "rest")),
                    out_specs=P())
    out = float(jax.jit(fn2)(params, x))
    ref = jax.vmap(lambda xi: _dense_forward(params, xi))(x)
    assert abs(out - float(jnp.mean(ref ** 2))) < 1e-5


def test_last_stage_broadcast():
    mesh = dist.build_mesh({"pp": 8})

    def run():
        idx = jax.lax.axis_index("pp")
        val = jnp.where(idx == 7, 42.0, 0.0)
        return pipeline_last_stage_value(val, "pp")

    out = jax.jit(shard_map(run, mesh=mesh, in_specs=(), out_specs=P()))()
    assert float(out) == 42.0
