"""paddle.linalg namespace (reference: python/paddle/linalg.py — re-exports
of tensor/linalg.py plus decomposition ops backed by
paddle/phi/kernels/*/svd_kernel, qr_kernel, eigh_kernel, lu_kernel, ...).

On TPU these lower to XLA's decomposition ops (jnp.linalg / jax.scipy);
several (eig, lu with pivoting) fall back to CPU inside XLA where the TPU
has no native lowering — same functional surface either way."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import (cholesky, cond, corrcoef, cov, det, eig, eigh,
                     inverse, lstsq, matrix_power, matrix_rank, multi_dot,
                     norm, pinv, qr, slogdet, solve, svd,
                     triangular_solve)

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "householder_product", "inv", "inverse",
    "lstsq", "lu", "lu_unpack", "matrix_exp", "matrix_power", "matrix_rank",
    "matrix_transpose", "multi_dot", "norm", "ormqr", "pca_lowrank", "pinv",
    "qr", "slogdet", "solve", "svd", "svdvals", "triangular_solve",
    "vector_norm",
]

inv = inverse


def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def svdvals(x, name=None):
    return jnp.linalg.svd(x, compute_uv=False)


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization (reference: paddle.linalg.lu — returns packed LU,
    pivots, and optionally an info tensor)."""
    import jax.scipy.linalg as jsl
    lu_, piv = jsl.lu_factor(x)
    if get_infos:
        info = jnp.zeros(x.shape[:-2], jnp.int32)
        return lu_, piv.astype(jnp.int32), info
    return lu_, piv.astype(jnp.int32)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack paddle.linalg.lu results into (P, L, U). Batched inputs
    supported (leading dims broadcast through the pivot loop)."""
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    L = (jnp.tril(lu_data, -1)
         + jnp.eye(m, n, dtype=lu_data.dtype))[..., :, :k]
    U = jnp.triu(lu_data)[..., :k, :]
    # pivots (LAPACK ipiv, 0-based here) -> permutation matrix; the swap
    # loop is static over k but each swap is batched over leading dims
    batch = lu_data.shape[:-2]
    perm = jnp.broadcast_to(jnp.arange(m), batch + (m,))
    for i in range(lu_pivots.shape[-1]):
        j = lu_pivots[..., i]
        pi = perm[..., i]
        pj = jnp.take_along_axis(perm, j[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
        perm = perm.at[..., i].set(pj)
        perm = jnp.where(
            jnp.arange(m) == j[..., None], pi[..., None], perm)
    # P[..., perm[r], r] = 1  (row-permutation matrix, P @ L @ U == A)
    P = (perm[..., None, :] == jnp.arange(m)[:, None]).astype(lu_data.dtype)
    out = []
    if unpack_pivots:
        out.append(P)
    if unpack_ludata:
        out.extend([L, U])
    return tuple(out)


def cholesky_solve(x, y, upper=False, name=None):
    import jax.scipy.linalg as jsl
    return jsl.cho_solve((y, not upper), x)


def matrix_exp(x, name=None):
    import jax.scipy.linalg as jsl
    return jsl.expm(x)


def matrix_transpose(x, name=None):
    return jnp.swapaxes(x, -2, -1)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """Flattened vector p-norm over `axis` (int, tuple/list, or None = all
    dims) — always the VECTOR norm, never a matrix norm, matching the
    reference's paddle.linalg.vector_norm."""
    if axis is None:
        axes = tuple(range(x.ndim))
    elif isinstance(axis, (tuple, list)):
        axes = tuple(a % x.ndim for a in axis)
    else:
        axes = (axis % x.ndim,)
    ax = jnp.abs(x.astype(jnp.float32))
    if p == float("inf"):
        out = jnp.max(ax, axis=axes, keepdims=keepdim)
    elif p == float("-inf"):
        out = jnp.min(ax, axis=axes, keepdims=keepdim)
    elif p == 0:
        out = jnp.sum((ax != 0).astype(jnp.float32), axis=axes,
                      keepdims=keepdim)
    else:
        out = jnp.sum(ax ** p, axis=axes, keepdims=keepdim) ** (1.0 / p)
    return out.astype(x.dtype)


def _reflectors(x, tau):
    """Yield the (v_i, tau_i) Householder pairs of geqrf layout (unit
    lower part, zeros above the diagonal)."""
    m, k = x.shape[-2], tau.shape[-1]
    for i in range(k):
        v = x[..., :, i]
        v = jnp.where(jnp.arange(m) < i, 0.0, v)
        v = v.at[..., i].set(1.0)
        yield v, tau[..., i]


def _householder_full(x, tau):
    """The FULL m x m product Q = H_0 H_1 ... H_{k-1}."""
    m = x.shape[-2]
    Q = jnp.eye(m, dtype=x.dtype)
    Q = jnp.broadcast_to(Q, x.shape[:-2] + (m, m)).copy() \
        if x.ndim > 2 else Q
    for v, t in _reflectors(x, tau):
        # Q (I - t v v^H) applied from the right, rank-1 update form
        Qv = Q @ v[..., :, None]
        Q = Q - t[..., None, None] * (Qv * jnp.conj(v[..., None, :]))
    return Q


def householder_product(x, tau, name=None):
    """Q from Householder reflectors (reference:
    paddle.linalg.householder_product; LAPACK orgqr): columns of x hold
    v_i (unit lower part) — the thin m x n slice of the full product."""
    del name
    return _householder_full(jnp.asarray(x),
                             jnp.asarray(tau))[..., :, :x.shape[-1]]


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Apply the implicit FULL Q of the reflectors to `other` (LAPACK
    ormqr semantics), reflector by reflector — O(k m n), never forming
    the m x m Q."""
    del name
    x, tau = jnp.asarray(x), jnp.asarray(tau)
    y = jnp.asarray(other)
    refl = list(_reflectors(x, tau))
    # Q = H_0 H_1 ... H_{k-1}: Q y applies reflectors last-first, Q^T y
    # first-last (H_i is Hermitian)
    if left:
        # y <- H y for each reflector, composing to Q y (or Q^T y)
        seq = refl[::-1] if not transpose else refl
        for v, t in seq:
            vy = jnp.conj(v)[..., None, :] @ y          # [. , 1, n]
            y = y - t[..., None, None] * (v[..., :, None] * vy)
        return y
    # right: y <- y H, composing to y Q (or y Q^T)
    seq = refl if not transpose else refl[::-1]
    for v, t in seq:
        yv = y @ v[..., :, None]                        # [. , m, 1]
        y = y - t[..., None, None] * (yv * jnp.conj(v)[..., None, :])
    return y


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (reference: paddle.linalg.pca_lowrank)."""
    m, n = x.shape[-2], x.shape[-1]
    q = min(6, m, n) if q is None else q
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    U, S, Vh = jnp.linalg.svd(x, full_matrices=False)
    return U[..., :q], S[..., :q], jnp.swapaxes(Vh, -2, -1)[..., :q]
