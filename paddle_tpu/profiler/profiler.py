"""Profiler (reference: python/paddle/profiler/profiler.py — ProfilerState
:89, targets :110, scheduler windows make_scheduler, export_chrome_tracing
:227; statistics tables profiler_statistic.py).

TPU design: the device timeline comes from jax.profiler (XPlane → TensorBoard
/ Perfetto); this Profiler adds the reference's scheduling state machine,
host-span summary tables, and a self-contained chrome-trace export so users
get the familiar workflow (start/step/stop, summary()) without extra tools.
"""

from __future__ import annotations

import enum
import json
import os
import time
from collections import defaultdict
from typing import Callable, Iterable, List, Optional, Sequence

from .utils import HostEvent, RecordEvent, collector

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "make_scheduler",
           "export_chrome_tracing", "RecordEvent", "SummaryView"]


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last record step of a window


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """Window state machine (reference semantics): skip_first CLOSED steps,
    then cycles of closed→ready→record; repeat=0 cycles forever."""
    assert closed >= 0 and ready >= 0 and record > 0
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = s // period
        if repeat and cycle >= repeat:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD  # record everything until stop()


def export_chrome_tracing(dir_name: Optional[str] = None,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callback writing chrome://tracing JSON. dir_name
    defaults to FLAGS_profiler_dir."""
    if dir_name is None:
        from ..flags import flag
        dir_name = flag("profiler_dir")

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name,
                            f"{name}_step{prof.step_num}.json")
        events = []
        for ev in prof._recorded:
            events.append({
                "name": ev.name, "ph": "X", "cat": ev.event_type,
                "ts": ev.start * 1e6, "dur": ev.duration * 1e6,
                "pid": os.getpid(), "tid": ev.tid,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        prof.last_export_path = path

    return handler


class SummaryView:
    """Aggregated per-name host-span stats (reference: profiler_statistic
    summary tables)."""

    def __init__(self, events: Sequence[HostEvent]):
        from .utils import Stat
        agg = defaultdict(Stat)
        for e in events:
            agg[e.name].add(e.duration)
        self.rows = {k: {"calls": s.count, "total": s.total, "min": s.min,
                         "max": s.max, "avg": s.avg}
                     for k, s in agg.items()}

    def __str__(self):
        if not self.rows:
            return "(no events recorded)"
        w = max(len(k) for k in self.rows)
        lines = [f"{'Name'.ljust(w)}  Calls     Total(ms)   Avg(ms)   "
                 f"Min(ms)   Max(ms)"]
        for k, r in sorted(self.rows.items(), key=lambda kv: -kv[1]["total"]):
            lines.append(
                f"{k.ljust(w)}  {r['calls']:5d}  {r['total']*1e3:10.3f}  "
                f"{r['avg']*1e3:8.3f}  {r['min']*1e3:8.3f}  "
                f"{r['max']*1e3:8.3f}")
        return "\n".join(lines)


class Profiler:
    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 profile_memory: bool = False, with_flops: bool = False,
                 timer_only: bool = False):
        del profile_memory, with_flops
        self.targets = list(targets or [ProfilerTarget.CPU,
                                        ProfilerTarget.TPU])
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif callable(scheduler):
            self.scheduler = scheduler
        else:  # (start, end) tuple shorthand, reference behavior
            start, end = scheduler
            self.scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                            record=end - start, repeat=1)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._recorded: List[HostEvent] = []   # current window only
        self._history: List[HostEvent] = []    # finished windows (summary)
        self._jax_trace_dir: Optional[str] = None
        self.last_export_path: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self.state = self.scheduler(self.step_num)
        self._apply_state()
        return self

    def stop(self):
        if self.state in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN):
            self._finish_window()
        collector.enabled = False
        self._stop_jax_trace()
        self.state = ProfilerState.CLOSED

    def step(self):
        prev = self.state
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._recorded.extend(collector.drain())
        self.step_num += 1
        self.state = self.scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN or (
                prev == ProfilerState.RECORD
                and self.state not in (ProfilerState.RECORD,
                                       ProfilerState.RECORD_AND_RETURN)):
            self._finish_window()
        self._apply_state()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- internals -----------------------------------------------------------
    def _apply_state(self):
        recording = self.state in (ProfilerState.RECORD,
                                   ProfilerState.RECORD_AND_RETURN)
        if recording and not collector.enabled:
            collector.clear()
            collector.enabled = True
            if not self.timer_only:
                self._start_jax_trace()
        elif not recording and collector.enabled:
            collector.enabled = False
            self._stop_jax_trace()

    def _start_jax_trace(self):
        if ProfilerTarget.TPU not in self.targets:
            return
        try:
            import tempfile
            import jax.profiler
            self._jax_trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            jax.profiler.start_trace(self._jax_trace_dir)
        except Exception:
            self._jax_trace_dir = None

    def _stop_jax_trace(self):
        if self._jax_trace_dir is not None:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_trace_dir = None

    def _finish_window(self):
        self._recorded.extend(collector.drain())
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)  # sees only this window's events
        # windows export independently; summary() still sees everything
        self._history.extend(self._recorded)
        self._recorded = []

    # -- reporting -----------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms") -> SummaryView:
        del sorted_by, op_detail, thread_sep, time_unit
        return SummaryView(self._history + self._recorded)
