"""Fault-tolerant runtime tests: crash-safe checkpoint commit, deterministic
fault injection, preemption (SIGTERM) handling, store retry/backoff,
watchdog observability, and Model.fit resilient= plumbing.

All tests here are tier-1 (fast, JAX_PLATFORMS=cpu, no `slow` marker): the
fault-injection sites are the only way the recovery paths ever execute, so
they must run on every CI pass (reference analog: the subprocess-kill
chaos pattern of test/legacy_test/test_dist_base.py, made deterministic).
"""

import ast
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.resilience import (
    FaultInjected, commit_checkpoint, faults, latest_checkpoint)
from paddle_tpu.distributed.resilience.commit import (
    COMMIT_MARKER, checkpoint_step, is_committed)
from paddle_tpu.distributed.resilience.driver import (
    NonFiniteLossError, WatchdogTimeout, run_resilient)
from paddle_tpu.distributed.watchdog import CommWatchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "resilience_worker.py")

# every site the checkpoint path plants, in commit order
CKPT_SITES = ["ckpt/after_chunk_write", "ckpt/before_metadata_write",
              "ckpt/before_commit", "ckpt/after_rename"]


def _arm(spec):
    paddle.set_flags({"FLAGS_fault_inject": spec})


def _sgd_step(state, i):
    """Deterministic: loss and update are pure functions of (state, i)."""
    x = jax.random.normal(jax.random.PRNGKey(i), (4,), dtype=jnp.float32)
    w = state["w"]
    loss = jnp.sum((w - x) ** 2)
    return {"w": w - 0.2 * (w - x)}, loss


# -- commit protocol ---------------------------------------------------------
def test_commit_latest_and_retention(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3, 4):
        p = commit_checkpoint({"w": jnp.full((4,), float(step))}, d, step,
                              keep_n=2)
        assert is_committed(p)
        assert latest_checkpoint(d) == p
        assert checkpoint_step(p) == step
    kept = sorted(os.listdir(d))
    assert kept == ["step_00000003", "step_00000004"]  # keep_n=2 pruned 1,2
    out = ckpt.load_state_dict({"w": jnp.zeros((4,))}, latest_checkpoint(d))
    np.testing.assert_array_equal(np.asarray(out["w"]), np.full((4,), 4.0))


def test_commit_idempotent_and_async(tmp_path):
    d = str(tmp_path)
    p1 = commit_checkpoint({"w": jnp.ones((4,))}, d, 5, async_save=True)
    p2 = commit_checkpoint({"w": jnp.zeros((4,))}, d, 5)  # recommit: no-op
    assert p1 == p2
    out = ckpt.load_state_dict({"w": jnp.zeros((4,))}, p1)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(4))


@pytest.mark.parametrize("site", CKPT_SITES)
def test_interrupted_commit_never_discoverable(tmp_path, site):
    """Acceptance: a checkpoint directory interrupted at ANY injected point
    is never returned by latest_checkpoint, and the straggler is GC'd."""
    d = str(tmp_path)
    good = commit_checkpoint({"w": jnp.ones((4,))}, d, 1)
    _arm(f"{site}:1")
    with pytest.raises(FaultInjected):
        commit_checkpoint({"w": jnp.full((4,), 2.0)}, d, 2)
    _arm("")
    assert latest_checkpoint(d) == good
    assert sorted(os.listdir(d)) == ["step_00000001"]  # straggler GC'd
    # the survivor still loads
    out = ckpt.load_state_dict({"w": jnp.zeros((4,))}, good)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(4))


def test_empty_or_missing_root(tmp_path):
    assert latest_checkpoint(str(tmp_path / "nope")) is None
    assert latest_checkpoint(str(tmp_path)) is None


# -- satellite: atomic metadata / file writes --------------------------------
def test_atomic_write_crash_leaves_old_bytes(tmp_path):
    from paddle_tpu.distributed.checkpoint.utils import atomic_write
    p = tmp_path / "0.metadata"
    p.write_bytes(b"OLD")
    with pytest.raises(RuntimeError, match="mid-write"):
        with atomic_write(str(p)) as f:
            f.write(b"NEW-PARTIAL")
            raise RuntimeError("crash mid-write")
    assert p.read_bytes() == b"OLD"
    assert os.listdir(tmp_path) == ["0.metadata"]  # temp file cleaned up


def test_metadata_crash_never_truncates(tmp_path):
    """Crash before the metadata replace leaves the OLD metadata loadable —
    not an opaque UnpicklingError from a truncated pickle."""
    d = str(tmp_path)
    ckpt.save_state_dict({"w": jnp.ones((4,))}, d)
    md_old = ckpt.load_metadata(d)
    _arm("ckpt/before_metadata_write:1")
    with pytest.raises(FaultInjected):
        ckpt.save_state_dict({"w": jnp.zeros((4,))}, d)
    _arm("")
    md = ckpt.load_metadata(d)  # must not raise
    assert set(md.state_dict_metadata) == set(md_old.state_dict_metadata)


def test_no_raw_final_path_writes_in_checkpoint_pkg():
    """Guard: nothing under distributed/checkpoint/ may open a final
    destination path for writing except the atomic-commit helper
    (utils.atomic_write). Parses the AST, so variable modes count too."""
    import paddle_tpu.distributed.checkpoint as pkg
    pkg_dir = os.path.dirname(pkg.__file__)
    violations = []
    for fname in sorted(os.listdir(pkg_dir)):
        if not fname.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(pkg_dir, fname)).read())
        stack = []

        def visit(node):
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                stack.append(node.name)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                mode = None
                if len(node.args) > 1:
                    mode = (node.args[1].value
                            if isinstance(node.args[1], ast.Constant)
                            else "<dynamic>")
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = (kw.value.value
                                if isinstance(kw.value, ast.Constant)
                                else "<dynamic>")
                writes = mode is not None and (
                    mode == "<dynamic>" or any(c in mode for c in "wax+"))
                allowed = fname == "utils.py" and "atomic_write" in stack
                if writes and not allowed:
                    violations.append(f"{fname}:{node.lineno} open(mode="
                                      f"{mode!r}) in {stack or ['<module>']}")
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_fn:
                stack.pop()
        visit(tree)
    assert not violations, (
        "final-destination checkpoint writes must go through "
        f"checkpoint.utils.atomic_write: {violations}")


def test_wait_async_save_aggregates_all_writer_errors():
    import importlib
    ssd = importlib.import_module(
        "paddle_tpu.distributed.checkpoint.save_state_dict")
    ssd._ASYNC_ERRORS.extend([ValueError("writer0 boom"),
                              OSError("writer1 disk full")])
    with pytest.raises(RuntimeError) as ei:
        ssd.wait_async_save()
    msg = str(ei.value)
    assert "2 writer(s)" in msg
    assert "writer0 boom" in msg and "writer1 disk full" in msg
    assert not ssd._ASYNC_ERRORS  # drained


# -- fault injection engine --------------------------------------------------
def test_fault_spec_nth_and_counts():
    _arm("a/b:3")
    fired = []
    for _ in range(5):
        try:
            faults.maybe_fail("a/b")
            fired.append(False)
        except FaultInjected:
            fired.append(True)
    assert fired == [False, False, True, False, False]  # one-shot on 3rd hit
    assert faults.hits() == {"a/b": 5}
    faults.reset()
    assert faults.hits() == {}


def test_fault_probabilistic_is_seed_deterministic():
    def pattern():
        out = []
        for _ in range(32):
            try:
                faults.maybe_fail("x/y")
                out.append(False)
            except FaultInjected:
                out.append(True)
        return out
    paddle.set_flags({"FLAGS_fault_inject_seed": 7})
    _arm("x/y:p0.5")
    p1 = pattern()
    _arm("x/y:p0.5")  # reconfigure resets the per-site stream
    p2 = pattern()
    assert p1 == p2
    assert any(p1) and not all(p1)  # actually Bernoulli, not constant


def test_fault_disarmed_is_silent():
    _arm("")
    for _ in range(3):
        faults.maybe_fail("anything/at/all")  # must not raise or count


# -- resilient driver --------------------------------------------------------
def test_end_to_end_crash_recovery_bitwise(tmp_path):
    """Acceptance: train N steps, crash mid-checkpoint, restart, resume
    from the last committed step, reach bitwise-identical losses and final
    state vs the uninterrupted run."""
    w0 = {"w": jnp.zeros((4,), jnp.float32)}
    golden_losses = {}
    golden, _ = run_resilient(
        _sgd_step, w0, steps=9, ckpt_dir=str(tmp_path / "a"), ckpt_every=3,
        on_step=lambda i, l: golden_losses.__setitem__(i, l))

    d = str(tmp_path / "b")
    losses = {}
    _arm("ckpt/after_chunk_write:2")  # survives commit@3, dies in commit@6
    with pytest.raises(FaultInjected):
        run_resilient(_sgd_step, w0, steps=9, ckpt_dir=d, ckpt_every=3,
                      on_step=lambda i, l: losses.__setitem__(i, l))
    _arm("")
    assert checkpoint_step(latest_checkpoint(d)) == 3
    state, info = run_resilient(
        _sgd_step, w0, steps=9, ckpt_dir=d, ckpt_every=3,
        on_step=lambda i, l: losses.__setitem__(i, l))
    assert info["resumed_from"].endswith("step_00000003")
    assert losses == golden_losses  # bitwise: dict of floats, == not approx
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(golden["w"]))


def test_nonfinite_skip_then_recover(tmp_path):
    def step(state, i):
        if i == 1:
            return {"w": state["w"] * np.float32("nan")}, float("nan")
        return {"w": state["w"] + 1.0}, 1.0
    state, info = run_resilient(step, {"w": jnp.zeros((2,))}, steps=4,
                                ckpt_dir=str(tmp_path))
    assert info["nonfinite_skips"] == 1
    # step 1 was rejected found_inf-style: 3 good updates landed
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full((2,), 3.0))


def test_nonfinite_abort_with_per_leaf_diagnostic(tmp_path):
    def step(state, i):
        return ({"good": state["good"],
                 "bad": state["bad"] * np.float32("nan")}, float("nan"))
    with pytest.raises(NonFiniteLossError) as ei:
        run_resilient(step, {"good": jnp.ones((2,)), "bad": jnp.ones((3,))},
                      steps=10, ckpt_dir=str(tmp_path),
                      max_consecutive_nonfinite=3)
    msg = str(ei.value)
    assert "3 consecutive non-finite" in msg
    assert "'bad'" in msg and "nan=3" in msg   # the guilty leaf, by name
    assert "'good'" not in msg                 # finite leaves stay silent


def test_watchdog_escalation_aborts_and_commits(tmp_path):
    wd = CommWatchdog(poll_interval=0.02,
                      on_timeout=lambda s, r: None)  # keep stderr clean
    d = str(tmp_path)

    def step(state, i):
        if i == 2:
            time.sleep(1.5)  # hangs well past the budget
        return {"w": state["w"] + 1.0}, 1.0

    try:
        with pytest.raises(WatchdogTimeout):
            run_resilient(step, {"w": jnp.zeros((2,))}, steps=10,
                          ckpt_dir=d, step_timeout=0.1,
                          abort_on_timeout=True, watchdog=wd)
    finally:
        wd.stop()
    assert wd.stats()["timeout_count"] == 1  # escalated exactly once
    # the hung step's final commit holds the last GOOD state (2 steps)
    p = latest_checkpoint(d)
    out = ckpt.load_state_dict({"step": 0, "state": {"w": jnp.zeros((2,))}},
                               p)
    assert out["step"] == 2
    np.testing.assert_array_equal(np.asarray(out["state"]["w"]),
                                  np.full((2,), 2.0))


def test_watchdog_reset_and_stats():
    fired = []
    wd = CommWatchdog(poll_interval=0.02,
                      on_timeout=lambda s, r: fired.append(s.tag))
    wd.start()
    try:
        with wd.watch("fast", timeout=10):
            pass
        with wd.watch("slow", timeout=0.05):
            time.sleep(0.2)
        s = wd.stats()
        assert s["timeout_count"] == 1 and fired == ["slow"]
        assert s["spans_started"] == 2 and s["spans_completed"] == 2
        assert s["active"] == 0
        wd.reset()
        assert wd.stats() == {"timeout_count": 0, "spans_started": 0,
                              "spans_completed": 0, "active": 0}
    finally:
        wd.stop()


# -- store retry/backoff -----------------------------------------------------
def test_store_transient_fault_is_retried():
    from paddle_tpu.distributed import store as store_mod
    paddle.set_flags({"FLAGS_store_retry_base_s": 0.001})
    st = store_mod.TCPStore(is_master=True)
    try:
        st.set("k", b"v")
        store_mod.reset_retry_stats()
        _arm("store/get:1")
        assert st.get("k") == b"v"  # 1st attempt injected, retry succeeds
        assert store_mod.retry_stats()["get"] == 1
    finally:
        _arm("")
        st.close()


def test_store_retry_exhaustion_raises_typed():
    from paddle_tpu.distributed import store as store_mod
    paddle.set_flags({"FLAGS_store_retry_base_s": 0.001,
                      "FLAGS_store_retry_max": 2})
    st = store_mod.TCPStore(is_master=True)
    try:
        st.set("k", b"v")
        store_mod.reset_retry_stats()
        _arm("store/get:p1.0")  # every attempt fails
        with pytest.raises(store_mod.TransientStoreError):
            st.get("k")
        assert store_mod.retry_stats()["get"] == 2  # = FLAGS_store_retry_max
    finally:
        _arm("")
        st.close()


def test_store_timeout_is_typed_and_not_retried():
    from paddle_tpu.distributed import store as store_mod
    assert issubclass(store_mod.StoreTimeout, TimeoutError)
    st = store_mod.TCPStore(is_master=True)
    try:
        store_mod.reset_retry_stats()
        t0 = time.monotonic()
        with pytest.raises(store_mod.StoreTimeout):
            st.get("never-set", timeout=0.05)
        assert time.monotonic() - t0 < 2.0  # no backoff loop on deadline
        assert store_mod.retry_stats().get("get", 0) == 0
    finally:
        st.close()


def test_store_connect_retries():
    from paddle_tpu.distributed import store as store_mod
    paddle.set_flags({"FLAGS_store_retry_base_s": 0.001})
    master = store_mod.TCPStore(is_master=True)
    try:
        store_mod.reset_retry_stats()
        _arm("store/connect:1")
        client = store_mod.TCPStore(host=master.host, port=master.port,
                                    is_master=False)
        client.close()
        assert store_mod.retry_stats()["connect"] == 1
    finally:
        _arm("")
        master.close()


# -- spawn-based: real process death -----------------------------------------
def _spawn(mode, ckpt_dir, steps=None, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               FLAGS_fault_inject="")
    env.update(extra_env or {})
    args = [sys.executable, WORKER, mode, ckpt_dir]
    if steps is not None:
        args.append(str(steps))
    return subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _result(out):
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(f"no RESULT line in: {out!r}")


def test_spawned_kill_crash_recovery(tmp_path):
    """Hard-kill (os._exit, no atexit/flush) mid-checkpoint, respawn,
    resume: losses match an uninterrupted spawned run exactly."""
    golden_dir, d = str(tmp_path / "a"), str(tmp_path / "b")
    p = _spawn("train", golden_dir)
    out, err = p.communicate(timeout=180)
    assert p.returncode == 0, err
    golden = _result(out)

    p = _spawn("train", d,
               extra_env={"FLAGS_fault_inject": "ckpt/after_chunk_write:2:kill"})
    out, err = p.communicate(timeout=180)
    assert p.returncode == faults.FAULT_EXIT_CODE, (out, err)
    assert checkpoint_step(latest_checkpoint(d)) == 3

    p = _spawn("train", d)  # respawn, fault disarmed
    out, err = p.communicate(timeout=180)
    assert p.returncode == 0, err
    resumed = _result(out)
    assert resumed["resumed_from"].endswith("step_00000003")
    assert resumed["w"] == golden["w"]  # bitwise (json round-trips exactly)
    for k, v in resumed["losses"].items():
        assert golden["losses"][k] == v


def test_spawned_sigterm_one_final_commit_in_grace(tmp_path):
    """Acceptance: SIGTERM during training produces exactly one final
    committed checkpoint within the grace budget."""
    d = str(tmp_path)
    p = _spawn("slow", d, steps=2000)
    try:
        for line in iter(p.stdout.readline, ""):
            if line.strip() == "READY":
                break
        time.sleep(0.4)  # let a few steps land
        t0 = time.monotonic()
        p.send_signal(signal.SIGTERM)
        out, err = p.communicate(timeout=60)
        elapsed = time.monotonic() - t0
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == 0, err
    res = _result(out)
    assert res["preempted"] is True
    assert 0 < res["completed"] < 2000
    assert elapsed < 15.0  # inside the grace budget passed to the worker
    committed = [n for n in os.listdir(d)
                 if os.path.isfile(os.path.join(d, n, COMMIT_MARKER))]
    assert len(committed) == 1  # exactly one final checkpoint
    assert checkpoint_step(os.path.join(d, committed[0])) == res["completed"]


# -- Model.fit plumbing ------------------------------------------------------
def _fit_batches():
    rng = np.random.RandomState(3)
    X = rng.randn(16, 4).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.int64)
    return [(X[i:i + 4], y[i:i + 4]) for i in range(0, 16, 4)]


def _fresh_model():
    paddle.seed(321)  # identical init across runs
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    return model


def test_fit_resilient_commits_and_resumes(tmp_path):
    batches = _fit_batches()
    cfg = {"ckpt_dir": None, "ckpt_every": 3, "seed": 11}

    # golden: straight 2-epoch run (8 steps)
    m1 = _fresh_model()
    m1.fit(batches, epochs=2, verbose=0,
           resilient=dict(cfg, ckpt_dir=str(tmp_path / "a")))
    assert checkpoint_step(latest_checkpoint(str(tmp_path / "a"))) == 8

    # interrupted: 1 epoch, then a second fit resumes and finishes epoch 2
    d = str(tmp_path / "b")
    m2 = _fresh_model()
    m2.fit(batches, epochs=1, verbose=0, resilient=dict(cfg, ckpt_dir=d))
    assert checkpoint_step(latest_checkpoint(d)) == 4
    m3 = _fresh_model()
    m3.fit(batches, epochs=2, verbose=0, resilient=dict(cfg, ckpt_dir=d))
    assert checkpoint_step(latest_checkpoint(d)) == 8

    for k, v in m1._params.items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(m3._params[k]), err_msg=k)


def test_fit_resilient_resumes_lr_schedule(tmp_path):
    """Host-side optimizer state (LR scheduler position) must survive the
    restart — otherwise a resumed warmup/decay restarts at step 0."""
    d = str(tmp_path)
    batches = _fit_batches()
    paddle.seed(321)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sched = paddle.optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.5)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(sched, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    model.fit(batches, epochs=2, verbose=0,
              resilient={"ckpt_dir": d, "seed": 9})
    lr_after = model._optimizer.get_lr()  # decayed twice: 0.1 -> 0.025

    paddle.seed(321)
    net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sched2 = paddle.optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.5)
    model2 = paddle.Model(net2)
    model2.prepare(paddle.optimizer.SGD(sched2,
                                        parameters=net2.parameters()),
                   nn.CrossEntropyLoss())
    model2.fit(batches, epochs=2, verbose=0,
               resilient={"ckpt_dir": d, "seed": 9})  # resumes, trains 0
    assert model2._optimizer.get_lr() == lr_after  # schedule not reset


def test_fit_resilient_noop_when_fully_trained(tmp_path):
    d = str(tmp_path)
    batches = _fit_batches()
    m1 = _fresh_model()
    m1.fit(batches, epochs=1, verbose=0,
           resilient={"ckpt_dir": d, "seed": 5})
    w1 = {k: np.asarray(v) for k, v in m1._params.items()}
    m2 = _fresh_model()
    m2.fit(batches, epochs=1, verbose=0,
           resilient={"ckpt_dir": d, "seed": 5})  # fully fast-forwarded
    for k, v in w1.items():
        np.testing.assert_array_equal(v, np.asarray(m2._params[k]),
                                      err_msg=k)
