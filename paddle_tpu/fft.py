"""paddle.fft namespace (reference: python/paddle/fft.py — c2c/r2c/c2r
FFT surface over paddle/phi/kernels/fft_kernel). On TPU everything lowers
to XLA's FFT HLO; norm semantics ("backward"|"ortho"|"forward") match
numpy's, which is what the reference implements."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    axes = tuple(range(-jnp.asarray(x).ndim, 0)) if axes is None else axes
    out = x
    for i, ax in enumerate(axes[:-1]):
        out = jnp.fft.fft(out, n=None if s is None else s[i], axis=ax,
                          norm=norm)
    return jnp.fft.hfft(out, n=None if s is None else s[-1], axis=axes[-1],
                        norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    axes = tuple(range(-jnp.asarray(x).ndim, 0)) if axes is None else axes
    out = jnp.fft.ihfft(x, n=None if s is None else s[-1], axis=axes[-1],
                        norm=norm)
    for i, ax in enumerate(axes[:-1]):
        out = jnp.fft.ifft(out, n=None if s is None else s[i], axis=ax,
                           norm=norm)
    return out


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None, name=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    return jnp.fft.ifftshift(x, axes=axes)
