"""paddle.static equivalent (reference: python/paddle/static/ Program +
python/paddle/base/executor.py:1227 Executor/_StandaloneExecutor:844 over
the C++ PirInterpreter).

TPU design: a "static program" IS a traced computation — jax.jit's jaxpr/
StableHLO plays the role of ProgramDesc/PIR, and XLA is the interpreter
(SURVEY §7 item 10: program capture = tracing, PIR/CINN come for free).
So `Program` wraps a Python callable + named inputs; `Executor.run`
feeds by name and fetches by index, compiling once per shape — the
reference's feed/fetch surface without a separate op-by-op IR walker.
Model building happens with the same nn.Layer/functional APIs (the
reference's dygraph-to-static convergence made those identical anyway);
`program_guard` + `data` keep the classic source shape working.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from ..enforce import (InvalidArgumentError, NotFoundError,
                       OutOfRangeError, PreconditionNotMetError, enforce,
                       enforce_in, enforce_type)
import numpy as np

from ..jit.api import InputSpec

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "name_scope",
           "py_func"]

_tls = threading.local()


class Program:
    """A named-input traced program (reference: base/framework.py Program).

    Two construction styles:
      * classic: with program_guard(prog): x = static.data(...); build
        a callable via prog.set_output(fn_of_inputs) or capture outputs
        with `prog.fetch(...)`.
      * direct: Program.from_callable(fn, input_specs).
    """

    def __init__(self):
        self._inputs: List[InputSpec] = []
        self._fn: Optional[Callable] = None
        self._output_names: Optional[List[str]] = None
        self._jitted = None

    # -- classic surface -----------------------------------------------------
    def _add_input(self, spec: InputSpec):
        self._inputs.append(spec)
        return spec

    def set_output(self, fn: Callable, output_names: Optional[List[str]] = None):
        """fn(*inputs_in_declaration_order) -> output(s). output_names lets
        Executor.run fetch by name."""
        self._fn = fn
        self._output_names = list(output_names) if output_names else None
        self._jitted = None
        return self

    @classmethod
    def from_callable(cls, fn: Callable, input_specs: Sequence[InputSpec],
                      output_names: Optional[List[str]] = None) -> "Program":
        p = cls()
        p._inputs = list(input_specs)
        p._fn = fn
        p._output_names = list(output_names) if output_names else None
        return p

    # -- execution -----------------------------------------------------------
    def input_names(self) -> List[str]:
        return [s.name or f"x{i}" for i, s in enumerate(self._inputs)]

    def _compiled(self):
        if self._jitted is None:
            enforce(self._fn is not None,
                    "Program has no computation: use set_output()/"
                    "from_callable (classic op-by-op building is tracing "
                    "here — see module docstring)", op="Program",
                    error=PreconditionNotMetError)
            self._jitted = jax.jit(self._fn)
        return self._jitted

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p._inputs = list(self._inputs)
        p._fn = self._fn
        p._output_names = (list(self._output_names)
                           if self._output_names else None)
        return p

    def __repr__(self):
        return (f"Program(inputs={self.input_names()}, "
                f"traced={self._fn is not None})")


def default_main_program() -> Program:
    if not hasattr(_tls, "main"):
        _tls.main = Program()
    return _tls.main


def default_startup_program() -> Program:
    # parameter init is eager (Layer construction); kept for API parity
    if not hasattr(_tls, "startup"):
        _tls.startup = Program()
    return _tls.startup


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    prev = getattr(_tls, "main", None)
    _tls.main = main_program
    try:
        yield
    finally:
        if prev is None:
            del _tls.main
        else:
            _tls.main = prev


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0) -> InputSpec:
    """Declare a named program input (reference: static.data). Returns the
    InputSpec; the built callable receives inputs in declaration order."""
    del lod_level
    spec = InputSpec(shape, dtype, name)
    default_main_program()._add_input(spec)
    return spec


@contextlib.contextmanager
def name_scope(prefix: str):
    yield  # naming is jaxpr-internal; kept for source compatibility


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """Host callback op (reference: static.py_func). Under jit this rides
    jax.pure_callback; gradients need a PyLayer instead."""
    del backward_func, skip_vars_in_backward_input
    enforce(out is not None, "py_func needs `out` (a ShapeDtypeStruct "
            "or example array describing the result)", op="py_func")
    shape_dtype = jax.ShapeDtypeStruct(jnp.shape(out), jnp.result_type(out))
    return jax.pure_callback(func, shape_dtype, x)


class Executor:
    """Feed/fetch runner (reference: base/executor.py Executor.run)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        names = program.input_names()
        missing = [n for n in names if n not in feed]
        enforce(not missing, f"feed missing inputs {missing}; "
                f"program declares {names}", op="Executor.run",
                error=NotFoundError)
        args = [jnp.asarray(feed[n]) for n in names]
        out = program._compiled()(*args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        if fetch_list is not None:
            picked = []
            out_names = program._output_names
            for item in fetch_list:
                if isinstance(item, int):
                    enforce(item < len(outs),
                            f"fetch index {item} out of range "
                            f"({len(outs)} outputs)", op="Executor.run",
                            error=OutOfRangeError)
                    picked.append(outs[item])
                elif isinstance(item, str):
                    if out_names is not None:
                        enforce_in(item, out_names,
                                   f"unknown fetch name {item!r}; program "
                                   f"outputs are named {out_names}",
                                   op="Executor.run")
                        picked.append(outs[out_names.index(item)])
                    elif len(outs) == 1 and len(fetch_list) == 1:
                        picked.append(outs[0])  # unambiguous
                    else:
                        raise InvalidArgumentError(
                            f"cannot fetch {item!r} by name: the program "
                            f"has {len(outs)} unnamed outputs — declare "
                            f"output_names via set_output/from_callable or "
                            f"fetch by integer index", op="Executor.run")
                else:
                    enforce_type(item, (int, str), op="Executor.run",
                                 name="fetch_list entry")
            outs = picked
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return list(outs)
