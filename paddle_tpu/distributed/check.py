"""Collective correctness checks.

TPU-native equivalent of the reference's comm guards
(reference: paddle/phi/core/distributed/check/static_check.cc —
``CommStaticCheck::SameShape/ScatterLikeShape/GatherLikeShape/CheckDataType``;
paddle/phi/core/distributed/check/nccl_dynamic_check.cc —
``NCCLDynamicCheck::CheckDataType/CheckShape`` + NaN scan of comm buffers).

Design. The reference validates buffers right before launching NCCL:
*static* checks compare shapes/dtypes/ranks host-side, *dynamic* checks
broadcast rank-0's dtype/shape through the communicator and scan device
buffers for NaN. On TPU the communicator is the XLA program, so:

- **Static checks run at trace time.** Every rank traces the same program,
  so a shape/dtype mismatch *within* one program is structurally impossible;
  what can still go wrong is the eager tier (rank-major arrays whose dim 0
  must equal the group size) and cross-host disagreement about the group.
  ``check_*`` functions validate those before dispatch.
- **Dynamic NaN checks are compiled into the program.** ``nan_guard``
  wraps a value in an ``error_if`` (jax.experimental.checkify-free debug
  assert via ``jnp.isnan`` + ``lax.cond`` → ``jax.debug.print``) or, for
  eager arrays, a host-side scan — mirroring
  ``FLAGS_enable_nccl_dynamic_check``'s NaN scan of comm buffers.

Enable via ``FLAGS_enable_comm_static_check`` / ``FLAGS_enable_comm_dynamic_check``
(reference flag: FLAGS_enable_nccl_dynamic_check, paddle/common/flags.cc).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..flags import define_flag, flag

__all__ = [
    "check_same_shape", "check_scatter_like_shape", "check_gather_like_shape",
    "check_dtype", "check_rank", "nan_guard", "CommCheckError",
]

define_flag("enable_comm_static_check", False,
            "validate shapes/dtypes/group layout before eager collectives")
define_flag("enable_comm_dynamic_check", False,
            "scan collective inputs for NaN/Inf (compiled into the program)")


from ..enforce import InvalidArgumentError


class CommCheckError(InvalidArgumentError):
    """Raised when a pre-collective static check fails (typed through the
    enforce taxonomy — reference: static_check.cc uses PADDLE_ENFORCE)."""


def _shape_dtype(x):
    return tuple(getattr(x, "shape", ())), jnp.result_type(x)


def check_rank(rank: int, nranks: int) -> None:
    """(static_check.cc CheckRank) rank must be a valid group index."""
    if not 0 <= rank < nranks:
        raise CommCheckError(
            f"rank {rank} out of range for group of size {nranks}")


def check_same_shape(tensor, nranks: int, op_name: str = "collective") -> None:
    """(static_check.cc SameShape) eager rank-major input: dim 0 must equal
    the group size and every per-rank slice is implicitly identical."""
    shape, _ = _shape_dtype(tensor)
    if not shape or shape[0] != nranks:
        raise CommCheckError(
            f"{op_name}: eager input must be rank-major with dim0 == group "
            f"size {nranks}, got shape {shape}")


def check_scatter_like_shape(tensor, nranks: int, scatter_dim: int = 0,
                             op_name: str = "reduce_scatter") -> None:
    """(static_check.cc ScatterLikeShape) the scattered dim must divide
    evenly by the group size."""
    shape, _ = _shape_dtype(tensor)
    data_shape = shape[1:]
    if not data_shape or data_shape[scatter_dim] % nranks != 0:
        raise CommCheckError(
            f"{op_name}: dim {scatter_dim} of per-rank shape {data_shape} "
            f"must be divisible by group size {nranks}")


def check_gather_like_shape(out_numel: int, in_numel: int, nranks: int,
                            op_name: str = "all_gather") -> None:
    """(static_check.cc GatherLikeShape) out numel == in numel * nranks."""
    if out_numel != in_numel * nranks:
        raise CommCheckError(
            f"{op_name}: output numel {out_numel} != input numel {in_numel} "
            f"* group size {nranks}")


def check_dtype(*tensors, op_name: str = "collective") -> None:
    """(nccl_dynamic_check.cc CheckDataType) all participants agree on dtype.
    Within one traced program agreement is structural; for eager inputs we
    verify the caller didn't mix dtypes across a rank-major batch."""
    dtypes = {str(getattr(t, "dtype", None) or jnp.result_type(t))
              for t in tensors if t is not None}
    if len(dtypes) > 1:
        raise CommCheckError(f"{op_name}: mixed dtypes across participants: "
                             f"{sorted(dtypes)}")


def _host_nan_scan(x, op_name: str) -> None:
    arr = np.asarray(x)
    if arr.dtype.kind in "fc":
        bad = ~np.isfinite(arr)
        if bad.any():
            raise FloatingPointError(
                f"{op_name}: {int(bad.sum())}/{arr.size} non-finite values "
                f"in collective input (comm-buffer NaN check)")


def nan_guard(x, op_name: str = "collective"):
    """Dynamic NaN/Inf scan of a collective input.

    Traced values get a compiled guard (``jax.debug.print`` on any
    non-finite element — XLA keeps it out of the hot path when clean);
    concrete arrays get a host-side scan that raises, matching the
    reference's abort-on-NaN behaviour. Returns ``x`` unchanged so it can
    be used inline: ``psum(nan_guard(x), axis)``.
    """
    if not flag("enable_comm_dynamic_check"):
        return x
    if isinstance(x, jax.core.Tracer):
        if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
            bad = jnp.size(x) - jnp.isfinite(x).sum()
            jax.lax.cond(
                bad > 0,
                lambda: jax.debug.print(
                    "[comm-check] {op}: {n} non-finite values in input",
                    op=op_name, n=bad),
                lambda: None)
        return x
    _host_nan_scan(x, op_name)
    return x


def static_check(tensor, nranks: int, op_name: str,
                 scatter_dim: Optional[int] = None,
                 expected_dim0: Optional[int] = None) -> None:
    """Entry point used by the eager collective tier when
    ``FLAGS_enable_comm_static_check`` is on. `expected_dim0` overrides the
    rank-major dim-0 expectation for multi-process runs, where each process
    only feeds the rows its devices cover."""
    if not flag("enable_comm_static_check"):
        return
    check_same_shape(tensor, expected_dim0 if expected_dim0 is not None
                     else nranks, op_name)
    if scatter_dim is not None:
        check_scatter_like_shape(tensor, nranks, scatter_dim, op_name)
