"""Pretrained-weight zoo plumbing (reference: each vision/models/*.py
carries a model_urls dict of (bcebos URL, md5) pairs and loads through
utils.download when pretrained=True).

The URLs/md5s below are the reference's published weight artifacts (data,
not code). With no egress the loader resolves them against the local cache
(see utils.download.WEIGHTS_HOME); `pretrained` may also be a direct
path/file:// URL to a .pdparams state dict."""

from __future__ import annotations

from ...enforce import NotFoundError

MODEL_URLS = {
    "resnet18": ("https://paddle-hapi.bj.bcebos.com/models/resnet18.pdparams",
                 "cf548f46534aa3560945be4b95cd11c4"),
    "resnet34": ("https://paddle-hapi.bj.bcebos.com/models/resnet34.pdparams",
                 "8d2275cf8706028345f78ac0e1d31969"),
    "resnet50": ("https://paddle-hapi.bj.bcebos.com/models/resnet50.pdparams",
                 "ca6f485ee1ab0492d38f323885b0ad80"),
    "resnet101": ("https://paddle-hapi.bj.bcebos.com/models/resnet101.pdparams",
                  "02f35f034ca3858e1e54d4036443c92d"),
    "resnet152": ("https://paddle-hapi.bj.bcebos.com/models/resnet152.pdparams",
                  "7ad16a2f1e7333859ff986138630fd7a"),
    "resnext50_32x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext50_32x4d.pdparams",
        "dc47483169be7d6f018fcbb7baf8775d"),
    "resnext50_64x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext50_64x4d.pdparams",
        "063d4b483e12b06388529450ad7576db"),
    "resnext101_32x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext101_32x4d.pdparams",
        "967b090039f9de2c8d06fe994fb9095f"),
    "resnext101_64x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext101_64x4d.pdparams",
        "98e04e7ca616a066699230d769d03008"),
    "resnext152_32x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext152_32x4d.pdparams",
        "18ff0beee21f2efc99c4b31786107121"),
    "resnext152_64x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext152_64x4d.pdparams",
        "77c4af00ca42c405fa7f841841959379"),
    "wide_resnet50_2": (
        "https://paddle-hapi.bj.bcebos.com/models/wide_resnet50_2.pdparams",
        "0282f804d73debdab289bd9fea3fa6dc"),
    "wide_resnet101_2": (
        "https://paddle-hapi.bj.bcebos.com/models/wide_resnet101_2.pdparams",
        "d4360a2d23657f059216f5d5a1a9ac93"),
}


def load_pretrained(model, arch: str, pretrained):
    """Apply zoo weights when requested. `pretrained` forms: False (no-op),
    True (registered URL for `arch`), or a path / file:// / http(s) URL."""
    if not pretrained:
        return model
    from ...utils.download import load_dict_from_url

    if isinstance(pretrained, str):
        sd = load_dict_from_url(pretrained)
    else:
        if arch not in MODEL_URLS:
            raise NotFoundError(
                f"no registered pretrained weights for '{arch}'; pass a "
                f"path or URL as pretrained=", op="load_pretrained")
        url, md5 = MODEL_URLS[arch]
        sd = load_dict_from_url(url, md5)
    model.set_state_dict(sd)
    return model
