"""Quantization (reference: python/paddle/quantization/ — QAT fake-quant
framework, PTQ observers; kernels paddle/phi/kernels/.../quantize_*).

TPU design: fake-quant as straight-through-estimator ops (custom_vjp),
QuantConfig + QAT wrapper inserting FakeQuant layers around Linear/Conv;
PTQ observers collect absmax ranges. Round 2 adds REAL int8 execution
(quantize_to_int8 / int8_matmul / qlinear / QuantizedLinear): int8×int8
→int32 on the v5e MXU via preferred_element_type, measured 1.26× the
bf16 rate at large shapes (BASELINE.md).

Scale convention (ONE convention module-wide): scale = absmax, integer
value q ≈ x·qmax/scale, dequant = q·scale/qmax — what absmax_scale /
quantize_weights / dequantize and the int8 execution path all share.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer

__all__ = ["fake_quant", "dequantize", "quantize_weights", "AbsmaxObserver",
           "HistObserver", "FakeQuant", "QuantConfig", "QAT", "PTQ"]


def absmax_scale(x):
    """Symmetric per-tensor scale — THE quantization range used by fake-
    quant, weight quantization and observers alike (one floor constant)."""
    return jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)


@jax.custom_vjp
def fake_quant(x, scale, bits=8):
    qmax = 2.0 ** (bits - 1) - 1
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _fq_fwd(x, scale, bits=8):
    return fake_quant(x, scale, bits), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # straight-through: pass gradient inside the clip range, zero outside
    inside = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quantize_weights(w, bits: int = 8):
    """Symmetric per-tensor int quantization. Returns (int_values, scale)."""
    qmax = 2.0 ** (bits - 1) - 1
    scale = absmax_scale(w)
    q = jnp.clip(jnp.round(w / scale * qmax), -qmax, qmax).astype(jnp.int8)
    return q, scale


def dequantize(q, scale, bits: int = 8):
    qmax = 2.0 ** (bits - 1) - 1
    return q.astype(jnp.float32) * scale / qmax


class AbsmaxObserver:
    """PTQ range observer (reference: quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        self.bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(x))))
        return x

    @property
    def scale(self) -> float:
        return max(self._absmax, 1e-8)


class HistObserver:
    """Percentile histogram observer (reference:
    quantization/observers/hist.py): accumulates a fixed-bin histogram of
    |x| with range-doubling rebinning and picks the scale at the `percent`
    quantile of observed mass — robust to the activation outliers an
    absmax observer chases (one spike would otherwise blow up the scale
    and crush resolution for the bulk)."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048,
                 percent: float = 0.999):
        import numpy as _np
        self.bits = quant_bits
        self.bins = bins
        self.percent = percent
        self._hist = _np.zeros(bins, _np.float64)
        self._range = 0.0

    def observe(self, x):
        import numpy as _np
        ax = _np.abs(_np.asarray(jax.device_get(x), _np.float32)).ravel()
        top = float(ax.max()) if ax.size else 0.0
        if top > self._range:
            # double the range until it covers, merging bin pairs so the
            # accumulated mass survives the re-binning
            new_range = max(self._range, 1e-8)
            while new_range < top:
                new_range *= 2.0
                half = self._hist.reshape(-1, 2).sum(axis=1)
                self._hist = _np.concatenate(
                    [half, _np.zeros(self.bins // 2)])
            self._range = new_range
        if self._range > 0 and ax.size:
            h, _ = _np.histogram(ax, bins=self.bins,
                                 range=(0.0, self._range))
            self._hist += h
        return x

    @property
    def scale(self) -> float:
        import numpy as _np
        total = self._hist.sum()
        if total <= 0:
            return 1e-8
        c = _np.cumsum(self._hist) / total
        idx = int(_np.searchsorted(c, self.percent))
        return max((idx + 1) / self.bins * self._range, 1e-8)


_OBSERVER_TYPES = {"abs_max": AbsmaxObserver, "hist": HistObserver}


class FakeQuant(Layer):
    """QAT fake-quant node with a learned-from-data running scale."""

    def __init__(self, bits: int = 8, momentum: float = 0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.register_buffer("scale", jnp.asarray(1.0))

    def forward(self, x):
        if self.training:
            cur = absmax_scale(x)
            new = self.momentum * self.scale + (1 - self.momentum) * cur
            self.scale = new
        return fake_quant(x, jnp.asarray(self.scale), self.bits)


class QuantConfig:
    """(reference: quantization/config.py) — which layer types to quantize
    and with how many bits."""

    def __init__(self, activation_bits: int = 8, weight_bits: int = 8,
                 quantizable_layer_type=("Linear", "Conv2D")):
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self.types = tuple(quantizable_layer_type)


class _QuantWrapper(Layer):
    def __init__(self, inner: Layer, cfg: QuantConfig):
        super().__init__()
        self.inner = inner
        self.act_q = FakeQuant(cfg.activation_bits)
        self.w_bits = cfg.weight_bits

    def forward(self, x):
        x = self.act_q(x)
        w = self.inner.weight.value
        scale = absmax_scale(w)
        orig = w
        self.inner.weight.value = fake_quant(w, scale, self.w_bits)
        try:
            out = self.inner(x)
        finally:
            self.inner.weight.value = orig
        return out


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py
    QAT.quantize wraps eligible layers with fake-quant)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer) -> Layer:
        def convert(layer: Layer) -> Layer:
            for name, sub in list(layer._sub_layers.items()):
                if type(sub).__name__ in self.config.types and hasattr(
                        sub, "weight"):
                    layer._sub_layers[name] = _QuantWrapper(sub, self.config)
                else:
                    convert(sub)
            return layer
        return convert(model)

    def convert(self, model: Layer) -> Dict[str, tuple]:
        """Produce deploy weights: {param_name: (int8_values, scale)}."""
        out = {}
        for name, p in model.named_parameters():
            if p.value.ndim >= 2:
                out[name] = quantize_weights(p.value,
                                             self.config.weight_bits)
        return out


class _Observed(Layer):
    def __init__(self, inner, name, observer):
        super().__init__()
        self.inner = inner
        self._obs_name = name
        self._observer = observer

    def forward(self, x):
        self._observer.observe(x)
        return self.inner(x)


class PTQ:
    """Post-training quantization (reference: quantization/ptq.py):
    `quantize(model)` wraps eligible layers with activation observers,
    calibration forwards populate them, and `convert(model)` replaces each
    observed Linear with a W8A8 QuantizedLinear whose ACTIVATION scale is
    the calibrated one (static quantization — no per-call absmax at
    deploy time). observer: "abs_max" or "hist" (percentile)."""

    def __init__(self, config: Optional[QuantConfig] = None,
                 observer: str = "abs_max", **observer_kw):
        from ..enforce import enforce_in
        self.config = config or QuantConfig()
        enforce_in(observer, set(_OBSERVER_TYPES), op="PTQ",
                   observer=observer)
        self._obs_cls = _OBSERVER_TYPES[observer]
        self._obs_kw = observer_kw
        self.observers: Dict[str, object] = {}

    def quantize(self, model: Layer) -> Layer:
        def convert(layer: Layer, prefix=""):
            for name, sub in list(layer._sub_layers.items()):
                path = f"{prefix}.{name}" if prefix else name
                if type(sub).__name__ in self.config.types:
                    obs = self._obs_cls(self.config.activation_bits,
                                        **self._obs_kw)
                    self.observers[path] = obs
                    layer._sub_layers[name] = _Observed(sub, path, obs)
                else:
                    convert(sub, path)
            return layer
        return convert(model)

    def scales(self) -> Dict[str, float]:
        return {k: o.scale for k, o in self.observers.items()}

    def convert(self, model: Layer) -> Layer:
        """Calibrated deploy conversion: every observed Linear becomes a
        QuantizedLinear with static activation scale from its observer
        (per-output-channel weight scales). Non-Linear observed layers are
        unwrapped (their scales remain available via scales())."""
        from ..nn.layer.common import Linear

        def walk(layer: Layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, _Observed):
                    inner = sub.inner
                    if isinstance(inner, Linear):
                        layer._sub_layers[name] = (
                            QuantizedLinear.from_linear(
                                inner, act_scale=sub._observer.scale))
                    else:
                        layer._sub_layers[name] = inner
                else:
                    walk(sub)
            return layer
        return walk(model)


# ---------------------------------------------------------------------------
# real int8 execution (round 2) — reference: paddle/phi/kernels/fusion/gpu
# quant_dequant + int8 matmul kernels (fused_multi_transformer_int8 etc.).
# On v5e the MXU runs int8 x int8 -> int32 at 2x the bf16 rate; this is the
# TPU-native int8 path, not a fake-quant simulation.
# ---------------------------------------------------------------------------

def quantize_to_int8(x, scale=None, axis=None):
    """Symmetric int8 quantization in the module's absmax convention
    (scale = absmax; dequant = q*scale/127 — interchangeable with
    quantize_weights/dequantize/observer scales). Pass axis= for
    per-channel scales computed here."""
    if scale is None:
        if axis is None:
            scale = absmax_scale(x)
        else:
            red = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
            scale = jnp.maximum(
                jnp.max(jnp.abs(x), axis=red, keepdims=True), 1e-8)
    q = jnp.clip(jnp.round(x / scale * 127.0), -127, 127).astype(jnp.int8)
    return q, scale


def int8_matmul(x_q, w_q, x_scale, w_scale, out_dtype=jnp.float32,
                psum_axis=None):
    """int8 @ int8 with int32 accumulation on the MXU, dequantized by the
    product of scales. x_scale: scalar (per-tensor); w_scale: scalar or
    per-output-channel (broadcasts on the last dim). psum_axis: for
    row-parallel (contraction-sharded) TP matmuls — psum the INT32
    accumulator across the axis before dequantizing, so the sharded
    product is bit-identical to the dense one (int32 partial sums are
    exact; a float psum of dequantized partials would reassociate the
    rounding)."""
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    if psum_axis is not None:
        acc = jax.lax.psum(acc, psum_axis)
    ws = jnp.reshape(jnp.asarray(w_scale), (-1,))  # [out] or [1]
    return (acc.astype(jnp.float32)
            * (jnp.asarray(x_scale) / 127.0) * (ws / 127.0)
            ).astype(out_dtype)


def qlinear(x, w_q, w_scale, bias=None, out_dtype=None, per_row=False,
            psum_axis=None):
    """Dynamic-activation-quant linear: quantize x per call, run the int8
    MXU matmul, dequantize (W8A8 dynamic — the llm.int8-style serving
    path). per_row=True scales each row (reduce only the contraction
    dim) instead of the whole tensor — REQUIRED when x batches
    independent requests (continuous batching): a per-tensor absmax would
    make one request's quantization grid depend on its co-scheduled
    batchmates' outliers. psum_axis: row-parallel TP — the per-row
    activation absmax is SHARED across the axis (pmax), each shard
    quantizes its slice on the common grid, and the int32 accumulator is
    psum'd before dequantization (see int8_matmul) — the sharded linear
    reproduces the dense int8 linear exactly."""
    out_dtype = out_dtype or x.dtype
    if per_row:
        x_scale = jnp.maximum(
            jnp.max(jnp.abs(x), axis=-1, keepdims=True), 1e-8)
        if psum_axis is not None:
            x_scale = jax.lax.pmax(x_scale, psum_axis)
        x_q, _ = quantize_to_int8(x, scale=x_scale)
    elif psum_axis is not None:
        x_scale = jax.lax.pmax(absmax_scale(x), psum_axis)
        x_q, _ = quantize_to_int8(x, scale=x_scale)
    else:
        x_q, x_scale = quantize_to_int8(x)
    out = int8_matmul(x_q, w_q, x_scale, w_scale, out_dtype=jnp.float32,
                      psum_axis=psum_axis)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype)


class QuantizedLinear(Layer):
    """Weight-only-storage / W8A8-compute linear (reference:
    fused int8 matmul kernels). Construct from a trained Linear via
    from_linear(); weights live as int8 + per-output-channel scales.
    act_scale: optional STATIC activation scale (PTQ-calibrated) — when
    absent, activations quantize dynamically per call (absmax)."""

    def __init__(self, w_q, w_scale, bias=None, act_scale=None):
        super().__init__()
        self.register_buffer("w_q", w_q)
        self.register_buffer("w_scale", jnp.reshape(w_scale, (-1,)))
        if act_scale is not None:
            self.register_buffer("act_scale", jnp.asarray(act_scale))
        else:
            self.act_scale = None
        if bias is not None:
            self.register_buffer("bias", bias)
        else:
            self.bias = None

    @classmethod
    def from_linear(cls, linear, act_scale=None):
        w = jnp.asarray(linear.weight.value)  # [in, out]
        w_q, w_scale = quantize_to_int8(w, axis=1)
        b = (jnp.asarray(linear.bias.value)
             if getattr(linear, "bias", None) is not None else None)
        return cls(w_q, w_scale, b, act_scale=act_scale)

    def forward(self, x):
        if self.act_scale is not None:
            x_q, _ = quantize_to_int8(x, scale=self.act_scale)
            out = int8_matmul(x_q, self.w_q, self.act_scale, self.w_scale,
                              out_dtype=jnp.float32)
            if self.bias is not None:
                out = out + self.bias.astype(jnp.float32)
            return out.astype(x.dtype)
        return qlinear(x, self.w_q, self.w_scale, self.bias)


__all__ += ["quantize_to_int8", "int8_matmul", "qlinear", "QuantizedLinear"]


def convert_to_int8(model, skip=()):
    """Replace every nn.Linear in `model` (in place, recursively) with a
    W8A8 QuantizedLinear built from its trained weights — the deploy-time
    int8 path the reference reaches through fused int8 kernels +
    config.enable_tensorrt_engine(precision_mode=Int8). `skip`: substring
    names to leave in fp (e.g. ("head",) for a sensitive output layer).
    Returns the model."""
    from ..nn.layer.common import Linear

    def walk(layer, prefix=""):
        for name, sub in list(layer._sub_layers.items()):
            full = f"{prefix}.{name}" if prefix else name
            if any(s in full for s in skip):
                continue
            if isinstance(sub, Linear):
                layer._sub_layers[name] = QuantizedLinear.from_linear(sub)
            else:
                walk(sub, full)

    walk(model)
    return model


__all__ += ["convert_to_int8"]

# delayed-scaling e4m3/e5m2 TRAINING tier (fp8_dot / Fp8Linear / amax-meta
# state) — submodule import only: fp8.py is jax-pure and must stay
# importable from the functional model paths without the Layer surface
from . import fp8  # noqa: E402,F401

__all__ += ["fp8"]
