"""Round-2 tensor-op surface: the ~80 reference ops the round-1 survey
left uncovered (reference: paddle/phi/ops/yaml/ops.yaml op schemas;
python/paddle/tensor/{math,manipulation,linalg,search,logic}.py).

Same design stance as tensor/__init__.py: the Python signature IS the op
schema and jnp/lax/jax.scipy ARE the kernels — every function here lowers
to XLA ops that fuse and shard like any other traced computation. Golden
tests: tests/test_op_golden.py (round-2 section)."""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..random import next_key

__all__: List[str] = []


def _public(fn):
    __all__.append(fn.__name__)
    return fn


# ------------------------------------------------------------- matmul family

@_public
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * (x @ y)


@_public
def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * jnp.matmul(x, y)


@_public
def multi_dot(tensors, name=None):
    return jnp.linalg.multi_dot(tensors)


@_public
def tensordot(x, y, axes=2, name=None):
    return jnp.tensordot(x, y, axes=axes)


@_public
def vdot(x, y, name=None):
    return jnp.vdot(x, y)


# ---------------------------------------------------------------- elementwise

@_public
def deg2rad(x, name=None):
    return jnp.deg2rad(x)


@_public
def rad2deg(x, name=None):
    return jnp.rad2deg(x)


@_public
def floor_mod(x, y, name=None):
    return jnp.mod(x, y)


@_public
def frexp(x, name=None):
    return jnp.frexp(x)


@_public
def gcd(x, y, name=None):
    return jnp.gcd(x, y)


@_public
def lcm(x, y, name=None):
    return jnp.lcm(x, y)


@_public
def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


@_public
def hypot(x, y, name=None):
    return jnp.hypot(x, y)


@_public
def copysign(x, y, name=None):
    return jnp.copysign(x, y)


@_public
def ldexp(x, y, name=None):
    return jnp.ldexp(x, y)


@_public
def nextafter(x, y, name=None):
    return jnp.nextafter(x, y)


@_public
def sinc(x, name=None):
    return jnp.sinc(x)


@_public
def signbit(x, name=None):
    return jnp.signbit(x)


@_public
def sgn(x, name=None):
    """sign for real; x/|x| (or 0) for complex (reference paddle.sgn)."""
    if jnp.iscomplexobj(x):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


@_public
def increment(x, value=1.0, name=None):
    return x + value


@_public
def gammaln(x, name=None):
    return jax.scipy.special.gammaln(x)


@_public
def gammainc(x, y, name=None):
    return jax.scipy.special.gammainc(x, y)


@_public
def gammaincc(x, y, name=None):
    return jax.scipy.special.gammaincc(x, y)


@_public
def multigammaln(x, p, name=None):
    return jax.scipy.special.multigammaln(x, p)


@_public
def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, x)


@_public
def i0(x, name=None):
    return jax.scipy.special.i0(x)


@_public
def i0e(x, name=None):
    return jax.scipy.special.i0e(x)


@_public
def i1(x, name=None):
    return jax.scipy.special.i1(x)


@_public
def i1e(x, name=None):
    return jax.scipy.special.i1e(x)


@_public
def logcumsumexp(x, axis=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return lax.cumlogsumexp(x, axis=axis)


# ------------------------------------------------------------- complex views

@_public
def complex(real, imag, name=None):
    return lax.complex(jnp.asarray(real), jnp.asarray(imag))


@_public
def polar(abs, angle, name=None):
    return lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


@_public
def is_complex(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.complexfloating)


@_public
def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


@_public
def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


@_public
def isreal(x, name=None):
    if is_complex(x):
        return jnp.imag(x) == 0
    return jnp.ones(jnp.asarray(x).shape, bool)


@_public
def is_tensor(x):
    return isinstance(x, (jax.Array, np.ndarray))


# ------------------------------------------------------------ predicates etc.

@_public
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return jnp.isin(x, test_x, assume_unique=assume_unique, invert=invert)


@_public
def isneginf(x, name=None):
    return jnp.isneginf(x)


@_public
def isposinf(x, name=None):
    return jnp.isposinf(x)


@_public
def rank(x):
    return jnp.asarray(jnp.asarray(x).ndim)


# -------------------------------------------------------------- manipulation

@_public
def broadcast_tensors(inputs, name=None):
    return list(jnp.broadcast_arrays(*inputs))


@_public
def diagflat(x, offset=0, name=None):
    return jnp.diagflat(x, k=offset)


@_public
def fliplr(x, name=None):
    return jnp.fliplr(x)


@_public
def flipud(x, name=None):
    return jnp.flipud(x)


@_public
def hsplit(x, num_or_indices, name=None):
    return jnp.hsplit(x, num_or_indices)


@_public
def vsplit(x, num_or_indices, name=None):
    return jnp.vsplit(x, num_or_indices)


@_public
def dsplit(x, num_or_indices, name=None):
    return jnp.dsplit(x, num_or_indices)


@_public
def hstack(x, name=None):
    return jnp.hstack(x)


@_public
def vstack(x, name=None):
    return jnp.vstack(x)


@_public
def dstack(x, name=None):
    return jnp.dstack(x)


@_public
def tensor_split(x, num_or_indices, axis=0, name=None):
    return jnp.array_split(x, num_or_indices, axis=axis)


@_public
def unflatten(x, axis, shape, name=None):
    axis = axis % x.ndim
    new = x.shape[:axis] + tuple(shape) + x.shape[axis + 1:]
    return x.reshape(new)


@_public
def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis` (reference paddle.unfold on tensors /
    torch.Tensor.unfold): output gains a trailing window dim."""
    axis = axis % x.ndim
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None, :]  # [n, size]
    out = jnp.take(x, idx.reshape(-1), axis=axis)
    out = out.reshape(x.shape[:axis] + (n, size) + x.shape[axis + 1:])
    # trailing window dim (torch/paddle convention)
    return jnp.moveaxis(out, axis + 1, -1)


@_public
def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, n, axis=axis)]


@_public
def vander(x, n=None, increasing=False, name=None):
    return jnp.vander(x, N=n, increasing=increasing)


@_public
def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return x.reshape(shape_or_dtype)
    return lax.bitcast_convert_type(x, shape_or_dtype)


@_public
def view_as(x, other, name=None):
    return x.reshape(other.shape)


@_public
def take(x, index, mode="raise", name=None):
    jmode = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return jnp.take(x.reshape(-1), index, mode=jmode)


@_public
def combinations(x, r=2, with_replacement=False, name=None):
    n = x.shape[0]
    gen = (itertools.combinations_with_replacement(range(n), r)
           if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(gen), dtype=np.int32).reshape(-1, r)
    return x[idx]


@_public
def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(jnp.dtype(dtype) if dtype != "int64"
                                    else jnp.int32)


@_public
def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(jnp.dtype(dtype) if dtype != "int64"
                                    else jnp.int32)


# --------------------------------------------------------- scatter-style ops

@_public
def index_fill(x, index, axis, value, name=None):
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].set(value)
    return jnp.moveaxis(moved, 0, axis)


@_public
def select_scatter(x, values, axis, index, name=None):
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].set(values)
    return jnp.moveaxis(moved, 0, axis)


@_public
def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sd)
    return x.at[tuple(idx)].set(value)


@_public
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto the (offset, axis1, axis2) diagonal of x."""
    a1, a2 = axis1 % x.ndim, axis2 % x.ndim
    moved = jnp.moveaxis(x, (a1, a2), (-2, -1))
    n, m = moved.shape[-2], moved.shape[-1]
    if offset >= 0:
        rows = jnp.arange(min(n, m - offset))
        cols = rows + offset
    else:
        cols = jnp.arange(min(m, n + offset))
        rows = cols - offset
    moved = moved.at[..., rows, cols].set(y)
    return jnp.moveaxis(moved, (-2, -1), (a1, a2))


@_public
def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    n = min(x.shape[-2], x.shape[-1] - offset) if offset >= 0 else \
        min(x.shape[-1], x.shape[-2] + offset)
    return diagonal_scatter(
        x, jnp.full(x.shape[:-2] + (n,), value, x.dtype), offset,
        x.ndim - 2, x.ndim - 1)


@_public
def masked_scatter(x, mask, value, name=None):
    """Fill True positions of mask with consecutive elements of value
    (row-major), like the reference masked_scatter."""
    mask = jnp.broadcast_to(mask, x.shape)
    flat_m = mask.reshape(-1)
    # position i takes value[#True before i]
    src_idx = jnp.cumsum(flat_m) - 1
    vals = jnp.take(value.reshape(-1), jnp.clip(src_idx, 0, None),
                    mode="clip")
    out = jnp.where(flat_m, vals.astype(x.dtype), x.reshape(-1))
    return out.reshape(x.shape)


# --------------------------------------------------------------- reductions

@_public
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


@_public
def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


@_public
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@_public
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@_public
def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


@_public
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if dx is None and x is None:
        dx = 1.0
    return jnp.trapezoid(y, x=x, dx=dx if dx is not None else 1.0,
                         axis=axis)


@_public
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoid rule along axis (reference:
    paddle.cumulative_trapezoid)."""
    y = jnp.asarray(y)
    ym = jnp.moveaxis(y, axis, -1)
    avg = (ym[..., 1:] + ym[..., :-1]) * 0.5
    if x is not None:
        xm = jnp.moveaxis(jnp.asarray(x), axis, -1) if jnp.asarray(x).ndim \
            else jnp.asarray(x)
        d = jnp.diff(xm, axis=-1) if jnp.asarray(x).ndim else x
        avg = avg * d
    else:
        avg = avg * (1.0 if dx is None else dx)
    return jnp.moveaxis(jnp.cumsum(avg, axis=-1), -1, axis)


@_public
def renorm(x, p, axis, max_norm, name=None):
    """Clamp each slice along `axis` to p-norm <= max_norm."""
    axis = axis % x.ndim
    red = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=red, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * scale


# ------------------------------------------------------------------ search

@_public
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    out = jnp.searchsorted(sorted_sequence, x,
                           side="right" if right else "left")
    return out.astype(jnp.int32) if out_int32 else out


@_public
def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    rng = None if (min == 0 and max == 0) else (min, max)
    return jnp.histogram_bin_edges(x, bins=bins, range=rng)


@_public
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    return jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                           weights=weights)


# ------------------------------------------------------------ random inplace
# (the reference's *_ inplace random ops return the refilled tensor; under
# a functional runtime "inplace" means "same shape/dtype, new value")

@_public
def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    return jax.random.uniform(next_key(), x.shape, x.dtype, min, max)


@_public
def geometric_(x, probs, name=None):
    u = jax.random.uniform(next_key(), x.shape, jnp.float32, 1e-7, 1.0)
    return (jnp.floor(jnp.log(u) / jnp.log1p(-probs)) + 1).astype(x.dtype)


@_public
def zero_(x, name=None):
    return jnp.zeros_like(x)


@_public
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return jnp.logspace(start, stop, int(num), base=base, dtype=dtype)


@_public
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-distance between row vectors ([..., M, D] x [..., N, D]
    -> [..., M, N]). For p=2 (unless compute_mode forbids it) the matmul
    expansion ||x||^2 + ||y||^2 - 2 x@y^T keeps memory O(M*N) instead of
    materializing the [M, N, D] difference tensor — and puts the FLOPs on
    the MXU."""
    if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
        x2 = jnp.sum(x * x, axis=-1)[..., :, None]
        y2 = jnp.sum(y * y, axis=-1)[..., None, :]
        xy = jnp.matmul(x, jnp.swapaxes(y, -2, -1))
        return jnp.sqrt(jnp.maximum(x2 + y2 - 2.0 * xy, 0.0))
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 0.0))
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
