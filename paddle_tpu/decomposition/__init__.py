"""Composite-op decomposition registry.

TPU-native equivalent of the reference's prim/primitive subsystem
(reference: python/paddle/decomposition/{register,rules,decomp}.py —
registry of rules decomposing big ops into primitive ops;
paddle/fluid/primitive/composite/ C++ composite rules;
paddle/fluid/primitive/rule/vjp/ VJP rules).

On TPU most of this is free: jax traces every composite op down to lax
primitives, and higher-order AD (the reference's motivation for
decomposition) works through any jnp composition. What remains useful —
and what this module provides — is:

* an explicit registry of decomposition rules in *pure lax/jnp primitive
  form* (no fused/opaque ops), so compiler passes and numerics audits can
  substitute a transparent form for any composite op;
* ``decompose(...)``: a context manager that swaps registered ops'
  implementations for their primitive rules inside the block (whitelist /
  blacklist semantics mirroring the reference's ``decompose(program,
  whitelist, blacklist)``);
* ``call_decomp(name, *args)`` for direct rule invocation in tests.

VJP rules come for free via jax.grad over the rule body, mirroring how the
reference derives higher-order AD from composite rules.
"""

from __future__ import annotations

import contextlib
import math
from typing import Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.registry import _OPS

__all__ = ["register_decomp", "has_decomp", "get_decomp_rule", "call_decomp",
           "list_decomps", "decompose"]

_RULES: Dict[str, Callable] = {}


def register_decomp(name: str):
    """(reference: decomposition/register.py register_decomp) Register a
    primitive-form rule for composite op ``name``."""

    def deco(fn: Callable):
        _RULES[name] = fn
        return fn

    return deco


def has_decomp(name: str) -> bool:
    return name in _RULES


def get_decomp_rule(name: str) -> Callable:
    return _RULES[name]


def call_decomp(name: str, *args, **kwargs):
    return _RULES[name](*args, **kwargs)


def list_decomps() -> List[str]:
    return sorted(_RULES)


def _functional_modules():
    from ..nn import functional as F
    from ..nn.functional import activation, norm
    return (F, activation, norm)


@contextlib.contextmanager
def decompose(whitelist: Optional[Iterable[str]] = None,
              blacklist: Optional[Iterable[str]] = None):
    """Substitute primitive-form rules for composite ops inside the block
    (reference: decomposition/decomp.py decompose — rewrites a program;
    here the rewrite happens at trace time by rerouting the two dispatch
    surfaces: the op registry — with Pallas fast paths suppressed — and the
    ``nn.functional`` module attributes, so both registry-dispatched ops and
    plain functional calls hit the primitive rule).

    Callers holding a direct ``from ... import softmax`` reference bound
    before the block keep the original implementation — reroute applies to
    module-attribute lookups (``F.softmax(...)``), the idiom every layer in
    this framework uses.
    """
    black = set(blacklist or ())
    requested = list(_RULES if whitelist is None else whitelist)
    names = [n for n in requested if n in _RULES and n not in black]
    missing = [n for n in requested if n not in _RULES]
    if missing:
        raise KeyError(f"no decomposition rule registered for {missing}; "
                       f"known rules: {list_decomps()}")
    saved_ops = {}
    saved_pallas = {}
    saved_attrs = []  # (module, attr, original)
    mods = _functional_modules()
    try:
        for n in names:
            rule = _RULES[n]
            if n in _OPS:
                saved_ops[n] = _OPS[n].fn
                saved_pallas[n] = _OPS[n].pallas_impl
                _OPS[n].fn = rule
                _OPS[n].pallas_impl = None  # rule must win over fast paths
            for mod in mods:
                orig = getattr(mod, n, None)
                if callable(orig) and orig is not rule:
                    saved_attrs.append((mod, n, orig))
                    setattr(mod, n, rule)
        yield
    finally:
        for n, fn in saved_ops.items():
            _OPS[n].fn = fn
            _OPS[n].pallas_impl = saved_pallas[n]
        for mod, attr, orig in saved_attrs:
            setattr(mod, attr, orig)


# ---------------------------------------------------------------------------
# rules (reference: python/paddle/decomposition/rules.py; each body uses only
# lax / elementwise jnp primitives so the decomposed program is transparent
# to passes and supports arbitrary-order AD)
# ---------------------------------------------------------------------------
@register_decomp("softmax")
def _softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    m = lax.stop_gradient(jnp.max(x, axis=axis, keepdims=True))
    e = jnp.exp(x - m)
    return e / jnp.sum(e, axis=axis, keepdims=True)


@register_decomp("log_softmax")
def _log_softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    m = lax.stop_gradient(jnp.max(x, axis=axis, keepdims=True))
    s = x - m
    return s - jnp.log(jnp.sum(jnp.exp(s), axis=axis, keepdims=True))


@register_decomp("sigmoid")
def _sigmoid(x):
    return 0.5 * (jnp.tanh(0.5 * x) + 1.0)


@register_decomp("silu")
def _silu(x):
    return x * _sigmoid(x)


@register_decomp("gelu")
def _gelu(x, approximate=False):
    if approximate:
        c = jnp.sqrt(2.0 / jnp.pi).astype(x.dtype)
        return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))
    return 0.5 * x * (1.0 + lax.erf(x / jnp.sqrt(2.0).astype(x.dtype)))


@register_decomp("layer_norm")
def _layer_norm(x, normalized_shape=None, weight=None, bias=None,
                epsilon=1e-5, name=None):
    del name
    if normalized_shape is None:
        axes = (-1,)
    else:
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        axes = tuple(range(-len(normalized_shape), 0))
    mu = jnp.mean(x, axis=axes, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=axes, keepdims=True)
    y = xc * lax.rsqrt(var + epsilon)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


@register_decomp("rms_norm")
def _rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim)) \
        if begin_norm_axis not in (-1, x.ndim - 1) else (-1,)
    ms = jnp.mean(x * x, axis=axes, keepdims=True)
    y = x * lax.rsqrt(ms + epsilon)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


@register_decomp("mean")
def _mean(x, axis=None, keepdim=False):
    if axis is None:
        n = x.size
    elif isinstance(axis, (tuple, list)):
        n = math.prod(x.shape[a] for a in axis)
    else:
        n = x.shape[axis]
    return jnp.sum(x, axis=axis, keepdims=keepdim) / n


@register_decomp("squared_l2_norm")
def _squared_l2_norm(x):
    return jnp.sum(x * x)


@register_decomp("softplus")
def _softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(beta * x > threshold, x,
                     jnp.logaddexp(beta * x, 0.0) / beta)


@register_decomp("swiglu")
def _swiglu(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return _silu(x) * y
