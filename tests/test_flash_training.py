"""Pallas flash attention in the training hot path (ISSUE 12).

Covers: direct kernel fwd/bwd parity vs the composed einsum (fp32 +
bf16), flags-off bitwise-identical lowered HLO, multi-step hybrid-loss
parity on the dp2·pp2·mp2 virtual mesh (50-step acceptance run in the
slow tier), bitwise equality of the remat modes (full replay vs
FLASH_REMAT_NAMES selective reuse), the jaxpr-level kernel-presence /
scores-absence assertions (a silent fallback to the composed path cannot
pass), the compose matrix (sp/ring mp-overlap × zero1 × {1F1B, ZBH1,
VPP} × fp8 GEMMs), the sep context-parallel legs (ring vs Ulysses,
single-process), MoE + llama legs, the sep/ulysses refusals, and the
planner's flash axis (validity, prune reasons, long-S activation-HBM
drop, honest compute cost).

Parity tolerance note: the fused kernel computes the same softmax
attention as the composed path but with online (tiled) normalization —
fp32 trajectories agree to reassociation noise (measured ≤1e-6 rel over
8 steps, the first steps usually bit-equal on this toy), never
guaranteed bit-for-bit. The bitwise guarantees of this PR are (a) the
OFF path — flash_attention=None/flags-off compiles byte-identical HLO —
and (b) ACROSS REMAT MODES with flash on: replaying the deterministic
kernel equals reusing its saved residuals exactly.

CPU tier-1 runs the kernels in interpreter mode (kernels.pallas._common)
— the whole matrix is testable off-TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.enforce import EnforceNotMet
from paddle_tpu.kernels.pallas import flash_attention as fa
from paddle_tpu.kernels.pallas import flash_training as ft
from paddle_tpu.models import gpt as G
from paddle_tpu.models import llama as L

from hlo_utils import attention_scores_dots, pallas_call_count

CFG = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
                  max_seq_len=64, dtype=jnp.float32)
SEQ = 64
LR = jnp.float32(1e-2)


def _data(batch=8, seq=SEQ, vocab=None, seed=0):
    rng = np.random.RandomState(seed)
    v = vocab or CFG.vocab_size
    return (jnp.asarray(rng.randint(0, v, (batch, seq))),
            jnp.asarray(rng.randint(0, v, (batch, seq))))


def _run_gpt(mesh, flash, steps, cfg=CFG, microbatches=2, **kw):
    opt = paddle.optimizer.AdamW(1e-2)
    step, shard, init = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=microbatches,
        flash_attention=flash, **kw)
    p = shard(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    s = init(p)
    tokens, labels = _data(vocab=cfg.vocab_size)
    losses = []
    for _ in range(steps):
        p, s, loss = step(p, s, tokens, labels, LR)
        losses.append(float(loss))
    return losses


def _max_rel(a, b):
    return max(abs(x - y) / max(abs(x), 1e-12) for x, y in zip(a, b))


def _composed(q, k, v):
    """The reference O(S²) causal attention (gpt._attention math)."""
    import math
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    S = logits.shape[-1]
    logits = jnp.where(jnp.tril(jnp.ones((S, S), bool)), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# Kernel level
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype,tol_o,tol_g",
                         [(jnp.float32, 2e-5, 2e-4),
                          (jnp.bfloat16, 2e-2, 5e-2)],
                         ids=["fp32", "bf16"])
def test_flash_matches_composed_fwd_bwd(dtype, tol_o, tol_g):
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(2, 128, 4, 16)).astype(dtype)
               for _ in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(ft.attention(q, k, v, ft.FlashAttentionConfig())
                       .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_composed(q, k, v).astype(jnp.float32) ** 2)

    o_f = ft.attention(q, k, v, ft.FlashAttentionConfig())
    o_r = _composed(q, k, v)
    np.testing.assert_allclose(np.asarray(o_f, np.float32),
                               np.asarray(o_r, np.float32), atol=tol_o)
    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_r):
        scale = max(float(jnp.abs(b.astype(jnp.float32)).max()), 1.0)
        np.testing.assert_allclose(np.asarray(a, np.float32) / scale,
                                   np.asarray(b, np.float32) / scale,
                                   atol=tol_g)


def test_resolve_and_flags():
    """resolve_flash_attention mirrors the fp8/mp_overlap resolution
    contract, and 'auto' reads FLAGS_flash_attention / FLAGS_flash_sep."""
    assert ft.resolve_flash_attention(None) is None
    assert ft.resolve_flash_attention(False) is None
    assert ft.resolve_flash_attention("auto") is None  # flags default off
    assert ft.resolve_flash_attention(True).sep is None
    assert ft.resolve_flash_attention("ring").sep == "ring"
    cfg = ft.FlashAttentionConfig(block_q=256)
    assert ft.resolve_flash_attention(cfg) is cfg
    paddle.set_flags({"FLAGS_flash_attention": True,
                      "FLAGS_flash_sep": "ulysses"})
    try:
        r = ft.resolve_flash_attention("auto")
        assert r is not None and r.sep == "ulysses"
        # a sep flag WITHOUT the flash flag is a loud misconfiguration,
        # not a silent einsum fallback
        paddle.set_flags({"FLAGS_flash_attention": False})
        with pytest.raises(EnforceNotMet, match="flash_sep"):
            ft.resolve_flash_attention("auto")
    finally:
        paddle.set_flags({"FLAGS_flash_attention": False,
                          "FLAGS_flash_sep": ""})
    with pytest.raises(EnforceNotMet):
        ft.FlashAttentionConfig(sep="nope")


# ---------------------------------------------------------------------------
# Engine level: off = bitwise no-op, on = parity + kernel presence
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def mesh8():
    return dist.build_mesh({"dp": 2, "pp": 2, "mp": 2})


def _build(mesh, flash, cfg=CFG, **kw):
    opt = paddle.optimizer.AdamW(1e-2)
    step, shard, init = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=2, flash_attention=flash, **kw)
    p = shard(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    return step, p, init(p)


def test_flash_off_is_bitwise_noop(mesh8):
    """FLAGS off + flash_attention='auto' must lower to byte-identical
    HLO as an explicit flash_attention=None build (the mp_overlap/
    telemetry no-op pattern) — and ON genuinely changes the program."""
    tokens, labels = _data()
    step_none, p, s = _build(mesh8, None)
    base = step_none.lower(p, s, tokens, labels, LR).as_text()
    step_auto, _, _ = _build(mesh8, "auto")
    assert step_auto.lower(p, s, tokens, labels, LR).as_text() == base
    step_on, _, _ = _build(mesh8, True)
    assert step_on.lower(p, s, tokens, labels, LR).as_text() != base


def test_flash_kernel_present_einsum_scores_absent(mesh8):
    """The anti-silent-fallback gate: flash on ⇒ pallas_call eqns in the
    traced step and ZERO rank-4 (S, S) scores dots; flash off ⇒ the
    reverse. (Compiled-TPU text would additionally show tpu_custom_call —
    hlo_utils.pallas_custom_call_count; interpret-mode CPU lowering has
    no custom-call marker, hence the jaxpr-level counters.)"""
    tokens, labels = _data()
    step_off, p, s = _build(mesh8, None)
    assert pallas_call_count(step_off, p, s, tokens, labels, LR) == 0
    assert attention_scores_dots(step_off, p, s, tokens, labels, LR,
                                 seq=SEQ) > 0
    step_on, _, _ = _build(mesh8, True)
    assert pallas_call_count(step_on, p, s, tokens, labels, LR) > 0
    assert attention_scores_dots(step_on, p, s, tokens, labels, LR,
                                 seq=SEQ) == 0


def test_flash_hybrid_loss_parity(mesh8):
    """8-step fp32 loss parity of the flash hybrid step vs the einsum
    baseline on dp2·pp2·mp2 (50-step acceptance run: slow tier)."""
    base = _run_gpt(mesh8, None, 8)
    fl = _run_gpt(mesh8, True, 8)
    assert _max_rel(base, fl) < 1e-5, (base, fl)


def test_remat_modes_bitwise_equal():
    """Full remat (replay the flash forward kernel) and selective remat
    (reuse the FLASH_REMAT_NAMES-saved out/lse residuals) must agree
    BITWISE — the kernel is deterministic, so replay == reuse exactly.
    The saved-residual mode provably skips the replay: its traced
    backward contains one fewer pallas_call."""
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=SEQ, dtype=jnp.float32)
    tokens, labels = _data(batch=4, vocab=cfg.vocab_size)
    p = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    plan = ft.FlashAttentionConfig()

    def vg(remat_save):
        return jax.jit(jax.value_and_grad(
            lambda p: G.dense_loss(p, tokens, labels, cfg,
                                   remat_save=remat_save, flash=plan)))

    l_full, g_full = vg(())(p)
    l_sel, g_sel = vg(("attn_out", "qkv"))(p)
    assert float(l_full) == float(l_sel)
    eq = jax.tree.map(lambda a, b: bool((a == b).all()), g_full, g_sel)
    assert all(jax.tree.leaves(eq)), eq
    n_full = pallas_call_count(vg(()), p)
    n_sel = pallas_call_count(vg(("attn_out", "qkv")), p)
    assert n_sel < n_full, (n_sel, n_full)


# ---------------------------------------------------------------------------
# Compose matrix: sp/ring × zero1 × {1F1B, ZBH1, VPP} × fp8
# ---------------------------------------------------------------------------
def test_compose_sp_zero1(mesh8):
    """Fast-tier compose gate: flash under seq-parallel TP + ZeRO-1
    tracks its own einsum baseline (attention consumes the gathered full
    sequence; heads stay local under TP)."""
    kw = dict(mp_overlap="seq_parallel", zero1_dp=True)
    base = _run_gpt(mesh8, None, 4, **kw)
    fl = _run_gpt(mesh8, True, 4, **kw)
    assert _max_rel(base, fl) < 1e-5, (base, fl)


@pytest.mark.slow
@pytest.mark.parametrize("kw", [
    dict(mp_overlap="seq_parallel"),
    dict(mp_overlap="collective_matmul"),
    dict(mp_overlap="collective_matmul", zero1_dp=True),
    dict(schedule="ZBH1"),
    dict(schedule="ZBH1", mp_overlap="seq_parallel", zero1_dp=True),
    dict(virtual_pp=2),
    dict(virtual_pp=2, mp_overlap="seq_parallel"),
    dict(fp8=True),
    dict(fp8=True, mp_overlap="seq_parallel", zero1_dp=True),
], ids=["sp", "ring", "ring-zero1", "zbh1", "zbh1-sp-zero1", "vpp",
        "vpp-sp", "fp8", "fp8-sp-zero1"])
def test_compose_matrix(mesh8, kw):
    """Each leg: the flash step vs ITS OWN einsum baseline under the same
    flags, 4 steps fp32 (fp8 legs: quantization-amplified tolerance —
    an attention-output ulp shifts an amax, which shifts next-step
    scales)."""
    tol = 5e-4 if kw.get("fp8") else 1e-5
    base = _run_gpt(mesh8, None, 4, **kw)
    fl = _run_gpt(mesh8, True, 4, **kw)
    assert _max_rel(base, fl) < tol, (kw, base, fl)


@pytest.mark.slow
def test_flash_50_step_trajectory(mesh8):
    """ISSUE 12 acceptance: the flash hybrid trajectory tracks the einsum
    baseline over 50 steps on dp2·pp2·mp2, fp32 (measured ~1e-6 rel as
    the toy overfits — the ≤2e-2 acceptance band is vast headroom; kept
    loose because kernel-vs-composed reassociation noise is chaotic)."""
    base = _run_gpt(mesh8, None, 50)
    fl = _run_gpt(mesh8, True, 50)
    assert _max_rel(base, fl) < 2e-2, (base, fl)


@pytest.mark.slow
def test_flash_bf16_tracks(mesh8):
    """bf16 compute dtype: step 0 must agree to kernel-vs-composed
    rounding (the composed path also accumulates in fp32, so only the
    online-softmax reassociation differs), later steps to the bf16
    quantization band (the mp_overlap bf16 pattern)."""
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                      num_heads=4, max_seq_len=SEQ, dtype=jnp.bfloat16)
    base = _run_gpt(mesh8, None, 3, cfg=cfg)
    fl = _run_gpt(mesh8, True, 3, cfg=cfg)
    assert abs(base[0] - fl[0]) / abs(base[0]) < 2e-3, (base, fl)
    assert _max_rel(base, fl) < 2e-2, (base, fl)


@pytest.mark.slow
def test_moe_flash_parity():
    """GPT-MoE on the ep mesh: flash threads through the MoE block's
    attention sublayer (_moe_block_fn) — parity vs the MoE einsum
    baseline."""
    cfg = G.gpt_moe_tiny(dtype=jnp.float32)
    mesh = dist.build_mesh({"dp": 2, "ep": 2, "pp": 1, "mp": 2})
    tokens, labels = _data(vocab=cfg.vocab_size)

    def run(flash):
        opt = paddle.optimizer.AdamW(1e-2)
        step, shard, init = G.build_hybrid_train_step(
            cfg, mesh, opt, flash_attention=flash)
        p = shard(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
        s = init(p)
        out = []
        for _ in range(4):
            p, s, l = step(p, s, tokens, labels, LR)
            out.append(float(l))
        return out

    base = run(None)
    fl = run(True)
    assert _max_rel(base, fl) < 1e-4, (base, fl)


def test_llama_flash_parity(mesh8):
    """Llama (GQA: 4 q heads, 2 kv heads over mp2 — one kv head per
    rank, KV indexed not repeated): flash vs the registry baseline."""
    cfg = L.llama_tiny(dtype=jnp.float32)
    tokens, labels = _data(vocab=cfg.vocab_size)

    def run(flash):
        opt = paddle.optimizer.AdamW(1e-2)
        step, shard, init = L.build_hybrid_train_step(
            cfg, mesh8, opt, num_microbatches=2, flash_attention=flash)
        p = shard(L.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
        s = init(p)
        out = []
        for _ in range(3):
            p, s, l = step(p, s, tokens, labels, LR)
            out.append(float(l))
        return out

    base = run(None)
    fl = run(True)
    assert _max_rel(base, fl) < 1e-4, (base, fl)


# ---------------------------------------------------------------------------
# sep context parallelism (ring / Ulysses), single-process
# ---------------------------------------------------------------------------
def test_sep_ring_vs_ulysses_parity():
    """ISSUE 12 sep leg: the same global problem on a dp2·sep2·mp2 mesh
    (sequence sharded over sep) under ring AND Ulysses context
    parallelism must track a sep-free flash baseline on dp4·mp2 — the
    global loss is the same mean over the same tokens either way."""
    mesh_sep = dist.build_mesh({"dp": 2, "sep": 2, "pp": 1, "mp": 2})
    mesh_dp4 = dist.build_mesh({"dp": 4, "pp": 1, "mp": 2})
    base = _run_gpt(mesh_dp4, True, 4)
    ring = _run_gpt(mesh_sep, "ring", 4)
    uly = _run_gpt(mesh_sep, "ulysses", 4)
    assert _max_rel(base, ring) < 1e-5, (base, ring)
    assert _max_rel(base, uly) < 1e-5, (base, uly)


@pytest.mark.slow
def test_llama_sep_ring_parity():
    """Llama sep ring (RoPE tables sliced to each rank's GLOBAL
    positions; rotated K blocks travel the ring)."""
    cfg = L.llama_tiny(dtype=jnp.float32)
    tokens, labels = _data(vocab=cfg.vocab_size)
    mesh_sep = dist.build_mesh({"dp": 2, "sep": 2, "pp": 1, "mp": 2})
    mesh_dp4 = dist.build_mesh({"dp": 4, "pp": 1, "mp": 2})

    def run(mesh, flash):
        opt = paddle.optimizer.AdamW(1e-2)
        step, shard, init = L.build_hybrid_train_step(
            cfg, mesh, opt, num_microbatches=2, flash_attention=flash)
        p = shard(L.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
        s = init(p)
        out = []
        for _ in range(4):
            p, s, l = step(p, s, tokens, labels, LR)
            out.append(float(l))
        return out

    base = run(mesh_dp4, True)
    ring = run(mesh_sep, "ring")
    assert _max_rel(base, ring) < 1e-5, (base, ring)


def test_sep_refusals(mesh8):
    """sep needs the mesh axis; is not composed with mp sequence
    parallelism (both shard the sequence) or MoE; ulysses needs
    heads/mp divisible by the sep degree."""
    opt = paddle.optimizer.AdamW(1e-2)
    with pytest.raises(EnforceNotMet, match="mesh axis"):
        G.build_hybrid_train_step(CFG, mesh8, opt, num_microbatches=2,
                                  flash_attention="ring")
    mesh_sep = dist.build_mesh({"dp": 2, "sep": 2, "pp": 1, "mp": 2})
    with pytest.raises(EnforceNotMet, match="sequence"):
        G.build_hybrid_train_step(CFG, mesh_sep, opt, num_microbatches=2,
                                  flash_attention="ring",
                                  mp_overlap="seq_parallel")
    moe_cfg = G.gpt_moe_tiny(dtype=jnp.float32)
    mesh_moe = dist.build_mesh({"dp": 1, "ep": 2, "sep": 2, "pp": 1,
                                "mp": 2})
    with pytest.raises(EnforceNotMet, match="MoE"):
        G.build_hybrid_train_step(moe_cfg, mesh_moe, opt,
                                  flash_attention="ring")
    # 4 heads / mp2 = 2 local heads; sep4 cannot take a head shard
    mesh_s4 = dist.build_mesh({"dp": 1, "sep": 4, "pp": 1, "mp": 2})
    with pytest.raises(EnforceNotMet, match="ulysses"):
        G.build_hybrid_train_step(CFG, mesh_s4, opt, num_microbatches=1,
                                  flash_attention="ulysses")
    lcfg = L.llama_tiny()
    with pytest.raises(EnforceNotMet, match="ulysses"):
        # kv heads 2 / mp2 = 1 per rank; sep2 cannot shard it
        mesh_l = dist.build_mesh({"dp": 2, "sep": 2, "pp": 1, "mp": 2})
        L.build_hybrid_train_step(lcfg, mesh_l, opt, num_microbatches=1,
                                  flash_attention="ulysses")
    # the GLOBAL sequence must fit the position table: dynamic_slice
    # would silently CLAMP an out-of-range start and hand later sep
    # ranks the first ranks' position rows — must refuse at trace
    step, shard, init = G.build_hybrid_train_step(
        CFG, mesh_sep, opt, num_microbatches=2, flash_attention="ring")
    p = shard(G.init_hybrid_params(CFG, jax.random.PRNGKey(0)))
    s = init(p)
    big_t, big_l = _data(seq=2 * CFG.max_seq_len)  # global = 2x table
    with pytest.raises(EnforceNotMet, match="max_seq_len"):
        step(p, s, big_t, big_l, LR)


# ---------------------------------------------------------------------------
# Planner: the flash_attention axis
# ---------------------------------------------------------------------------
def test_planner_flash_axis_validity_and_prunes():
    from paddle_tpu.distributed.auto_tuner import planner as PL
    spec = PL.ModelSpec.from_config(G.gpt_tiny(), "gpt")
    ok = PL.PlanCandidate(dp=4, mp=2, flash_attention=True)
    assert PL.check_candidate(ok, spec, world=8, global_batch=8,
                              seq=256) is None
    kw = ok.engine_kwargs(family="gpt")
    assert kw["flash_attention"] is True
    assert "flash" in str(ok)
    # seq not a lane multiple -> pruned with a stated reason
    r = PL.check_candidate(ok, spec, world=8, global_batch=8, seq=192)
    assert r and "128" in r
    # head_dim > 256 -> pruned
    big = G.GPTConfig(vocab_size=1024, hidden_size=1024, num_layers=2,
                      num_heads=2, max_seq_len=256)
    spec_big = PL.ModelSpec.from_config(big, "gpt")
    r = PL.check_candidate(PL.PlanCandidate(dp=4, mp=2,
                                            flash_attention=True),
                           spec_big, world=8, global_batch=8, seq=256)
    assert r and "head_dim" in r
    # the default enumeration emits flash-aware candidates
    cands, _ = PL.generate_plan_candidates(spec, 8, global_batch=8,
                                           seq=256)
    assert any(c.flash_attention for c in cands)
    assert any(not c.flash_attention for c in cands)


def test_planner_flash_hbm_drops_and_compute_honest():
    """The acceptance property: at long S the flash candidate's
    activation-HBM estimate drops vs the einsum estimate (O(S) vs O(S²)
    rematted scores), while its predicted compute is HIGHER (the
    two-kernel backward re-derives scores tiles) — flash wins the
    ranking exactly where memory binds, not by fiat."""
    from paddle_tpu.distributed.auto_tuner import planner as PL
    cfg = G.gpt_1p3b()
    spec = PL.ModelSpec.from_config(cfg, "gpt")
    cm = PL.CostModel(spec, PL.KNOWN_PROFILES["tpu-v5e"],
                      global_batch=8, seq=4096)
    base = PL.PlanCandidate(dp=1, mp=8)
    fl = PL.PlanCandidate(dp=1, mp=8, flash_attention=True)
    pb, pf = cm.predict(base), cm.predict(fl)
    assert pf.hbm["act"] < pb.hbm["act"]
    assert pf.compute_s > pb.compute_s
    assert pf.hbm["params"] == pb.hbm["params"]
    # the flops delta matches the analytic attention model exactly
    from paddle_tpu.observability import flops as F
    a_e = F.attention_flops_per_token(num_layers=cfg.num_layers,
                                      hidden_size=cfg.hidden_size,
                                      seq_len=4096, impl="einsum")
    a_f = F.attention_flops_per_token(num_layers=cfg.num_layers,
                                      hidden_size=cfg.hidden_size,
                                      seq_len=4096, impl="flash")
    want = (8 * 4096) * (a_f["hardware"] - a_e["hardware"]) / 8 \
        * ((1 + 1 - 1) / 1)
    assert abs((pf.compute_units - pb.compute_units) - want) \
        <= 1e-6 * want
    # and at a tight budget the einsum twin is pruned while flash fits
    budget = pf.hbm_bytes * 1.02 / 1e9
    rep = PL.plan(cfg, world=8, global_batch=8, seq=4096, family="gpt",
                  hbm_gb=budget,
                  micro_batch_options=(1,), schedules=("1f1b",),
                  vpp_options=(1,), zero_stage_options=(0,),
                  comm_bucket_options=(0.0,), mp_overlap_options=(None,))
    kept = {str(s.candidate) for s in rep.ranked}
    assert str(fl) in kept
    assert str(base) not in kept
    assert any("analytic HBM" in reason and str(c) == str(base)
               for c, reason in rep.pruned)


def test_flash_engine_kwargs_round_trip():
    """A planner-emitted flash candidate builds and steps through the
    real engine (the PR 9 round-trip pattern)."""
    from paddle_tpu.distributed.auto_tuner import planner as PL
    c = PL.PlanCandidate(dp=2, pp=2, mp=2, micro_batches=2,
                         flash_attention=True)
    spec = PL.ModelSpec.from_config(CFG, "gpt")
    assert PL.check_candidate(c, spec, world=8, global_batch=8,
                              seq=SEQ + 64) is None
    mesh = c.build_mesh()
    kw = c.engine_kwargs(family="gpt")
    opt = paddle.optimizer.AdamW(1e-2)
    step, shard, init = G.build_hybrid_train_step(CFG, mesh, opt, **kw)
    p = shard(G.init_hybrid_params(CFG, jax.random.PRNGKey(0)))
    s = init(p)
    tokens, labels = _data()
    p, s, loss = step(p, s, tokens, labels, LR)
    assert np.isfinite(float(loss))
