"""SqueezeNet (reference: python/paddle/vision/models/squeezenet.py)."""

from __future__ import annotations
from ._utils import no_pretrained

import jax.numpy as jnp

from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(nn.Layer):
    def __init__(self, inp, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(inp, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        x = self.squeeze(x)
        return jnp.concatenate([self.expand1(x), self.expand3(x)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
            x = x.reshape((x.shape[0], -1))
        return x


def squeezenet1_0(pretrained: bool = False, **kwargs) -> SqueezeNet:
    no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained: bool = False, **kwargs) -> SqueezeNet:
    no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)
