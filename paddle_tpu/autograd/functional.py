"""Functional autograd (reference: python/paddle/autograd/ — paddle.grad
(double-grad capable), incubate.autograd jacobian/hessian/vjp/jvp)."""

from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

__all__ = ["grad", "jacobian", "hessian", "vjp", "jvp"]


def grad(func: Callable, argnums: Union[int, Sequence[int]] = 0,
         has_aux: bool = False, allow_unused: bool = False,
         create_graph: bool = True):
    """Gradient transform. create_graph/allow_unused exist for surface
    parity: jax grads are always differentiable (higher-order free), and
    unused inputs get zero cotangents rather than None."""
    del allow_unused, create_graph
    return jax.grad(func, argnums=argnums, has_aux=has_aux)


def jacobian(func: Callable, xs, create_graph: bool = False):
    """Dense jacobian of func at xs. xs: array, or tuple of arrays — the
    tuple form differentiates w.r.t. EVERY input and returns a per-input
    tuple (reference behavior)."""
    del create_graph
    if isinstance(xs, (tuple, list)):
        argnums = tuple(range(len(xs)))
        return jax.jacrev(func, argnums=argnums)(*xs)
    return jax.jacrev(func)(xs)


def hessian(func: Callable, xs, create_graph: bool = False):
    """Hessian blocks; tuple xs → tuple-of-tuples over all input pairs."""
    del create_graph
    if isinstance(xs, (tuple, list)):
        argnums = tuple(range(len(xs)))
        return jax.hessian(func, argnums=argnums)(*xs)
    return jax.hessian(func)(xs)


def vjp(func: Callable, xs, v=None):
    """Returns (outputs, vjp_result). v defaults to ones like the output
    (reference behavior)."""
    single = not isinstance(xs, (tuple, list))
    args = (xs,) if single else tuple(xs)
    out, pullback = jax.vjp(func, *args)
    if v is None:
        v = jax.tree.map(jnp.ones_like, out)
    grads = pullback(v)
    return out, grads[0] if single else grads


def jvp(func: Callable, xs, v=None):
    single = not isinstance(xs, (tuple, list))
    args = (xs,) if single else tuple(xs)
    if v is None:
        tangents = jax.tree.map(jnp.ones_like, args)
    else:
        tangents = (v,) if single else tuple(v)
    out, tangent_out = jax.jvp(func, args, tangents)
    return out, tangent_out
