"""TP-aware RNG (reference: fleet/layers/mpu/random.py) — re-exported from
the core threefry-based tracker (paddle_tpu/random.py)."""

from .....random import (MODEL_PARALLEL_RNG, RNGStatesTracker,  # noqa: F401
                         get_rng_state_tracker, model_parallel_random_seed)

__all__ = ["MODEL_PARALLEL_RNG", "RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed"]
