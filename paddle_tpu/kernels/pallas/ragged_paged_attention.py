"""Unified ragged prefill+decode paged attention for TPU (Pallas).

One kernel serves a MIXED batch over the block-paged KV pool: each row of
the ragged batch is either a decode step (q_len = 1) or a chunked-prefill
slice (q_len = chunk, causal within the chunk, attending to every prior
KV page), with per-row ``(q_len, kv_len)`` descriptors riding SCALAR
PREFETCH next to the block tables — so the serving engine's whole step is
ONE compiled dispatch instead of a prefill program plus a decode program
(Ragged Paged Attention, arXiv:2604.15464; reference block kernels
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).

Design (extends paged_attention.py, which stays as the decode-only
baseline the two-program engine path compiles):

  * pools head-major ``[H_kv, num_blocks, bs, D]`` — one (head, block)
    tile is a contiguous ``[bs, D]`` VMEM block; the K/V BlockSpec index
    maps dereference ``tables[r, j]`` so only referenced blocks stream;
  * grid ``(R, H_kv, nb)``: rows × kv heads × table slots. Per-row
    ``kv_len`` clamps past-end steps to the last used block (Pallas skips
    the re-fetch when consecutive steps map to the same block) and the
    compute body is predicated off — a decode row costs its own blocks,
    never the batch max;
  * the q tile folds (chunk, GQA group) into one ``[C*g, D]`` MXU
    operand; in-kernel masking applies BOTH raggedness (``c < q_len``)
    and causality (``col_pos <= kv_len - q_len + c``), so decode rows and
    prefill chunks share the grid with no inter-row padding;
  * optional int8 KV: pools stored int8 with per-(head, page) scales in
    the module's absmax convention (quantization/: dequant = q·s/127),
    dequantized IN-KERNEL right after the VMEM fetch — decode is
    bandwidth-bound, so the kernel streams half the HBM bytes per step
    and a fixed pool budget admits ~2x the sequences;
  * online softmax in VMEM scratch, exactly like the training flash
    kernel; empty rows (q_len = 0) emit zeros.

Interpreter mode runs the same kernel on CPU (tier-1 parity tests).
Page-size guidance is unchanged from paged_attention.py: pick
block_size >= 128 on real TPUs; tiny vLLM-style pages drown in grid
overhead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import LANES as _LANES
from ._common import interpret as _interpret

__all__ = ["ragged_paged_attention"]

_NEG_INF = -1e30


def _ragged_kernel(*refs, scale, bs, nb, g, quantized, qmax):
    if quantized:
        (tables_ref, qlens_ref, kvlens_ref, ks_ref, vs_ref,
         q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc) = refs
    else:
        (tables_ref, qlens_ref, kvlens_ref,
         q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc) = refs
        ks_ref = vs_ref = None
    r = pl.program_id(0)
    h = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    ql = qlens_ref[r]
    kl = kvlens_ref[r]
    used = (kl + bs - 1) // bs

    @pl.when((j < used) & (ql > 0))
    def _compute():
        q = q_ref[0, 0]  # [CG, D] — (chunk, group) folded, c-major
        k = k_ref[0, 0]  # [bs, D] (int8 when quantized)
        v = v_ref[0, 0]
        if quantized:
            page = tables_ref[r, j]
            k_deq = k.astype(jnp.float32) * (ks_ref[h, page] / qmax)
            v_deq = v.astype(jnp.float32) * (vs_ref[h, page] / qmax)
        else:
            k_deq, v_deq = k, v
        s = jax.lax.dot_general(
            q, k_deq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [CG, bs]
        # row f of the folded tile is chunk position c = f // g; its
        # absolute query position is kv_len - q_len + c (the chunk holds
        # the LAST q_len tokens of the sequence)
        c = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // g
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        ok = (c < ql) & (col <= kl - ql + c)
        s = jnp.where(ok, s, _NEG_INF)
        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(ok, p, 0.0)
        l_sc[:] = l_sc[:] * alpha[:, None] + jnp.sum(p, axis=1)[:, None]
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        pv = jax.lax.dot_general(
            p.astype(v_deq.dtype), v_deq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_sc[:, 0]
        dead = (l == 0.0) | (m_sc[:, 0] <= _NEG_INF * 0.5)
        inv = jnp.where(dead, 0.0, 1.0 / jnp.maximum(l, 1e-37))
        o_ref[0, 0] = (acc_sc[:] * inv[:, None]).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pool, v_pool, block_tables, q_lens,
                           kv_lens, scale: float,
                           k_scales=None, v_scales=None):
    """q: [R, C, H_q, D] — row r's chunk occupies columns [0, q_lens[r]);
    pools: [H_kv, num_blocks, bs, D] (float, or int8 with k_scales /
    v_scales: [H_kv, num_blocks] f32 per-page absmax scales);
    block_tables: [R, nb] int32; q_lens: [R] int32 (0 = inactive row);
    kv_lens: [R] int32 — TOTAL kv length including this chunk (query c
    sits at absolute position kv_lens - q_lens + c) → [R, C, H_q, D]."""
    R, C, hq, D = q.shape
    hkv, _, bs, _ = k_pool.shape
    nb = block_tables.shape[1]
    g = hq // hkv
    quantized = k_scales is not None
    # quantized pools dequantize in-kernel in the absmax convention
    # (quantization/kv_cache.py): int8 grid tops at 127, e4m3 at 448
    qmax = (448.0 if k_pool.dtype == jnp.dtype(jnp.float8_e4m3fn)
            else 127.0)
    CG = C * g
    CG8 = max(8, -(-CG // 8) * 8)  # sublane-align the folded tile
    # [R, C, hkv, g, D] -> [R, hkv, C*g, D], chunk-major rows (c = f // g)
    qt = q.reshape(R, C, hkv, g, D).transpose(0, 2, 1, 3, 4)
    qt = qt.reshape(R, hkv, CG, D)
    if CG8 != CG:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, CG8 - CG), (0, 0)))

    def q_idx(r, h, j, *prefetch):
        return (r, h, 0, 0)

    def kv_idx(r, h, j, *prefetch):
        tables, qlens, kvlens = prefetch[:3]
        # clamp past-end steps to the last used block: the index repeats,
        # so Pallas skips the re-fetch and the tail costs nothing
        used_last = jnp.maximum((kvlens[r] + bs - 1) // bs - 1, 0)
        return (h, tables[r, jnp.minimum(j, used_last)], 0, 0)

    prefetch = [block_tables, q_lens.astype(jnp.int32),
                kv_lens.astype(jnp.int32)]
    if quantized:
        prefetch += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(R, hkv, nb),
        in_specs=[
            pl.BlockSpec((1, 1, CG8, D), q_idx),
            pl.BlockSpec((1, 1, bs, D), kv_idx),
            pl.BlockSpec((1, 1, bs, D), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, CG8, D), q_idx),
        scratch_shapes=[
            pltpu.VMEM((CG8, _LANES), jnp.float32),
            pltpu.VMEM((CG8, _LANES), jnp.float32),
            pltpu.VMEM((CG8, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, scale=scale, bs=bs, nb=nb, g=g,
                          quantized=quantized, qmax=qmax),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, hkv, CG8, D), q.dtype),
        interpret=_interpret(),
    )(*prefetch, qt, k_pool, v_pool)
    out = out[:, :, :CG].reshape(R, hkv, C, g, D)
    return out.transpose(0, 2, 1, 3, 4).reshape(R, C, hq, D)
