"""Structured JSONL event log — the crash-forensics channel.

One JSON object per line, flushed per event, so a SIGKILL mid-run leaves
every completed line readable (the same discipline as the resilience
commit protocol's atomic writes). Schema: every event carries

    {"ts": <unix seconds>, "pid": <os pid>, "host": <process index>,
     "role": "<trainer|serving|...>", "event": "<kind>", ...fields}

``host``/``role`` keep MERGED streams attributable: a fleet run (or the
RL loop that drives a trainer and a serving engine side by side) funnels
several processes' logs into one timeline via
:func:`merge_event_streams`, and the reader must still know which
process and which half of the system produced each line.

Long resilient runs would otherwise grow the log without bound, so the
file is size-capped (``FLAGS_telemetry_jsonl_max_mb``; 0 = unbounded):
when an emit would cross the cap, the live file rotates to ``<path>.1``
(one generation — the bound is 2x the cap) and the fresh file opens with
a ``jsonl_rotated`` event recording what moved where.

Producers: the resilient runner (resume/commit/skip/SIGTERM/abort), the
TelemetryHost (decoded device-metric intervals), the fleet
TelemetryAggregator (straggler_detected), Model.fit (step reports) and
the serving engine (admits/completions). The process-global log is bound
to ``FLAGS_telemetry_jsonl``; pass an explicit :class:`EventLog` where a
private file is wanted (tests, multi-run drivers).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["EventLog", "get_event_log", "set_event_log",
           "merge_event_streams"]


def default_host() -> int:
    """This process's fleet index (the launcher's rank env, 0 standalone).
    Read from env rather than jax.process_index() so emitting an event
    can never initialize a jax backend."""
    for var in ("PADDLE_TRAINER_ID", "JAX_PROCESS_ID"):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


class EventLog:
    def __init__(self, path: str, *, role: str = "trainer",
                 host: Optional[int] = None, max_mb: Optional[float] = None):
        self.path = path
        self.role = str(role)
        self.host = default_host() if host is None else int(host)
        if max_mb is None:
            from ..flags import flag
            max_mb = float(flag("telemetry_jsonl_max_mb"))
        self.max_bytes = int(max_mb * (1 << 20)) if max_mb > 0 else 0
        self.rotations = 0
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._bytes = self._f.tell()

    def emit(self, event: str, **fields: Any) -> None:
        rec = {"ts": round(time.time(), 6), "pid": os.getpid(),
               "host": self.host, "role": self.role, "event": str(event)}
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable) + "\n"
        with self._lock:
            if (self.max_bytes and self._bytes
                    and self._bytes + len(line) > self.max_bytes):
                self._rotate_locked()
            self._f.write(line)
            self._f.flush()  # per-line durability: forensics-friendly
            self._bytes += len(line)

    def _rotate_locked(self) -> None:
        """Size-cap rotation: the live file becomes <path>.1 (replacing
        any previous generation — total on disk stays <= 2x the cap) and
        a fresh file opens, announcing itself with a jsonl_rotated event
        so a reader of the new file knows history moved."""
        self._f.close()
        rotated_to = self.path + ".1"
        try:
            os.replace(self.path, rotated_to)
        except OSError:
            # rotation impossible (locked/read-only target): give up on
            # the cap for this log's lifetime rather than re-entering a
            # close/replace/reopen + jsonl_rotated cycle on EVERY emit —
            # the one announcement below records that capping stopped
            rotated_to = None
            self.max_bytes = 0
        self._f = open(self.path, "a", encoding="utf-8")
        self._bytes = self._f.tell()
        self.rotations += 1
        rec = {"ts": round(time.time(), 6), "pid": os.getpid(),
               "host": self.host, "role": self.role,
               "event": "jsonl_rotated", "rotated_to": rotated_to,
               "rotation": self.rotations,
               "max_bytes": self.max_bytes}
        line = json.dumps(rec, default=_jsonable) + "\n"
        self._f.write(line)
        self._f.flush()
        self._bytes += len(line)

    def tail(self, n: int) -> List[Dict[str, Any]]:
        """Last <= n decoded records of the CURRENT file generation, read
        back from disk (bounded read from the end — the flight recorder
        calls this on the crash path where the log may be huge)."""
        with self._lock:
            self._f.flush()
        return read_jsonl_tail(self.path, n)

    def span(self, name: str):
        """Host span recorded BOTH as begin/end JSONL events and as a
        profiler HostEvent, so it lands in Profiler summaries/chrome
        traces too (the unified-trace contract)."""
        return _Span(self, name)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _Span:
    def __init__(self, log: EventLog, name: str):
        self._log = log
        self._name = name
        from ..profiler.utils import RecordEvent
        self._rec = RecordEvent(name)

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._log.emit("span_begin", name=self._name)
        self._rec.begin()
        return self

    def __exit__(self, *exc):
        self._rec.end()
        self._log.emit("span_end", name=self._name,
                       duration_s=round(time.perf_counter() - self._t0, 6))
        return False


def _jsonable(x):
    try:
        return float(x)
    except (TypeError, ValueError):
        return repr(x)


def read_jsonl_tail(path: str, n: int,
                    max_bytes: int = 1 << 20) -> List[Dict[str, Any]]:
    """Decode the last <= n records of a JSONL file, reading at most
    `max_bytes` from the end (a torn first line after the seek is
    skipped). Returns [] for a missing file — the crash path must never
    raise from here."""
    if n <= 0:
        return []
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > max_bytes:
                f.seek(size - max_bytes)
                f.readline()  # discard the (likely torn) partial line
            lines = f.read().decode("utf-8", errors="replace").splitlines()
    except OSError:
        return []
    out: List[Dict[str, Any]] = []
    for line in lines[-n:]:
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return out


def merge_event_streams(*logs, out_path: Optional[str] = None,
                        roles: Optional[Dict[int, str]] = None
                        ) -> List[Dict[str, Any]]:
    """Merge several JSONL event streams (EventLog instances or file
    paths) into ONE role-tagged timeline ordered by ``ts`` (stable for
    ties, so each stream's own order is preserved).

    This is the pre-work for the RL-loop scenario (ROADMAP item 5): a
    training step loop and a ServingEngine each write their own stream;
    the merged view is the single timeline an operator reads. Records
    missing a ``role`` (pre-rotation history, foreign producers) get one
    from `roles` — {stream_index: role} — defaulting to "stream<i>".

    Size-cap rotation is transparent: when a stream's live file has a
    rotated ``<path>.1`` generation next to it, that generation's
    records are read FIRST (they are strictly older — the rotation
    renamed the whole previous file), so a capped long-running log
    merges as one unbroken timeline instead of silently dropping its
    oldest half.

    out_path: also write the merged records as JSONL. Returns the merged
    record list.
    """
    merged: List[tuple] = []
    for i, log in enumerate(logs):
        # every emit flushes, so the on-disk file is already current
        path = log.path if isinstance(log, EventLog) else str(log)
        fallback = (roles or {}).get(
            i, log.role if isinstance(log, EventLog) else f"stream{i}")
        lines: List[str] = []
        # rotated generation first: all of <path>.1 predates <path>
        for p in (path + ".1", path):
            try:
                with open(p, "r", encoding="utf-8") as f:
                    lines.extend(f.read().splitlines())
            except OSError:
                continue
        for rec_no, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            rec.setdefault("role", fallback)
            merged.append((float(rec.get("ts", 0.0)), i, rec_no, rec))
    merged.sort(key=lambda t: t[:3])
    records = [rec for _, _, _, rec in merged]
    if out_path is not None:
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec, default=_jsonable) + "\n")
    return records


_GLOBAL: Optional[EventLog] = None
_GLOBAL_PATH: Optional[str] = None
_EXPLICIT = False
_LOCK = threading.Lock()


def get_event_log() -> Optional[EventLog]:
    """The process event log: an explicitly installed one
    (:func:`set_event_log`) wins; otherwise the log bound to
    FLAGS_telemetry_jsonl (None when the flag is empty), re-bound if the
    flag changed since the last call."""
    global _GLOBAL, _GLOBAL_PATH
    with _LOCK:
        if _EXPLICIT:
            return _GLOBAL
    from ..flags import flag
    path = str(flag("telemetry_jsonl") or "")
    with _LOCK:
        if _EXPLICIT:
            return _GLOBAL
        if _GLOBAL is not None and _GLOBAL_PATH == path:
            return _GLOBAL
        if _GLOBAL is not None:
            _GLOBAL.close()
            _GLOBAL, _GLOBAL_PATH = None, None
        if path:
            _GLOBAL = EventLog(path)
            _GLOBAL_PATH = path
    return _GLOBAL


def emit_event(event: str, **fields) -> None:
    """Emit one event iff a process log is configured — the one copy of
    the get_event_log-guarded emit the resilience/checkpoint layers use
    for lifecycle forensics (resume/commit/reshard/carry decisions)."""
    log = get_event_log()
    if log is not None:
        log.emit(event, **fields)


def set_event_log(log: Optional[EventLog]) -> Optional[EventLog]:
    """Install an explicit process log (tests/drivers) that shadows the
    flag binding; set_event_log(None) restores flag-driven behavior.
    Returns the previous log (not closed — caller owns both)."""
    global _GLOBAL, _GLOBAL_PATH, _EXPLICIT
    with _LOCK:
        prev, _GLOBAL = _GLOBAL, log
        _GLOBAL_PATH = log.path if log is not None else None
        _EXPLICIT = log is not None
    return prev
