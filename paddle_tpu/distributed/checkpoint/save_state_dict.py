"""Sharded checkpoint save (reference:
python/paddle/distributed/checkpoint/save_state_dict.py:145 save_state_dict;
dedup of replicated shards at :107-144; async save via side process at :291).

TPU-native shape: under single-controller JAX each process sees only its
addressable shards (`x.addressable_shards`); every process writes one
`.distcp` file with its replica-0 shards (dedup: replicated copies have
replica_id > 0 and are skipped), and process 0 writes `0.metadata` after a
cross-process gather of chunk metadata. Async save snapshots shards to host
memory synchronously, then a writer thread does the file IO — the train loop
resumes as soon as device→host copies finish (the Orbax async pattern).
"""

from __future__ import annotations

import atexit
import os
import pickle
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .metadata import (SCHEMA_VERSION, LocalTensorIndex, LocalTensorMetadata,
                       Metadata, SavedLayout)
from .utils import (atomic_write, chunk_name, flatten_state_dict,
                    shard_chunks, to_host)

__all__ = ["save_state_dict", "wait_async_save", "build_layout"]

_PENDING: List[threading.Thread] = []
_SEM: list = [None, 0]
_SEM_LOCK = threading.Lock()


def _writer_semaphore(n: int, thread: threading.Thread
                      ) -> threading.Semaphore:
    """Concurrent async-save writer cap (FLAGS_async_ckpt_workers). A
    resize only takes effect once every registered writer has finished —
    swapping the semaphore under outstanding permits would let old+new
    permits exceed the cap. Registration (append) happens under the SAME
    lock as the check-and-swap, and unstarted threads (ident None) count
    as outstanding, so a writer that was handed the old semaphore can
    never be missed by the drain scan."""
    with _SEM_LOCK:
        drained = all(t.ident is not None and not t.is_alive()
                      for t in _PENDING)
        if _SEM[0] is None or (_SEM[1] != n and drained):
            _SEM[0] = threading.Semaphore(max(n, 1))
            _SEM[1] = n
        _PENDING.append(thread)
        return _SEM[0]
_ASYNC_ERRORS: List[BaseException] = []


def wait_async_save() -> None:
    """Block until all in-flight async checkpoint writes complete.
    Re-raises writer failures — a silently missing checkpoint must not look
    like success — aggregating EVERY writer's error into the message, so a
    multi-writer crash isn't narrowed to whichever thread died first."""
    while _PENDING:
        t = _PENDING.pop()
        t.join()
    if _ASYNC_ERRORS:
        errs = list(_ASYNC_ERRORS)
        _ASYNC_ERRORS.clear()
        detail = "; ".join(f"[writer {i}] {type(e).__name__}: {e}"
                           for i, e in enumerate(errs))
        raise RuntimeError(
            f"async checkpoint save failed: {len(errs)} writer(s) raised: "
            f"{detail}") from errs[0]


atexit.register(wait_async_save)


def _gather_metadata_across_processes(local_meta):
    """Multi-host: merge per-process chunk metadata. With one process this is
    the identity; with many, ride jax's coordination service.

    process_allgather requires identical shapes on every host, while pickled
    metadata is naturally ragged — so first agree on the max length, then
    exchange length-prefixed zero-padded buffers."""
    if jax.process_count() == 1:
        return [local_meta]
    from jax.experimental import multihost_utils
    payload = pickle.dumps(local_meta)
    n = len(payload)
    max_n = int(np.max(multihost_utils.process_allgather(
        np.asarray([n], dtype=np.int64))))
    buf = np.zeros(max_n + 8, dtype=np.uint8)
    buf[:8] = np.frombuffer(np.int64(n).tobytes(), dtype=np.uint8)
    buf[8:8 + n] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    out = []
    for row in np.asarray(gathered).reshape(-1, max_n + 8):
        ln = int(np.frombuffer(row[:8].tobytes(), dtype=np.int64)[0])
        out.append(pickle.loads(row[8:8 + ln].tobytes()))
    return out


def _store_gather_commit(meta_store, tag, proc, nproc, coordinator_rank,
                         local_meta, write_metadata_fn):
    """Store-coordinated metadata exchange + commit barrier: pure TCP, safe
    on a writer thread while the main thread keeps using the devices. The
    coordinator writes 0.metadata only after seeing EVERY rank's chunk
    metadata; other ranks return only after the commit marker exists, so a
    checkpoint directory with 0.metadata is always complete."""
    meta_store.set(f"{tag}/meta/{proc}", pickle.dumps(local_meta))
    if proc == coordinator_rank:
        all_meta = [pickle.loads(meta_store.get(f"{tag}/meta/{r}"))
                    for r in range(nproc)]
        write_metadata_fn(all_meta)
        meta_store.set(f"{tag}/commit", b"1")
    else:
        meta_store.get(f"{tag}/commit")  # blocks until committed


def _spec_entries(spec) -> tuple:
    """PartitionSpec -> plain picklable tuple (str | None | tuple[str])."""
    out = []
    for e in spec:
        out.append(tuple(e) if isinstance(e, (tuple, list)) else e)
    return tuple(out)


def _leaf_layout(value):
    """(mesh_dict, spec_entries, replication) for one array leaf, derived
    from its sharding. NamedSharding gives the exact mesh/spec; anything
    else records a replicated spec with a best-effort replica count."""
    sharding = getattr(value, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    if mesh is not None and hasattr(sharding, "spec"):
        axes = {str(a): int(mesh.shape[a]) for a in mesh.axis_names}
        spec = _spec_entries(sharding.spec)
        used = set()
        for e in spec:
            for a in (e if isinstance(e, tuple) else (e,)):
                if a is not None:
                    used.add(a)
        repl = 1
        for a, n in axes.items():
            if a not in used:
                repl *= n
        return axes, spec, repl
    ndim = getattr(value, "ndim", 0)
    if isinstance(value, jax.Array):
        repl = 1 + max((s.replica_id for s in value.addressable_shards),
                       default=0)
    else:
        repl = 1
    return {}, (None,) * ndim, repl


def build_layout(flat: Dict[str, Any], extra: Optional[Dict] = None
                 ) -> SavedLayout:
    """Derive the SavedLayout of a FLATTENED state dict from its arrays'
    shardings. `extra` carries the model-level hints shardings cannot
    express (pp/vpp stacked-block layout, comm_ef plan, carry policies —
    producers: models.hybrid_engine init_state.layout_extra)."""
    lay = SavedLayout(process_count=int(jax.process_count()),
                      extra=dict(extra or {}))
    for key, value in flat.items():
        if not isinstance(value, (jax.Array, np.ndarray)) and not hasattr(
                value, "addressable_shards"):
            continue
        axes, spec, repl = _leaf_layout(value)
        for a, n in axes.items():
            lay.mesh.setdefault(a, n)
        lay.specs[key] = spec
        lay.global_shapes[key] = tuple(int(d) for d in value.shape)
        lay.replication[key] = int(repl)
    return lay


_SAVE_SEQ = [0]  # per-process save counter; equal across processes because
#                  every process calls save_state_dict the same number of
#                  times — used to namespace store keys per save


def _store_from_env():
    """The launcher's TCP KV store (PADDLE_MASTER), if this is a
    multi-process job started through paddle_tpu.distributed.launch.
    Always connects as a CLIENT — the launcher hosts the server (a second
    listener on the same endpoint would fail or, worse, fork the ranks
    onto two disjoint stores)."""
    ep = os.environ.get("PADDLE_MASTER")
    if not ep:
        return None
    from ..store import TCPStore
    host, port = ep.rsplit(":", 1)
    try:
        return TCPStore(host, int(port), is_master=False)
    except Exception:
        return None


def save_state_dict(state_dict: Dict, path: str,
                    process_mesh=None,  # accepted for API parity; unused —
                                        # shardings are carried by the arrays
                    coordinator_rank: int = 0,
                    async_save: bool = False,
                    store=None,
                    layout: object = "auto",
                    layout_extra: Optional[Dict] = None) -> None:
    """Save a (possibly nested) state dict of sharded jax.Arrays.

    Every process writes only the shards it owns (replica 0), so the on-disk
    checkpoint is deduplicated; the metadata file records the global offset of
    each chunk so `load_state_dict` can reshard into ANY target sharding.

    async_save: device→host snapshots happen synchronously; file IO and the
    metadata commit run on a writer thread. Multi-process async needs a TCP
    KV `store` (paddle_tpu.distributed.TCPStore; defaults to the launcher's
    PADDLE_MASTER store) so the cross-process metadata exchange and commit
    barrier stay OFF the jax device runtime (reference:
    save_state_dict.py:291 async via side process) — with no store it falls
    back to a synchronous save with a warning, never silently.

    layout: "auto" (record the SavedLayout topology metadata — schema v2 —
    iff FLAGS_ckpt_reshard is on) / True / False-or-None. With no layout
    the metadata pickle stays byte-identical to the v1 format.
    layout_extra: model-level hints stored in SavedLayout.extra (pp/vpp
    block layout, comm_ef plan fingerprint, carry policies).
    """
    if layout == "auto":
        from ...flags import flag
        layout = bool(flag("ckpt_reshard"))
    os.makedirs(path, exist_ok=True)
    flat, mapping = flatten_state_dict(state_dict)
    saved_layout = build_layout(flat, layout_extra) if layout else None

    proc = jax.process_index()
    data_file = f"{proc}_0.distcp"

    chunks: Dict[str, np.ndarray] = {}          # chunk name -> host array
    local_meta: Dict[str, List] = {}            # key -> [(offset, shape, dtype, file)]
    misc: Dict[str, Any] = {}

    for key, value in flat.items():
        if not isinstance(value, (jax.Array, np.ndarray)) and not hasattr(
                value, "addressable_shards"):
            misc[key] = value
            continue
        if not isinstance(value, jax.Array) and proc != coordinator_rank:
            continue  # host numpy leaf: replicated everywhere; rank 0 writes
        entries = []
        for offset, shape, replica_id, _dev, shard in shard_chunks(value):
            if replica_id != 0:
                continue  # dedup: another replica owns this chunk
            host = to_host(shard)
            name = chunk_name(key, offset)
            if name in chunks:
                continue  # same chunk addressable via several local devices
            chunks[name] = host
            entries.append((offset, shape, str(host.dtype), data_file))
        local_meta[key] = entries

    def _write_metadata(all_meta):
        from ..resilience import faults
        md = Metadata(flat_mapping=mapping, misc=misc)
        if saved_layout is not None:
            md.schema_version = SCHEMA_VERSION
            md.layout = saved_layout
        else:
            # v1 byte-compat: drop the v2 fields from the instance dict so
            # the pickle is byte-identical to pre-layout checkpoints
            # (attribute access falls back to the class defaults)
            md.__dict__.pop("schema_version", None)
            md.__dict__.pop("layout", None)
        for rank_meta in all_meta:
            for key, entries in rank_meta.items():
                lst = md.state_dict_metadata.setdefault(key, [])
                for offset, shape, dtype, fname in entries:
                    lst.append(LocalTensorMetadata(tuple(offset),
                                                   tuple(shape), dtype))
                    md.storage_metadata[
                        LocalTensorIndex(key, tuple(offset))] = fname
        faults.maybe_fail("ckpt/before_metadata_write")
        # temp-file + os.replace: a crash mid-dump can never leave a
        # truncated pickle at 0.metadata (load_metadata would otherwise
        # surface it as an opaque UnpicklingError)
        with atomic_write(os.path.join(path, "0.metadata")) as f:
            pickle.dump(md, f)

    def write_files(chunks=chunks, local_meta=local_meta, misc=misc,
                    meta_store=None, tag=None):
        from ..resilience import faults
        with atomic_write(os.path.join(path, data_file)) as f:
            np.savez(f, **chunks)  # file handle keeps our .distcp name
        # torn-chunk site: simulates a storage-layer lie (fsync acked,
        # bytes not durable) by truncating the landed file before dying
        faults.maybe_corrupt_file("ckpt/torn_chunk",
                                  os.path.join(path, data_file))
        faults.maybe_fail("ckpt/after_chunk_write")
        if meta_store is not None:
            _store_gather_commit(meta_store, tag, proc, jax.process_count(),
                                 coordinator_rank, local_meta,
                                 _write_metadata)
        else:
            all_meta = _gather_metadata_across_processes(local_meta)
            if proc == coordinator_rank:
                _write_metadata(all_meta)

    def run_async(**kw):
        from ...flags import flag
        sem_box = []

        def guarded():
            with sem_box[0]:
                try:
                    write_files(**kw)
                except BaseException as e:  # surfaced by wait_async_save
                    _ASYNC_ERRORS.append(e)
        t = threading.Thread(target=guarded, daemon=False)
        sem_box.append(_writer_semaphore(int(flag("async_ckpt_workers")), t))
        t.start()

    _SAVE_SEQ[0] += 1
    if async_save and jax.process_count() == 1:
        run_async()
    elif async_save:
        st = store if store is not None else _store_from_env()
        if st is None:
            import warnings
            warnings.warn(
                "async_save on a multi-process job needs a TCP store for "
                "the off-device metadata exchange (pass store=, or launch "
                "via paddle_tpu.distributed.launch which sets "
                "PADDLE_MASTER); falling back to a SYNCHRONOUS save")
            write_files()
        else:
            # tag carries the elastic restart generation: the launcher's
            # store outlives worker restarts, and a reset _SAVE_SEQ must
            # not alias a previous incarnation's keys (stale metadata /
            # commit markers would let the coordinator commit early)
            gen = os.environ.get("PADDLE_RESTART_COUNT", "0")
            run_async(meta_store=st,
                      tag=f"ckpt/g{gen}/{_SAVE_SEQ[0]}/{path}")
    else:
        write_files()
