"""MobileNetV3 (reference: python/paddle/vision/models/mobilenetv3.py)."""

from __future__ import annotations
from ._utils import no_pretrained

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class _SE(nn.Layer):
    def __init__(self, c, squeeze=4):
        super().__init__()
        mid = _make_divisible(c // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _ConvBNAct(nn.Sequential):
    def __init__(self, inp, out, kernel=3, stride=1, groups=1, act=None):
        pad = (kernel - 1) // 2
        layers = [nn.Conv2D(inp, out, kernel, stride, pad, groups=groups,
                            bias_attr=False), nn.BatchNorm2D(out)]
        if act == "relu":
            layers.append(nn.ReLU())
        elif act == "hardswish":
            layers.append(nn.Hardswish())
        super().__init__(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, mid, out, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        layers = []
        if mid != inp:
            layers.append(_ConvBNAct(inp, mid, 1, act=act))
        layers.append(_ConvBNAct(mid, mid, kernel, stride, groups=mid,
                                 act=act))
        if use_se:
            layers.append(_SE(mid))
        layers.append(_ConvBNAct(mid, out, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_LARGE = [
    # k, mid, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        sc = lambda c: _make_divisible(c * scale)
        layers = [_ConvBNAct(3, sc(16), 3, 2, act="hardswish")]
        c = sc(16)
        for k, mid, out, se, act, stride in cfg:
            layers.append(_InvertedResidual(c, sc(mid), sc(out), k, stride,
                                            se, act))
            c = sc(out)
        lastconv = sc(cfg[-1][1])
        layers.append(_ConvBNAct(c, lastconv, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lastconv, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape((x.shape[0], -1))
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kw)
