"""Probability distributions (reference: python/paddle/distribution/ —
Distribution base, Normal, Uniform, Categorical, Bernoulli, ... and
kl_divergence registry kl.py).

TPU design: pure jnp math + threefry sampling (rng keys in, arrays out) so
every method composes with jit/vmap/grad; the reference's curand-backed
in-place samplers become functional `sample(shape, key)`.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from ..enforce import InvalidArgumentError, enforce

from ..random import next_key

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Laplace", "Gumbel", "LogNormal", "kl_divergence",
           "register_kl"]


class Distribution:
    event_rank = 0  # trailing dims that form one event (1 for MVN etc.)

    def sample(self, shape=(), key=None):
        raise NotImplementedError

    def rsample(self, shape=(), key=None):
        return self.sample(shape, key)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def _key(self, key):
        return next_key() if key is None else key


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(self._key(key), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        var = jnp.square(self.scale)
        return (-jnp.square(value - self.loc) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
            self.scale) + jnp.zeros_like(self.loc)

    def cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))

    @property
    def mean(self):
        return jnp.broadcast_to(self.loc, jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))

    @property
    def variance(self):
        return jnp.broadcast_to(jnp.square(self.scale),
                                jnp.broadcast_shapes(self.loc.shape,
                                                     self.scale.shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.base = Normal(loc, scale)

    def sample(self, shape=(), key=None):
        return jnp.exp(self.base.sample(shape, key))

    def log_prob(self, value):
        safe = jnp.where(value > 0, value, 1.0)  # support is (0, inf)
        lp = self.base.log_prob(jnp.log(safe)) - jnp.log(safe)
        return jnp.where(value > 0, lp, -jnp.inf)

    @property
    def mean(self):
        return jnp.exp(self.base.loc + jnp.square(self.base.scale) / 2)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(self._key(key), shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return jnp.square(self.high - self.low) / 12


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        enforce((logits is None) != (probs is None),
                "exactly one of logits/probs",
                error=InvalidArgumentError, op="Categorical")
        if probs is not None:
            probs = jnp.asarray(probs, jnp.float32)
            self.logits = jnp.log(probs / jnp.sum(probs, -1, keepdims=True))
        else:
            self.logits = jnp.asarray(logits, jnp.float32)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, -1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(self._key(key), self.logits,
                                      shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, -1)
        return jnp.take_along_axis(
            logp, value[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return -jnp.sum(jnp.exp(logp) * logp, -1)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = jnp.asarray(probs, jnp.float32)

    @property
    def probs(self):
        return self.probs_

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs_.shape
        return jax.random.bernoulli(self._key(key), self.probs_,
                                    shape).astype(jnp.float32)

    def log_prob(self, value):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

    @property
    def mean(self):
        return self.probs_

    @property
    def variance(self):
        return self.probs_ * (1 - self.probs_)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.rate.shape
        return jax.random.exponential(self._key(key), shape) / self.rate

    def log_prob(self, value):
        lp = jnp.log(self.rate) - self.rate * value
        return jnp.where(value >= 0, lp, -jnp.inf)  # support is [0, inf)

    def entropy(self):
        return 1.0 - jnp.log(self.rate)

    @property
    def mean(self):
        return 1.0 / self.rate


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.laplace(self._key(key),
                                                          shape)

    def log_prob(self, value):
        return (-jnp.abs(value - self.loc) / self.scale
                - jnp.log(2 * self.scale))

    def entropy(self):
        return 1.0 + jnp.log(2 * self.scale) + jnp.zeros_like(self.loc)


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.gumbel(self._key(key),
                                                         shape)

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)


# -- KL registry -------------------------------------------------------------
_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p: Normal, q: Normal):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p: Categorical, q: Categorical):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return jnp.sum(jnp.exp(logp) * (logp - logq), -1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p: Bernoulli, q: Bernoulli):
    pp = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
    return pp * (jnp.log(pp) - jnp.log(qq)) + (1 - pp) * (
        jnp.log1p(-pp) - jnp.log1p(-qq))


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p: Uniform, q: Uniform):
    # KL is finite only when support(p) ⊆ support(q)
    inside = (p.low >= q.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return jnp.where(inside, kl, jnp.inf)


# round-2 surface: more distributions + the transform family (must come
# last: _round2 imports the base classes from this module)
from ._round2 import *  # noqa: E402,F401,F403
from ._round2 import __all__ as _r2_all
__all__ += list(_r2_all)

# round-4: the last reference distribution the inventory named absent
from .lkj_cholesky import LKJCholesky  # noqa: E402
__all__ += ["LKJCholesky"]
