"""Numerics observability: in-program tensor health + host-side anomaly
detection and spike-triggered forensics (ISSUE 15).

The stack trains through five low-precision surfaces (fp8 delayed
scaling, three int8 error-feedback wires, an int8/fp8 KV pool) whose
whole correctness story is *bounded dither* — yet a saturating fp8
scale, a growing EF residual or a loss spike is invisible until a run
diverges. PR 10 built the *performance* measurement loop; this module is
its *numerics* twin, split the same way:

* **In-program stats** (device side, riding the telemetry ring exactly
  as every other builtin does — flags-off lowers byte-identical HLO):
  :class:`NumericsConfig` is the plan a model builder threads into
  ``hybrid_engine.build_train_step(numerics=)``. The ENGINE then
  registers the series below onto the telemetry config and computes the
  engine-side ones; the MODELS deposit the activation stats from their
  block scans through the pipeline aux channel + ``observe()``:

  - ``num_gnorm_l<i>``       per-stacked-layer gradient norm (engine,
    replication-aware — the global-norm clip's accounting per layer
    index; storage order under vpp, both MoE families summed per pair);
  - ``num_act_rms_l<i>`` / ``num_act_absmax_l<i>``  per-layer block-
    output activation rms / absmax (models; mean over microbatches,
    max over data shards — 1F1B dense path);
  - ``num_ef_comm`` / ``num_ef_moe`` / ``num_ef_zero3``  global norms of
    the three error-feedback residual carries (engine, from the same
    ``opt_state`` namespaces the wires thread);
  - ``num_fp8_sat_<site>`` / ``num_fp8_headroom_<site>``  per-GEMM-site
    scale saturation ratio (this step's observed amax over the scale's
    representable cap — > 1 means values clipped) and log2 headroom
    (engine, read from the delayed-scaling ``fp8_meta`` observations).

* **Host side**: :class:`NumericsMonitor` — windowed anomaly detectors
  (loss/grad-norm spike vs rolling median, per-layer grad/act spikes,
  EF-residual growth, fp8 saturation rate, nonfinite onset) emitting ONE
  reason-tagged ``numerics_anomaly`` JSONL event per episode and one
  bounded flight-recorder bundle gaining ``numerics.json`` (last-K
  per-layer stats + detector state). :class:`NumericsGuard` bundles a
  TelemetryHost + monitor into the ``run_resilient(numerics=)`` hook
  that can skip-step or rollback-to-last-checkpoint on confirmed
  divergence (``FLAGS_numerics_action``).

The serving side's numerics twin (KV-pool page-scale drift) lives in
``inference.serving`` — host-side gauges off the same ``FLAGS_numerics``
switch.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from statistics import median as _median
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["NumericsConfig", "numerics_from_flags", "resolve_numerics",
           "numerics_series", "EF_SERIES", "fp8_site_health",
           "DetectorConfig", "detector_from_flags", "NumericsMonitor",
           "NumericsGuard", "numerics_spike_check"]

# opt_state EF namespace -> telemetry series name (the engine registers
# the subset whose plans are live; tests fetch the carries and assert the
# series against independently recomputed norms)
EF_SERIES: Dict[str, str] = {"comm_ef": "num_ef_comm",
                             "moe_ef": "num_ef_moe",
                             "zero3_ef": "num_ef_zero3"}

# clamp on the log2 headroom series: an all-zero amax observation (site
# not yet exercised) would otherwise print log2(cap/tiny) ~ 40+
HEADROOM_CLAMP = 32.0


@dataclasses.dataclass
class NumericsConfig:
    """In-program tensor-health plan a model builder hands the engine.

    num_layers: stacked-block layer count (global dim-0 of the params'
        ``block_key`` subtree — ``cfg.num_layers`` for the dense models,
        layer PAIRS for GPT-MoE). 0 disables the per-layer series.
    act: the model deposits per-layer activation rms/absmax from its
        block scan (the builders enable this on the plain-1F1B dense
        path, where the pipeline aux channel exists; per-layer GRAD
        norms are engine-side and work under every schedule).
    block_key / pp_axis: where the stacked block subtree lives and which
        mesh axis shards its layer dim (dim 0) — the engine all-gathers
        the per-layer vector over it so the replicated telemetry row is
        identical on every rank.
    """
    num_layers: int = 0
    act: bool = False
    block_key: str = "blocks"
    pp_axis: str = "pp"

    def meta(self) -> Dict[str, Any]:
        return {"num_layers": int(self.num_layers), "act": bool(self.act),
                "block_key": self.block_key, "pp_axis": self.pp_axis}


def numerics_from_flags() -> bool:
    from ..flags import flag
    return bool(flag("numerics"))


def resolve_numerics(arg, *, num_layers: int, act: bool,
                     block_key: str = "blocks",
                     pp_axis: str = "pp") -> Optional[NumericsConfig]:
    """ONE resolution of a model builder's numerics= argument ("auto"
    reads FLAGS_numerics; bool forces; an explicit NumericsConfig wins)
    — gpt and llama both route through here so the flag semantics can
    never drift between families. None/off resolves to None and the
    build compiles bitwise-identically to one without the argument."""
    if isinstance(arg, NumericsConfig):
        return arg
    if arg is None:
        return None
    on = numerics_from_flags() if arg == "auto" else bool(arg)
    if not on:
        return None
    return NumericsConfig(num_layers=int(num_layers), act=bool(act),
                          block_key=block_key, pp_axis=pp_axis)


def numerics_series(ncfg: NumericsConfig, *,
                    ef_namespaces: Sequence[str] = (),
                    fp8_sites: Sequence[str] = ()) -> Tuple[str, ...]:
    """The telemetry series a numerics build registers — derived from the
    config + the engine's live plans alone, so the host decodes buffers
    with no side channel (the BUILTIN_SERIES discipline)."""
    names: List[str] = []
    for i in range(int(ncfg.num_layers)):
        names.append(f"num_gnorm_l{i}")
    if ncfg.act:
        for i in range(int(ncfg.num_layers)):
            names.append(f"num_act_rms_l{i}")
            names.append(f"num_act_absmax_l{i}")
    for ns in ef_namespaces:
        names.append(EF_SERIES[ns])
    for s in fp8_sites:
        names.append(f"num_fp8_sat_{s}")
        names.append(f"num_fp8_headroom_{s}")
    return tuple(names)


def fp8_site_health(amax_obs, scales, axes=()) -> Dict[str, Any]:
    """Per-site fp8 scale health from this step's amax observations vs
    the delayed scales the step USED (runs inside the compiled step; the
    engine merges the result into the telemetry row).

    axes: mesh axes to pmax the saturation / pmin the headroom over —
    the engine passes EVERY mesh axis. The amax observations are
    deliberately never pmax'd over the stacked pipeline axis (that
    would mix different layers' amaxes into the scale update), so each
    pp rank's local reduction only covers ITS layer stack; the
    replicated telemetry row must be rank-identical, and a clip on
    another rank's layers must still surface.

    * ``num_fp8_sat_<site>``: max over roles (and stacked layers) of
      observed_amax / (scale x fmax) — the fraction of the quantizer's
      representable range the step's largest value needed. > 1 means the
      e4m3/e5m2 cast CLIPPED this step (delayed scaling saturates for
      one step on a fresh outlier by design; a sustained rate is the
      anomaly, which the monitor detects).
    * ``num_fp8_headroom_<site>``: min over roles/layers of
      log2(scale x fmax / amax) — bits of range headroom left (clamped
      to +-HEADROOM_CLAMP; unexercised sites read the clamp).

    Pipelined observation note: the hybrid path's scale cotangents SUM
    over the pipeline's T ticks (an additive upper bound, quantization
    .fp8.update_fp8_meta) — saturation reads proportionally high there;
    the detectors compare against each series' own rolling history, so
    the constant factor cancels.
    """
    import jax.numpy as jnp
    from jax import lax
    from ..quantization.fp8 import role_fmax
    tiny = 1e-12
    axes = tuple(axes)
    out: Dict[str, Any] = {}
    for site, roles in amax_obs.items():
        sat, hr = [], []
        for role, a in roles.items():
            cap = (scales[site][role].astype(jnp.float32)
                   * role_fmax(role))
            af = jnp.maximum(a.astype(jnp.float32), 0.0)
            sat.append(jnp.max(af / jnp.maximum(cap, tiny)))
            hr.append(jnp.min(jnp.clip(
                jnp.log2(jnp.maximum(cap, tiny)
                         / jnp.maximum(af, tiny)),
                -HEADROOM_CLAMP, HEADROOM_CLAMP)))
        s = jnp.max(jnp.stack(sat))
        h = jnp.min(jnp.stack(hr))
        if axes:
            s = lax.pmax(s, axes)
            h = -lax.pmax(-h, axes)
        out[f"num_fp8_sat_{site}"] = s
        out[f"num_fp8_headroom_{site}"] = h
    return out


# ---------------------------------------------------------------------------
# Host side: windowed anomaly detection + forensics + the driver hook.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class DetectorConfig:
    """Windowed-detector knobs (host side only — nothing here touches
    compiled programs).

    window: rolling history per series (also the last-K depth of the
        ``numerics.json`` forensics snapshot).
    min_history: observations a series needs before its spike/growth
        detector arms (a cold start must not flag the first fetch).
    spike_factor: loss/grad-norm/activation spike threshold — fire when
        the new value exceeds the rolling MEDIAN by this factor (median,
        not mean: one prior spike must not mask the next).
    ef_growth_factor: EF-residual growth threshold vs rolling median.
    sat_threshold / sat_rate: an fp8 site is anomalous when more than
        ``sat_rate`` of its recent window observed saturation ratio
        > ``sat_threshold``.
    clear_obs: consecutive healthy observations that END an episode
        (one ``numerics_anomaly`` event + one bundle per episode).
    action: what a CONFIRMED episode asks the resilient driver to do —
        "none" (observe only), "skip" (reject diverging steps, the
        found_inf discipline) or "rollback" (reload the last committed
        checkpoint and re-train forward).
    confirm: anomalous observations inside one episode before the
        action fires (a single spiky fetch stays forensics-only).
    max_rollbacks: rollbacks the monitor will ever request (a bad data
        shard would otherwise loop the driver forever).
    """
    window: int = 32
    min_history: int = 8
    spike_factor: float = 4.0
    ef_growth_factor: float = 8.0
    sat_threshold: float = 1.0
    sat_rate: float = 0.5
    clear_obs: int = 16
    action: str = "none"
    confirm: int = 2
    max_rollbacks: int = 1


def detector_from_flags() -> DetectorConfig:
    from ..flags import flag
    return DetectorConfig(window=int(flag("numerics_window")),
                          spike_factor=float(flag("numerics_spike_factor")),
                          action=str(flag("numerics_action")))


class NumericsMonitor:
    """Windowed anomaly detection over the numerics telemetry series.

    Feed it per-step host losses (:meth:`note_loss`) and decoded
    telemetry rows (:meth:`ingest_row` — the :class:`NumericsGuard` does
    both). Each observation runs the detectors against that series' own
    rolling history; the FIRST anomalous observation opens an *episode*:
    one reason-tagged ``numerics_anomaly`` JSONL event, one bounded
    flight-recorder bundle (which gains ``numerics.json`` — the monitor
    registers weakly, so EVERY bundle from any crash path includes the
    numerics state). Further anomalous observations extend the episode
    silently; ``clear_obs`` consecutive healthy ones close it (with a
    ``numerics_recovered`` event) and re-arm detection.

    Duplicate-step protection: telemetry rows arrive one interval late,
    so the same step's loss may be seen twice (driver + ring) — each
    series ignores observations at or before its last-seen step.
    """

    def __init__(self, cfg: Optional[DetectorConfig] = None,
                 event_log=None):
        self.cfg = cfg or detector_from_flags()
        self._event_log = event_log
        k = max(int(self.cfg.window), 4)
        self._hist: Dict[str, deque] = {}
        self._last_step: Dict[str, int] = {}
        self._steps: deque = deque(maxlen=k)
        self.anomalies: List[Dict[str, Any]] = []
        self.rollbacks = 0
        self._episode: Optional[Dict[str, Any]] = None
        self._healthy = 0
        self._pending_action: Optional[str] = None
        from .flight_recorder import register_numerics_monitor
        register_numerics_monitor(self)

    # -- ingestion -----------------------------------------------------------
    def note_loss(self, step: int, loss: float) -> None:
        """Per-step host-observed loss (the driver's float(loss) —
        per-step granularity, one interval earlier than the ring)."""
        self._observe(int(step), {"loss": float(loss)})

    def ingest_row(self, step: int, values: Dict[str, float]) -> None:
        """One decoded telemetry row (series name -> value at `step`)."""
        self._observe(int(step), {k: float(v) for k, v in values.items()})

    # -- detectors -----------------------------------------------------------
    def _detect(self, name: str, v: float,
                hist: Sequence[float]) -> Optional[str]:
        cfg = self.cfg
        if not math.isfinite(v):
            return f"nonfinite_value:{name}"
        if name == "nonfinite_count":
            return "nonfinite" if v > 0 else None
        spike_kind = None
        if name in ("loss", "grad_norm"):
            spike_kind = f"{name}_spike"
        elif name.startswith("num_gnorm_l"):
            spike_kind = f"layer_grad_spike:{name}"
        elif name.startswith("num_act_absmax_l"):
            spike_kind = f"act_spike:{name}"
        if spike_kind is not None:
            if len(hist) < max(cfg.min_history, 1):
                return None
            med = _median(hist)
            if v > max(med * cfg.spike_factor, med + 1e-9):
                return spike_kind
            return None
        if name.startswith("num_ef_"):
            if len(hist) < max(cfg.min_history, 1):
                return None
            med = _median(hist)
            if v > 1e-12 and v > max(med * cfg.ef_growth_factor,
                                     med + 1e-12):
                return f"ef_growth:{name}"
            return None
        if name.startswith("num_fp8_sat_"):
            recent = list(hist)[-(cfg.window - 1):] + [v]
            if len(recent) < cfg.min_history:
                return None
            rate = sum(1 for x in recent
                       if x > cfg.sat_threshold) / len(recent)
            if rate >= cfg.sat_rate:
                return f"fp8_saturation:{name}"
            return None
        return None

    def _observe(self, step: int, values: Dict[str, float]) -> None:
        reasons: List[str] = []
        trig: Dict[str, float] = {}
        # unique observed steps (the ring's rows lag the per-step host
        # loss, so the same step arrives twice — forensics readers
        # correlating snapshot windows must not see duplicates)
        if step not in self._steps:
            self._steps.append(step)
        for name, v in values.items():
            if step <= self._last_step.get(name, -1):
                continue  # duplicate (ring row behind the host loss)
            self._last_step[name] = step
            h = self._hist.setdefault(
                name, deque(maxlen=max(int(self.cfg.window), 4)))
            r = self._detect(name, v, h)
            h.append(v)
            if r is not None:
                reasons.append(r)
                trig[name] = v
        if reasons:
            self._healthy = 0
            if self._episode is None:
                self._open_episode(step, reasons, trig)
            else:
                ep = self._episode
                ep["hits"] += 1
                ep["last_step"] = step
                for r in reasons:
                    if r not in ep["reasons"]:
                        ep["reasons"].append(r)
            self._arm_action()
        else:
            self._healthy += 1
            if (self._episode is not None
                    and self._healthy >= self.cfg.clear_obs):
                self._emit("numerics_recovered",
                           step=step,
                           first_step=self._episode["step"],
                           reasons=self._episode["reasons"])
                self._episode = None

    def _open_episode(self, step: int, reasons: List[str],
                      trig: Dict[str, float]) -> None:
        self._episode = {"step": step, "last_step": step, "hits": 1,
                         "reasons": list(reasons), "values": dict(trig)}
        anomaly = {"step": step, "reasons": list(reasons),
                   "values": dict(trig)}
        self.anomalies.append(anomaly)
        self._emit("numerics_anomaly", step=step,
                   reason=reasons[0], reasons=reasons, values=trig)
        from .flight_recorder import maybe_dump
        anomaly["bundle"] = maybe_dump(
            "numerics_anomaly",
            extra={"step": step, "reasons": reasons, "values": trig})

    def _arm_action(self) -> None:
        cfg = self.cfg
        ep = self._episode
        if cfg.action == "none" or ep is None:
            return
        if ep["hits"] < cfg.confirm or ep.get("actioned"):
            return
        if cfg.action == "rollback" and self.rollbacks >= cfg.max_rollbacks:
            return
        ep["actioned"] = True
        self._pending_action = cfg.action
        if cfg.action == "rollback":
            self.rollbacks += 1

    def consume_action(self) -> Optional[str]:
        """The driver's per-step query: "skip"/"rollback" once armed by a
        confirmed episode, else None. Skip re-arms on the next anomalous
        observation of the same episode; rollback is budgeted by
        max_rollbacks across the whole run."""
        a, self._pending_action = self._pending_action, None
        if a == "skip" and self._episode is not None:
            # keep skipping while the episode stays confirmed — the next
            # anomalous observation re-arms anyway; a healthy one clears
            self._episode["actioned"] = False
        return a

    def on_rollback(self) -> None:
        """The driver rolled the run back: histories describe a future
        that no longer exists — reset windows and close the episode."""
        self._hist.clear()
        self._last_step.clear()
        self._steps.clear()
        self._episode = None
        self._healthy = 0
        self._pending_action = None

    def refund_rollback(self) -> None:
        """The driver found NO checkpoint to roll back to: return the
        budget (charged at arm time) and un-action the episode so a
        later confirmation — once a commit exists — can re-arm."""
        self.rollbacks = max(self.rollbacks - 1, 0)
        if self._episode is not None:
            self._episode["actioned"] = False

    # -- forensics -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Bounded host state for flight-recorder bundles
        (``numerics.json``): last-K values of every tracked series
        (per-layer stats included), detector config + episode state, the
        anomaly history."""
        return {
            "config": dataclasses.asdict(self.cfg),
            "steps": list(self._steps),
            "series": {k: [float(v) for v in d]
                       for k, d in sorted(self._hist.items())},
            "episode": self._episode,
            "healthy_streak": self._healthy,
            "rollbacks": self.rollbacks,
            "anomalies": self.anomalies[-8:],
        }

    def _emit(self, event: str, **fields) -> None:
        log = self._event_log
        if log is None:
            from .events import get_event_log
            log = get_event_log()
        if log is not None:
            log.emit(event, **fields)


class NumericsGuard:
    """The ``run_resilient(numerics=)`` hook: one object bundling the
    telemetry fetch (TelemetryHost), the anomaly monitor and the
    action policy. Build the ENGINE first (it registers the numerics
    series onto the telemetry config — ``init_state.telemetry_config``
    is the resolved config for flag-driven builds), then::

        guard = NumericsGuard(init_state.telemetry_config)
        run_resilient(step_fn, state, ..., numerics=guard)

    ``after_step`` feeds the per-step loss, polls the ring on the
    interval cadence, runs the detectors and returns the confirmed
    action ("skip"/"rollback") or None. `prom` additionally exports the
    decoded grad-norm/loss as live gauges (TelemetryHost's export)."""

    def __init__(self, telemetry, monitor: Optional[NumericsMonitor]
                 = None, *, prom=None, event_log=None):
        from .metrics import TelemetryHost
        self.host = TelemetryHost(telemetry, event_log=event_log,
                                  prom=prom)
        self.monitor = monitor or NumericsMonitor(event_log=event_log)

    @staticmethod
    def _carrier(state):
        """The engine opt-state carry holding the telemetry buffer —
        the driver's state dict nests it one level down."""
        if not isinstance(state, dict):
            return None
        if "telemetry" in state:
            return state
        for v in state.values():
            if isinstance(v, dict) and "telemetry" in v:
                return v
        return None

    def _feed(self, new: Optional[Dict[str, List[float]]]) -> None:
        if not new:
            return
        n = len(next(iter(new.values())))
        steps = self.host.steps[-n:]
        for j, s in enumerate(steps):
            self.monitor.ingest_row(s, {k: v[j] for k, v in new.items()})

    def after_step(self, state, step: int,
                   loss: Optional[float] = None) -> Optional[str]:
        if loss is not None:
            self.monitor.note_loss(step, float(loss))
        carrier = self._carrier(state)
        if carrier is not None:
            self._feed(self.host.poll(carrier, step))
        return self.monitor.consume_action()

    def flush(self, state) -> None:
        carrier = self._carrier(state)
        if carrier is not None:
            self._feed(self.host.flush(carrier))

    def on_rollback(self, state=None) -> None:
        """The driver restored `state` from a checkpoint: reset the
        detectors AND rewind the host to the restored carry's ring
        count — without the rewind the ingest watermark (set while
        polling the abandoned timeline) would silently drop every
        replayed row and leave the detectors blind exactly when
        re-divergence must be caught."""
        self.monitor.on_rollback()
        carrier = self._carrier(state) if state is not None else None
        if carrier is not None:
            import jax
            self.host.rewind(int(jax.device_get(
                carrier["telemetry"]["count"])))

    def on_rollback_unavailable(self) -> None:
        """The driver had no checkpoint to roll back to."""
        self.monitor.refund_rollback()


# ---------------------------------------------------------------------------
# The CI/dryrun leg: spike-injected run -> detection + bundle asserted.
# ---------------------------------------------------------------------------
def numerics_spike_check(workdir: str, *, steps: int = 20,
                         spike_at: int = 14,
                         mesh_shape: Optional[Dict[str, int]] = None
                         ) -> Dict[str, Any]:
    """End-to-end numerics acceptance (shared by the ``__graft_entry__``
    dryrun leg and tier-1): a gpt-tiny hybrid run with numerics
    telemetry on, driven by ``run_resilient`` with a NumericsGuard,
    while the ``numerics/spike`` faults-grammar site injects one
    host-observed loss spike at step `spike_at`. Asserts EXACTLY one
    ``numerics_anomaly`` JSONL event and one flight-recorder bundle
    whose ``numerics.json`` carries the per-layer stats. Single-process
    (the degraded form of the 2-proc leg — the detectors and forensics
    are host-local either way). Returns a summary dict."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.resilience import run_resilient
    from paddle_tpu.models import gpt as G
    from .events import EventLog, set_event_log
    from .flight_recorder import FlightRecorder, set_flight_recorder
    from .metrics import TelemetryConfig

    mesh_shape = mesh_shape or {"dp": 2, "pp": 1, "mp": 1}
    mesh = dist.build_mesh(mesh_shape)
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=2, max_seq_len=32, dtype=jnp.float32,
                      param_dtype=jnp.float32)
    tcfg = TelemetryConfig(interval=4, strict=False)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3)
    step, shard_params, init_state = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=1, telemetry=tcfg,
        numerics=True)
    p = shard_params(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    s = init_state(p)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))

    def step_fn(st, i):
        del i
        np_, ns_, loss = step(st["params"], st["opt"], tokens, labels,
                              jnp.float32(1e-3))
        return {"params": np_, "opt": ns_}, loss

    log_path = os.path.join(workdir, "numerics.jsonl")
    crash_dir = os.path.join(workdir, "crash")
    log = EventLog(log_path)
    prev_log = set_event_log(log)
    prev_rec = set_flight_recorder(FlightRecorder(crash_dir))
    prev_fault = paddle.get_flags(["FLAGS_fault_inject"])
    try:
        paddle.set_flags(
            {"FLAGS_fault_inject": f"numerics/spike:{spike_at}"})
        guard = NumericsGuard(
            tcfg, NumericsMonitor(
                DetectorConfig(window=16, min_history=4, spike_factor=4.0,
                               clear_obs=4),
                event_log=log),
            event_log=log)
        state, info = run_resilient(
            step_fn, {"params": p, "opt": s}, steps=steps,
            ckpt_dir=os.path.join(workdir, "ckpt"), ckpt_every=0,
            numerics=guard)
    finally:
        paddle.set_flags(prev_fault)
        set_event_log(prev_log)
        set_flight_recorder(prev_rec)
        log.close()

    import json
    events = [json.loads(l) for l in open(log_path, encoding="utf-8")]
    anomalies = [e for e in events if e["event"] == "numerics_anomaly"]
    assert len(anomalies) == 1, \
        f"expected exactly one numerics_anomaly event, got {anomalies}"
    assert any(r.startswith("loss_spike") for r in anomalies[0]["reasons"])
    bundles = sorted(d for d in os.listdir(crash_dir)
                     if d.startswith("flight_"))
    assert len(bundles) == 1, bundles
    nj = os.path.join(crash_dir, bundles[0], "numerics.json")
    assert os.path.exists(nj), "bundle missing numerics.json"
    with open(nj, encoding="utf-8") as f:
        forensic = json.load(f)
    mon = next(iter(forensic.values()))
    per_layer = [k for k in mon["series"] if k.startswith("num_gnorm_l")]
    assert len(per_layer) == cfg.num_layers, mon["series"].keys()
    assert mon["anomalies"], "monitor snapshot lost the anomaly"
    assert info["completed_steps"] == steps
    return {"anomaly_step": anomalies[0]["step"],
            "reasons": anomalies[0]["reasons"],
            "layers": len(per_layer),
            "bundle": bundles[0]}
