"""Auto-parallel planner: analytic config search over the hybrid engine's
real flag surface.

The repo's asset is that observability carries *measurement-validated*
analytic models — per-token FLOPs (``observability.flops``), mp-axis wire
bytes (``mp_wire_bytes``), dp bucket-plan accounting, the ep all-to-all
wire model (``ep_a2a_wire_bytes``) and the (M, P, V, schedule) pipeline
tick formulas the telemetry tests re-derive. This module turns them into
a search: given a model config and a mesh size, enumerate
:class:`PlanCandidate` configurations of ``build_hybrid_train_step``
under divisibility/shape constraints, score each with a three-part cost
model (compute seconds incl. the schedule bubble, exposed-communication
seconds with per-mode overlap discounts, per-collective dispatch
overhead), prune candidates whose analytic per-chip HBM exceeds the
budget, and emit the top-k as ready-to-run engine kwargs.

The MLPerf TPU-pod scaling study (arXiv:1909.09756) is this search run by
hand across pod slices; the reference's ``InferSpmd``/spmd_rules layer is
Paddle's version of the capability. The T3 framing (arXiv:2401.16677)
supplies the overlap model: a collective adjacent to a GEMM hides under
it up to a mode-dependent *hidable fraction*.

fp8 candidates are enumerable (``fp8_options=(False, True)``) but scored
compute-NEUTRAL: no bench round has recorded the fp8 MXU speedup on
hardware yet (the CPU emulation is ~neutral too), and inventing a rate
multiplier would break the model's measurement-validated contract — the
constraint checker still guarantees emitted fp8 configs compose legally.

Model constants (the ``_HIDE_*`` tables, ``gemm_efficiency``) are
calibrated against this repo's recorded rounds — BASELINE.md round 5/6
(mp_overlap temp-bytes + the CPU-proxy op-count ordering), the PR 2
bucketed-overlap deltas — and are *re-calibratable from measurement*:
:meth:`CostModel.calibrate` fits the compute rate and per-collective
launch overhead to a measured anchor sweep (``auto_tuner.sweep``), which
is how the CPU-smoke validation closes the loop between predicted and
measured step times.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["PlanCandidate", "ModelSpec", "HardwareProfile", "profile_for",
           "KNOWN_PROFILES", "CostModel", "Prediction",
           "generate_plan_candidates", "plan", "PlanReport", "ScoredPlan",
           "model_config_by_name", "PLAN_MODELS", "HIDE_KEYS",
           "profile_to_json", "profile_from_json", "resolve_profile"]

SCHEDULES = ("1f1b", "zbh1", "interleaved")
MP_OVERLAP_MODES = (None, "seq_parallel", "collective_matmul")

# T3-style hidable fractions: the share of a mode's wire time the
# adjacent compute can hide (exposed = wire * (1 - hide)). Calibrated to
# the recorded rounds: plain allreduce TP leaves most of the wire exposed
# (the 43.3% multichip MFU of BENCH_r05's secondary), seq-parallel's
# AG/RS pairs schedule async against the GEMMs, the ring collective
# matmul interleaves chunk transfers with partial products (PR 5).
# These are the TABLE defaults; a measured HardwareProfile (the
# observability.profile_reader capture pipeline) carries per-mode
# overrides in its ``hide`` dict under the HIDE_KEYS vocabulary, and
# measured entries WIN over both the table and the overlap_capable
# zeroing — attribution from the observed timeline beats the heuristic.
_HIDE_MP = {None: 0.2, "allreduce": 0.2,
            "seq_parallel": 0.55, "collective_matmul": 0.85}
# dp gradient sync: the monolithic end-of-backward pmean serializes
# against the optimizer; size-targeted buckets issued in backward order
# hide under later backward compute (PR 2's measured win).
_HIDE_DP_MONOLITHIC = 0.0
_HIDE_DP_BUCKETED = 0.7
# zero3 per-block param all-gather: the scan_gather prefetch issues block
# i+1's transfer beside block i's GEMMs (the engine's default,
# FLAGS_zero3_overlap_ag) — most of the wire hides; without the prefetch
# the gather sits at the top of each block's critical path.
_HIDE_DP_ZERO3_AG = {True: 0.8, False: 0.3}
# ep all-to-alls: chunk-overlapped exchange (FLAGS_moe_overlap) hides
# chunk j+1's transfer behind chunk j's expert GEMM.
_HIDE_EP = {False: 0.1, True: 0.6}
_HIDE_PP = 0.0  # pipeline ppermutes sit on the critical path

# the hide-override vocabulary a measured profile may carry (profile
# capture labels its windows with these; CostModel consults them)
HIDE_KEYS = ("mp:allreduce", "mp:seq_parallel", "mp:collective_matmul",
             "dp:monolithic", "dp:bucketed", "dp:zero3_ag", "ep:plain",
             "ep:overlap", "pp")


# ---------------------------------------------------------------------------
# Hardware profiles.
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class HardwareProfile:
    """Per-chip rates the cost model converts bytes/flops into seconds
    with. ``gemm_efficiency`` is the achievable fraction of peak on the
    dense stack (the measured-or-peak rate: ~0.6 is this repo's measured
    flagship MFU); ``collective_launch_s`` is the per-collective dispatch
    overhead — microseconds on TPU, ~fractions of a millisecond on the
    CPU smoke mesh where collectives are scheduler ops, which is exactly
    why the CPU proxy ranks mp modes by op count (BASELINE.md round 6)
    while a real pod ranks them by exposed wire."""
    name: str = "tpu-v5e"
    peak_flops: float = 197e12
    hbm_gb: float = 16.0
    ici_gbs: float = 45.0
    collective_launch_s: float = 2e-6
    gemm_efficiency: float = 0.6
    # whether the backend's scheduler can actually hide collectives under
    # adjacent compute (the latency-hiding/async-collective machinery).
    # False on the CPU smoke mesh: every mode's wire is equally exposed
    # there, so configs rank by collective COUNT — the measured round-6
    # CPU proxy ordering (allreduce < sp < ring) — while TPU profiles
    # rank by exposed wire after the T3 hidable-fraction discount.
    overlap_capable: bool = True
    # measured per-mode hidable-fraction overrides (HIDE_KEYS vocabulary),
    # filled by observability.profile_reader.derive_hardware_profile; a
    # key present here WINS over the table constant AND the
    # overlap_capable zeroing (it IS the measurement). compare=False so
    # frozen-dataclass hashing never touches the dict.
    hide: Optional[Dict[str, float]] = dataclasses.field(
        default=None, compare=False)
    source: str = dataclasses.field(default="table", compare=False)


KNOWN_PROFILES: Dict[str, HardwareProfile] = {
    "tpu-v5e": HardwareProfile("tpu-v5e", 197e12, 16.0, 45.0, 2e-6, 0.6),
    "tpu-v5p": HardwareProfile("tpu-v5p", 459e12, 95.0, 90.0, 2e-6, 0.6),
    "tpu-v4": HardwareProfile("tpu-v4", 275e12, 32.0, 45.0, 2e-6, 0.6),
    "tpu-v6e": HardwareProfile("tpu-v6e", 918e12, 32.0, 90.0, 2e-6, 0.6),
    "tpu-v3": HardwareProfile("tpu-v3", 123e12, 16.0, 35.0, 2e-6, 0.6),
    # CPU smoke mesh: nominal 1e12 "peak" (flops.peak_flops convention),
    # collectives are cheap memcpys but each costs real scheduling time,
    # and nothing hides under anything (overlap_capable=False).
    "cpu": HardwareProfile("cpu", 1e12, 4.0, 8.0, 5e-4, 0.5,
                           overlap_capable=False),
}


def profile_for(devices=None, *, hbm_gb: Optional[float] = None
                ) -> HardwareProfile:
    """Profile of the current backend (flag/CLI ``--hbm-gb`` overrides the
    budget — FLAGS_auto_parallel_hbm_gb is read by the CLI/launcher)."""
    import jax
    devices = devices if devices is not None else jax.devices()
    kind = (getattr(devices[0], "device_kind", "") or "").lower()
    plat = devices[0].platform.lower()
    name = "cpu"
    if plat == "tpu":
        for key, prof in (("v5 lite", "tpu-v5e"), ("v5litepod", "tpu-v5e"),
                          ("v5e", "tpu-v5e"), ("v5p", "tpu-v5p"),
                          ("v6", "tpu-v6e"), ("v4", "tpu-v4"),
                          ("v3", "tpu-v3")):
            if key in kind:
                name = prof
                break
        else:
            name = "tpu-v5e"
    prof = KNOWN_PROFILES[name]
    if hbm_gb is not None and hbm_gb > 0:
        prof = dataclasses.replace(prof, hbm_gb=float(hbm_gb))
    return prof


def profile_to_json(profile: HardwareProfile) -> Dict[str, Any]:
    return dataclasses.asdict(profile)


def profile_from_json(d: Dict[str, Any]) -> HardwareProfile:
    """HardwareProfile from a dict (the ``hardware_profile`` payload the
    profile-capture pipeline serializes); unknown keys are ignored so the
    format can grow."""
    fields = {f.name for f in dataclasses.fields(HardwareProfile)}
    kw = {k: v for k, v in d.items() if k in fields}
    if kw.get("hide") is not None:
        kw["hide"] = {str(k): float(v) for k, v in kw["hide"].items()}
    return HardwareProfile(**kw)


def resolve_profile(spec: Optional[str], *,
                    hbm_gb: Optional[float] = None) -> HardwareProfile:
    """CLI/launcher profile resolution: a KNOWN_PROFILES name, a path to
    a measured-profile JSON (the observability.profile_reader artifact —
    anything ending in .json or naming an existing file), or None to
    detect from the current backend."""
    import os
    if spec:
        if spec in KNOWN_PROFILES:
            prof = KNOWN_PROFILES[spec]
        elif spec.endswith(".json") or os.path.exists(spec):
            from ...observability.profile_reader import load_profile_json
            prof = load_profile_json(spec)
        else:
            raise ValueError(
                f"unknown profile {spec!r}: not one of "
                f"{sorted(KNOWN_PROFILES)} and not a profile JSON path")
        if hbm_gb is not None and hbm_gb > 0:
            prof = dataclasses.replace(prof, hbm_gb=float(hbm_gb))
        return prof
    return profile_for(hbm_gb=hbm_gb)


# ---------------------------------------------------------------------------
# The candidate: one point on the hybrid flag surface.
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class PlanCandidate:
    """One ``build_hybrid_train_step`` configuration over the REAL flag
    surface (the axes the hybrid engine actually mounts: dp/ep/pp/mp — the
    old tuner's "sharding"/"sep" vocabulary is gone).

    ``schedule`` uses the planner vocabulary {"1f1b", "zbh1",
    "interleaved"}; "interleaved" requires ``vpp > 1`` and maps to
    ``virtual_pp=vpp`` on the engine. ``remat`` records the activation
    policy the cost/memory model assumes — the hybrid pipeline always
    checkpoints each stage (``jax.checkpoint`` around the stage body), so
    generated candidates carry "full"; it is NOT an engine kwarg.
    ``moe_*`` fields only apply to MoE configs (cfg.moe_num_experts > 0).
    """
    dp: int = 1
    mp: int = 1
    pp: int = 1
    ep: int = 1
    vpp: int = 1
    schedule: str = "1f1b"
    micro_batches: int = 1
    # ZeRO stage over dp (replaces the old boolean `zero1`): 0 off;
    # 1 dp-sharded optimizer state; 2 additionally accounts the grad
    # buffer dp-sharded (same collectives as 1 in the fused engine — an
    # HBM-rule axis); 3 params dp-sharded at rest, per-block all-gather
    # on use (the exposed-comm term below prices that AG, discounted by
    # the scan_gather prefetch's hidable fraction)
    zero_stage: int = 0
    remat: str = "full"
    fp8: bool = False
    comm_bucket_mb: float = 0.0
    mp_overlap: Optional[str] = None
    # fused Pallas flash attention in the block bodies
    # (build_hybrid_train_step(flash_attention=...)): trades MORE executed
    # attention flops (the two-kernel backward re-derives scores tiles)
    # for O(S) instead of O(S²) rematted-activation HBM — the cost model
    # scores both sides, so flash candidates win exactly where the scores
    # memory is the binding constraint (long S under a tight HBM budget)
    flash_attention: bool = False
    moe_index: bool = True
    moe_quantize: bool = False
    moe_overlap: bool = False

    @property
    def world(self) -> int:
        return self.dp * self.mp * self.pp * self.ep

    def mesh_dims(self) -> Dict[str, int]:
        """Axis -> degree in the engine's mount order (outer -> inner;
        the axes ``build_hybrid_train_step`` names: dp, ep, pp, mp —
        degree-1 axes are kept so shardings can name them)."""
        return {"dp": self.dp, "ep": self.ep, "pp": self.pp, "mp": self.mp}

    def build_mesh(self, devices=None):
        """jax Mesh for this candidate on the first ``world`` devices."""
        import jax
        from ..topology import build_mesh
        devices = list(devices if devices is not None else jax.devices())
        return build_mesh(self.mesh_dims(), devices[:self.world])

    def engine_kwargs(self, *, family: str = "gpt",
                      global_batch: Optional[int] = None,
                      seq: Optional[int] = None) -> Dict[str, Any]:
        """Ready-to-run ``build_hybrid_train_step(cfg, mesh, opt, **kw)``
        kwargs. Everything is EXPLICIT (never "auto") so a plan is
        reproducible regardless of ambient FLAGS_*. The llama builder
        exposes a subset of the surface (no schedule/comm_overlap/moe
        kwargs); candidates outside it are never generated for llama."""
        kw: Dict[str, Any] = {
            "num_microbatches": self.micro_batches,
            "virtual_pp": self.vpp,
            "zero_stage": int(self.zero_stage),
            "fp8": bool(self.fp8),
            "telemetry": None,
            "mp_overlap": self.mp_overlap,
            "flash_attention": bool(self.flash_attention),
        }
        if self.zero_stage >= 3:
            # pin the gather knobs the cost model scored (prefetch on,
            # unquantized) so a plan is reproducible regardless of
            # ambient FLAGS_zero3_* — the same explicitness rule as
            # every other kwarg here (both builders accept zero3=)
            from ..comm_overlap import Zero3Config
            kw["zero3"] = Zero3Config()
        if family == "gpt":
            from ..comm_overlap import CommOverlapConfig, MoeDispatchConfig
            kw["schedule"] = "ZBH1" if self.schedule == "zbh1" else "1F1B"
            kw["comm_overlap"] = (
                CommOverlapConfig(bucket_mb=self.comm_bucket_mb)
                if self.comm_bucket_mb > 0 else None)
            # always explicit: the engine only consumes this when the
            # config is MoE, and an "auto" default would re-open the
            # flag-surface dependence plans exist to pin down
            kw["moe_dispatch"] = MoeDispatchConfig(
                index=self.moe_index, quantize=self.moe_quantize,
                overlap=self.moe_overlap)
            if self.moe_quantize:
                if global_batch is None or seq is None:
                    raise ValueError(
                        "a quantized-a2a candidate sizes its error-feedback "
                        "residuals at build time: pass global_batch and seq "
                        "to engine_kwargs()")
                kw["moe_ef_tokens"] = (global_batch // (self.dp * self.ep),
                                       seq)
        return kw

    def __str__(self):
        parts = [f"dp{self.dp}"]
        if self.ep > 1:
            parts.append(f"ep{self.ep}")
        if self.pp > 1:
            parts.append(f"pp{self.pp}")
        if self.mp > 1:
            parts.append(f"mp{self.mp}")
        s = "x".join(parts) + f" {self.schedule}"
        if self.vpp > 1:
            s += f"v{self.vpp}"
        s += f" M{self.micro_batches}"
        if self.zero_stage:
            s += f" zero{self.zero_stage}"
        if self.fp8:
            s += " fp8"
        if self.comm_bucket_mb > 0:
            s += f" bkt{self.comm_bucket_mb:g}"
        if self.mp_overlap:
            s += " " + {"seq_parallel": "sp",
                        "collective_matmul": "ring"}.get(
                str(self.mp_overlap), str(self.mp_overlap))
        if self.flash_attention:
            s += " flash"
        if self.ep > 1 or self.moe_quantize or self.moe_overlap:
            s += " moe:" + ("i" if self.moe_index else "d") \
                + ("q" if self.moe_quantize else "") \
                + ("o" if self.moe_overlap else "")
        return s


# ---------------------------------------------------------------------------
# The model's shape, parameter layout and flop structure.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ModelSpec:
    """Everything the cost/memory model needs about a model config,
    extracted ONCE (the per-leaf (shape, dtype, spec) table comes from the
    model's own ``init_hybrid_params``/``hybrid_param_specs`` via
    eval_shape — no buffers)."""
    family: str
    cfg: Any
    hidden: int
    layers: int
    ffn: int
    vocab: int
    heads: int
    act_itemsize: int
    param_itemsize: int
    n_block_params: int       # matmul params inside the pipelined blocks
    n_head_params: int        # LM head (outside the pipeline/remat)
    moe_experts: int = 0
    leaves: List[Tuple[int, int, Tuple, Tuple]] = dataclasses.field(
        default_factory=list)  # (n_elems, itemsize, spec_axes, shape)

    @classmethod
    def from_config(cls, cfg, family: str = "gpt") -> "ModelSpec":
        import jax
        import jax.numpy as jnp
        import numpy as np
        if family == "gpt":
            from ...models import gpt as M
        elif family == "llama":
            from ...models import llama as M
        else:
            raise ValueError(f"unknown model family {family!r}")
        pshape = jax.eval_shape(
            lambda: M.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
        specs = M.hybrid_param_specs(cfg)
        from jax.sharding import PartitionSpec as P

        leaves: List[Tuple[int, int, Tuple, Tuple]] = []

        def one(sp, s):
            axes = []
            for d, e in enumerate(tuple(sp or ())):
                if e is None:
                    continue
                axes.append((d, tuple(e) if isinstance(e, tuple) else (e,)))
            leaves.append((int(np.prod(s.shape)),
                           jnp.dtype(s.dtype).itemsize, tuple(axes),
                           tuple(s.shape)))
            return 0

        jax.tree.map(one, specs, pshape,
                     is_leaf=lambda x: x is None or isinstance(x, P))

        H, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
        if family == "gpt":
            FF = cfg.ffn_hidden
            heads = cfg.num_heads
            attn_p = 4 * H * H
            ffn_p = 2 * H * FF
            moe_e = getattr(cfg, "moe_num_experts", 0)
            n_ffn_layers = (L // 2) if moe_e > 0 else L
            n_block = L * attn_p + n_ffn_layers * ffn_p
        else:
            FF = cfg.intermediate_size
            heads = cfg.num_heads
            d = cfg.head_dim
            kv = cfg.num_kv_heads * d
            n_block = L * (2 * H * H + 2 * H * kv + 3 * H * FF)
            moe_e = 0
        return cls(family=family, cfg=cfg, hidden=H, layers=L, ffn=FF,
                   vocab=V, heads=heads,
                   act_itemsize=jnp.dtype(cfg.dtype).itemsize,
                   param_itemsize=jnp.dtype(cfg.param_dtype).itemsize,
                   n_block_params=n_block, n_head_params=H * V,
                   moe_experts=moe_e, leaves=leaves)

    @property
    def moe_on(self) -> bool:
        return self.moe_experts > 0


def _shard_product(spec_axes, sizes: Dict[str, int]) -> int:
    prod = 1
    for _, axes in spec_axes:
        for a in axes:
            prod *= sizes.get(a, 1)
    return prod


def _leaf_dp_shardable(shape, spec_axes, dp: int) -> bool:
    """Mirror of hybrid_engine.zero_dims (the ONE per-leaf
    dp-shardability rule): the first dim with no mesh axis whose extent
    divides dp (and is >= dp) shards the optimizer state (stage >= 1),
    the grad buffer (stage >= 2) and the params themselves (stage 3)
    over dp."""
    sharded_dims = {d for d, _ in spec_axes}
    for d, extent in enumerate(shape):
        if d in sharded_dims:
            continue
        if extent % dp == 0 and extent >= dp:
            return True
    return False


# ---------------------------------------------------------------------------
# Candidate generation under the engine's real constraints.
# ---------------------------------------------------------------------------
def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def check_candidate(c: PlanCandidate, spec: ModelSpec, *, world: int,
                    global_batch: int, seq: int) -> Optional[str]:
    """The ONE copy of the engine's composition/divisibility rules the
    generator and the CLI both consult. Returns a prune reason, or None
    when ``build_hybrid_train_step(**engine_kwargs)`` will accept the
    candidate."""
    cfg = spec.cfg
    if c.world != world:
        return f"needs {c.world} devices, mesh has {world}"
    if c.schedule not in SCHEDULES:
        return f"unknown schedule {c.schedule!r}"
    if c.zero_stage not in (0, 1, 2, 3):
        return f"zero_stage must be 0/1/2/3, got {c.zero_stage}"
    if c.zero_stage and c.dp <= 1:
        # the engine degenerates fine at dp=1, but a size-1 shard axis
        # buys nothing — pruning it keeps the ranked list free of
        # score-tied duplicates (the launcher trial-runs only top-k)
        return "zero_stage shards over dp: needs dp > 1"
    if c.mp_overlap not in MP_OVERLAP_MODES:
        return f"unknown mp_overlap mode {c.mp_overlap!r} " \
               f"(one of {MP_OVERLAP_MODES})"
    if (c.schedule == "interleaved") != (c.vpp > 1):
        return "interleaved iff vpp > 1"
    if c.schedule != "1f1b" and c.pp <= 1:
        return f"{c.schedule} needs a pipeline (pp > 1): zbh1's split " \
               "backward and the interleaved chunk wrap only buy bubble"
    if spec.layers % (c.pp * c.vpp) != 0:
        return f"layers {spec.layers} not divisible by pp*vpp " \
               f"{c.pp * c.vpp}"
    if c.vpp > 1 and c.micro_batches < c.pp:
        return "interleaved schedule needs micro_batches >= pp"
    if spec.heads % c.mp != 0:
        return f"heads {spec.heads} not divisible by mp {c.mp}"
    if spec.vocab % c.mp != 0:
        return f"vocab {spec.vocab} not divisible by mp {c.mp}"
    if spec.family == "llama":
        if cfg.num_kv_heads % c.mp != 0:
            return f"kv heads {cfg.num_kv_heads} not divisible by mp {c.mp}"
        if c.schedule == "zbh1":
            return "llama builder exposes 1f1b/interleaved only"
        if c.comm_bucket_mb > 0:
            return "llama builder has no comm_overlap kwarg (flag-driven)"
        if c.ep > 1 or c.moe_quantize or c.moe_overlap:
            return "llama has no MoE path"
    replicas = c.dp * c.ep
    if global_batch % replicas != 0:
        return f"global batch {global_batch} not divisible by dp*ep " \
               f"{replicas}"
    b_rank = global_batch // replicas
    if b_rank % c.micro_batches != 0:
        return f"per-rank batch {b_rank} not divisible by " \
               f"micro_batches {c.micro_batches}"
    if c.mp_overlap is not None:
        if c.mp <= 1:
            return "mp_overlap needs mp > 1"
        if seq % c.mp != 0:
            return f"sequence parallelism needs seq {seq} divisible by " \
                   f"mp {c.mp}"
    if c.fp8:
        if c.schedule != "1f1b" or c.vpp > 1:
            return "fp8 delayed scaling supports the 1F1B schedule only"
        if c.mp_overlap == "collective_matmul":
            return "fp8 x ring collective-matmul sums partial amax " \
                   "observations"
        if c.comm_bucket_mb > 0:
            return "fp8 is not composed with comm_overlap"
    if c.flash_attention:
        head_dim = spec.hidden // spec.heads
        if head_dim > 256:
            return f"flash kernel caps head_dim at 256 (got {head_dim})"
        if seq % 128 != 0:
            return f"flash attention tiles 128-lane sequence blocks: " \
                   f"seq {seq} % 128 != 0 (pad upstream)"
    if spec.moe_on:
        if spec.moe_experts % c.ep != 0:
            return f"ep {c.ep} must divide expert count {spec.moe_experts}"
        if spec.ffn % c.mp != 0:
            return f"expert hidden {spec.ffn} not divisible by mp {c.mp}"
        if c.schedule != "1f1b" or c.vpp > 1:
            return "GPT-MoE supports the 1F1B schedule only"
        if c.fp8 or c.mp_overlap is not None:
            return "GPT-MoE is not composed with fp8 or sequence " \
                   "parallelism"
        if c.moe_quantize:
            if c.pp != 1 or c.micro_batches != 1:
                return "moe_quantize_a2a needs pp=1 and micro_batches=1"
            if c.comm_bucket_mb > 0:
                return "moe_quantize_a2a is not composed with comm_overlap"
    else:
        if c.ep != 1:
            return "dense model: ep must be 1"
        if c.moe_quantize or c.moe_overlap:
            return "dense model: no MoE exchange to configure"
    return None


def generate_plan_candidates(
        spec: ModelSpec, world: int, *, global_batch: int, seq: int,
        micro_batch_options: Sequence[int] = (1, 2, 4, 8),
        schedules: Sequence[str] = SCHEDULES,
        vpp_options: Sequence[int] = (1, 2),
        # stage 1 is deliberately absent from the default enumeration:
        # stages 1 and 2 compile the SAME program (tier-1 asserted) and
        # score identically, but stage 2's HBM accounting dominates —
        # enumerating both just fills the ranked list with score-tied
        # twins that burn launcher trial slots. Stage 1 remains fully
        # constructible/checkable for explicit candidates.
        zero_stage_options: Sequence[int] = (0, 2, 3),
        fp8_options: Sequence[bool] = (False,),
        comm_bucket_options: Sequence[float] = (0.0, 4.0),
        mp_overlap_options: Sequence[Optional[str]] = MP_OVERLAP_MODES,
        flash_options: Sequence[bool] = (False, True),
        moe_variants: Optional[Sequence[Dict[str, bool]]] = None,
) -> Tuple[List[PlanCandidate], List[Tuple[PlanCandidate, str]]]:
    """Enumerate the surface and split it into (valid, pruned-with-reason).

    fp8 defaults OFF in the enumeration (it changes numerics, not just
    schedule — opt in with fp8_options=(False, True) when an fp8 run is
    acceptable). flash_attention defaults to BOTH (numerics-preserving:
    the kernel computes the same softmax-attention; the search trades its
    higher executed flops against the O(S²)→O(S) activation HBM). MoE
    variants default to index dispatch with and without the
    overlapped/quantized exchange where legal.
    """
    if moe_variants is None:
        if spec.moe_on:
            moe_variants = ({"moe_index": True},
                            {"moe_index": True, "moe_overlap": True},
                            {"moe_index": True, "moe_quantize": True,
                             "moe_overlap": True})
        else:
            moe_variants = ({},)
    ep_options = ([e for e in _divisors(world)
                   if spec.moe_experts % e == 0] if spec.moe_on else [1])
    valid: List[PlanCandidate] = []
    pruned: List[Tuple[PlanCandidate, str]] = []
    seen = set()
    for ep in ep_options:
        for dp in _divisors(world // ep):
            rem = world // (ep * dp)
            for mp in _divisors(rem):
                pp = rem // mp
                for (M, sched, vpp, zs, f8, bkt, mpo, fl, moe) in \
                        itertools.product(micro_batch_options, schedules,
                                          vpp_options, zero_stage_options,
                                          fp8_options, comm_bucket_options,
                                          mp_overlap_options, flash_options,
                                          moe_variants):
                    if (sched == "interleaved") != (vpp > 1):
                        continue  # structural, not worth a prune record
                    c = PlanCandidate(
                        dp=dp, mp=mp, pp=pp, ep=ep, vpp=vpp,
                        schedule=sched, micro_batches=M, zero_stage=zs,
                        fp8=f8, comm_bucket_mb=bkt, mp_overlap=mpo,
                        flash_attention=fl, **moe)
                    if c in seen:
                        continue
                    seen.add(c)
                    reason = check_candidate(c, spec, world=world,
                                             global_batch=global_batch,
                                             seq=seq)
                    if reason is None:
                        valid.append(c)
                    else:
                        pruned.append((c, reason))
    return valid, pruned


# ---------------------------------------------------------------------------
# The three-part cost model.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Prediction:
    """One candidate's scored estimate. ``step_s = compute_s (bubble
    included) + exposed_comm_s + launch_s``."""
    step_s: float
    compute_s: float
    exposed_comm_s: float
    launch_s: float
    bubble_frac: float
    comm_frac: float
    mfu: float
    hbm_bytes: float
    n_collectives: int
    compute_units: float        # executed FLOPs per chip (calibration x)
    wire: Dict[str, float] = dataclasses.field(default_factory=dict)
    hbm: Dict[str, float] = dataclasses.field(default_factory=dict)


class CostModel:
    """Analytic step-time/HBM model over :class:`PlanCandidate`s.

    predict() returns seconds from three parts:

    (a) compute — executed FLOPs per chip from ``observability.flops``
        (remat-aware: the hybrid pipeline fully remats each stage;
        MoE adds the capacity expert GEMMs and, for dense dispatch, the
        2*T*E*C*D einsum delta — ``gpt_moe_flops_per_token``), scaled by
        the schedule's executed-tick ratio ((M+P-1)/M for 1F1B,
        (V*M+P-1)/(V*M) interleaved, the ``zbh1_speedup`` model for
        ZBH1), divided by the measured-or-peak rate;

    (b) exposed communication — the validated wire models
        (``mp_wire_bytes`` with schedule-aware executed-block counts, dp
        bucket accounting, ``ep_a2a_wire_bytes``, pp boundary ppermutes)
        over the profile's link bandwidth, discounted by the T3 hidable
        fraction of each mode;

    (c) per-collective launch overhead — n_collectives x
        ``collective_launch_s``; negligible on TPU, DOMINANT on the CPU
        smoke mesh (which is why the CPU proxy ranks ring > sp >
        allreduce by op count — BASELINE.md round 6 — while the same
        model with TPU rates ranks them the other way around).
    """

    def __init__(self, spec: ModelSpec, profile: HardwareProfile, *,
                 global_batch: int, seq: int,
                 rate_flops: Optional[float] = None,
                 collective_launch_s: Optional[float] = None,
                 step_overhead_s: float = 0.0):
        self.spec = spec
        self.profile = profile
        self.B = int(global_batch)
        self.S = int(seq)
        self.rate = (rate_flops if rate_flops is not None
                     else profile.peak_flops * profile.gemm_efficiency)
        self.t_launch = (collective_launch_s
                         if collective_launch_s is not None
                         else profile.collective_launch_s)
        # fixed per-step dispatch/host overhead (seconds): ~0 on TPU at
        # real shapes, tens of ms on the CPU smoke mesh at toy shapes —
        # calibrate() fits it from the measured anchors
        self.step_overhead_s = float(step_overhead_s)

    # -- schedule structure --------------------------------------------------
    @staticmethod
    def _ticks(c: PlanCandidate) -> float:
        M, P, V = c.micro_batches, c.pp, c.vpp
        if c.schedule == "interleaved":
            return V * M + P - 1
        return M + P - 1

    @staticmethod
    def _tick_ratio(c: PlanCandidate) -> float:
        """Executed stage work / useful stage work (>= 1): every pipeline
        tick runs the stage body, bubbles included (they compute on
        zeros and move real bytes — the telemetry tests' accounting)."""
        M, P, V = c.micro_batches, c.pp, c.vpp
        if c.schedule == "interleaved":
            return (V * M + P - 1) / (V * M)
        if c.schedule == "zbh1":
            from ...distributed.fleet.meta_parallel.pp_utils.spmd_pipeline \
                import zbh1_speedup
            return ((M + P - 1) / M) / zbh1_speedup(P, M)
        return (M + P - 1) / M

    def bubble_frac(self, c: PlanCandidate) -> float:
        r = self._tick_ratio(c)
        return max(0.0, 1.0 - 1.0 / r)

    # -- (a) compute ---------------------------------------------------------
    def compute_units(self, c: PlanCandidate) -> float:
        """Executed FLOPs per chip per step."""
        from ...observability import flops as F
        sp = self.spec
        b_rank = self.B // (c.dp * c.ep)
        mb = b_rank // c.micro_batches
        # pipelined blocks: remat full (the stage bodies are checkpointed)
        blk = F.transformer_flops_per_token(
            n_params=sp.n_block_params, num_layers=sp.layers,
            hidden_size=sp.hidden, seq_len=self.S, remat=c.remat)
        units = (b_rank * self.S) * blk["hardware"] / (c.mp * c.pp) \
            * self._tick_ratio(c)
        if c.flash_attention:
            # swap the einsum attention term for the flash one: the fused
            # kernel EXECUTES more flops (its two-kernel backward
            # re-derives the scores tiles) — the honest compute cost the
            # O(S²)→O(S) HBM saving below is traded against
            a_e = F.attention_flops_per_token(
                num_layers=sp.layers, hidden_size=sp.hidden,
                seq_len=self.S, impl="einsum", remat=c.remat)
            a_f = F.attention_flops_per_token(
                num_layers=sp.layers, hidden_size=sp.hidden,
                seq_len=self.S, impl="flash", remat=c.remat)
            units += (b_rank * self.S) \
                * (a_f["hardware"] - a_e["hardware"]) / (c.mp * c.pp) \
                * self._tick_ratio(c)
        # LM head + embedding run on every pp rank (outside the remat'd
        # pipeline): 6 flops/param fwd+bwd, sharded over mp only
        units += (b_rank * self.S) * 6.0 * sp.n_head_params / c.mp
        if sp.moe_on:
            m = F.gpt_moe_flops_per_token(sp.cfg, tokens_per_rank=mb * self.S,
                                          mp=c.mp)
            L2 = sp.layers // 2
            per_layer_exec = m["expert_gemm_flops_per_rank_step"] / L2
            n_exec = (c.micro_batches + c.pp - 1) * (L2 / c.pp)
            units += per_layer_exec * n_exec
            if not c.moe_index:
                # dense one-hot dispatch einsums: fwd pays the delta,
                # backward re-runs both under remat (~3x forward)
                units += 3.0 * m["dense_dispatch_flops_per_moe_layer"] \
                    * n_exec
        return units

    def model_flops_per_token(self) -> float:
        """The MFU numerator (useful work per trained token)."""
        from ...observability import flops as F
        sp = self.spec
        f = F.transformer_flops_per_token(
            n_params=sp.n_block_params + sp.n_head_params,
            num_layers=sp.layers, hidden_size=sp.hidden,
            seq_len=self.S)["model"]
        if sp.moe_on:
            f += F.gpt_moe_flops_per_token(
                sp.cfg, tokens_per_rank=self.B * self.S
            )["model_flops_per_token"]
        return f

    # -- (b) wire ------------------------------------------------------------
    def wire_bytes(self, c: PlanCandidate) -> Dict[str, float]:
        """Per-rank per-step wire bytes by mesh axis, from the validated
        observability models (the SAME formulas the models deposit via
        note_mp_comm/note_ep_comm and the telemetry tests re-derive)."""
        from ...observability.metrics import ep_a2a_wire_bytes, \
            mp_wire_bytes
        sp = self.spec
        dt = sp.act_itemsize
        b_rank = self.B // (c.dp * c.ep)
        mb = b_rank // c.micro_batches
        a_blk = mb * self.S * sp.hidden * dt
        a_full = b_rank * self.S * sp.hidden * dt
        M, P, V = c.micro_batches, c.pp, c.vpp
        out: Dict[str, float] = {"mp": 0.0, "dp": 0.0, "ep": 0.0,
                                 "pp": 0.0, "z3ag": 0.0}
        if c.mp > 1:
            if sp.moe_on:
                n_pairs_local = (sp.layers // 2) / c.pp
                executed = (M + P - 1) * n_pairs_local
                from ...incubate.distributed.models.moe.gate import \
                    compute_capacity
                E = sp.moe_experts
                C = compute_capacity(mb * self.S, E, 1,
                                     sp.cfg.moe_capacity_factor)
                out["mp"] = mp_wire_bytes(
                    "allreduce", c.mp,
                    gemm_pair_bytes=3.0 * executed * a_blk,
                    allreduce_bytes=(2.0 * a_full
                                     + 4.0 * b_rank * self.S * 4
                                     + executed * float(E * C * sp.hidden
                                                        * dt)))
            else:
                executed = (V * M + P - 1) * (sp.layers / c.pp) / V
                mode = c.mp_overlap or "allreduce"
                out["mp"] = mp_wire_bytes(
                    mode, c.mp,
                    gemm_pair_bytes=2.0 * executed * a_blk,
                    allreduce_bytes=(2.0 * a_full
                                     + 4.0 * b_rank * self.S * 4),
                    scatter_bytes=a_full)
        if c.dp > 1:
            if c.zero_stage >= 3:
                # sharded leaves reduce inside the loss's AD transpose —
                # their 2·AG + 1·RS are the z3ag term (the validated
                # observability model); only the replicated-leaf grads
                # still all-reduce on the classic dp path
                from ...observability.metrics import zero3_ag_wire_bytes
                blk, oth, repl, _ = self._z3_leaf_split(c)
                out["dp"] = 2.0 * (c.dp - 1) / c.dp * repl
                out["z3ag"] = zero3_ag_wire_bytes(
                    c.dp, block_param_bytes=blk / V,
                    n_stage_executions=self._ticks(c),
                    other_param_bytes=oth, quantize=False)
            else:
                # stages 0/1/2 move the same dp bytes: one all-reduce, or
                # the RS + closing param AG pair (each f·G)
                grad_local = self._grad_local_bytes(c)
                out["dp"] = 2.0 * (c.dp - 1) / c.dp * grad_local
        if c.ep > 1:
            from ...incubate.distributed.models.moe.gate import \
                compute_capacity
            E = sp.moe_experts
            C = compute_capacity(mb * self.S, E, 1,
                                 sp.cfg.moe_capacity_factor)
            n_exec = (M + P - 1) * (sp.layers // 2) / c.pp
            out["ep"] = ep_a2a_wire_bytes(
                c.ep, payload_elems=float(E * C * sp.hidden),
                n_layer_executions=float(n_exec), itemsize=dt,
                quantize=c.moe_quantize)
        if c.pp > 1:
            a_pp = a_blk / (c.mp if c.mp_overlap else 1)
            out["pp"] = 2.0 * self._ticks(c) * a_pp
        return out

    def _grad_local_bytes(self, c: PlanCandidate) -> float:
        sizes = c.mesh_dims()
        total = 0.0
        for n, item, spec_axes, _shape in self.spec.leaves:
            total += n * item / _shard_product(spec_axes, sizes)
        return total

    def _z3_leaf_split(self, c: PlanCandidate):
        """(block_bytes, other_bytes, repl_bytes, per_layer_bytes) for the
        zero3 wire/HBM model — param bytes LOCAL to the mp/pp/ep shards,
        full over dp. `block` = dp-shardable stacked pipeline leaves
        (spec carries 'pp'): gathered per layer per tick. `other` =
        dp-shardable once-per-step leaves (embeddings/head/final LN).
        `repl` = leaves with no dp-shardable dim (stay replicated; their
        grads still pmean). per_layer_bytes = one LOCAL layer's gathered
        block params (the stage-3 live-working-set unit: the scan carry
        holds ~2 of these under the prefetch)."""
        sizes = c.mesh_dims()
        blk = oth = repl = per_layer = 0.0
        for n, item, spec_axes, shape in self.spec.leaves:
            local = n * item / _shard_product(spec_axes, sizes)
            if not _leaf_dp_shardable(shape, spec_axes, c.dp):
                repl += local
                continue
            if any("pp" in axes for _, axes in spec_axes):
                blk += local
                per_layer += local * c.pp / max(shape[0], 1)
            else:
                oth += local
        return blk, oth, repl, per_layer

    def _hide(self, key: str, table: float) -> float:
        """Hidable fraction for one wire term: a measured override in the
        profile's ``hide`` dict wins outright (it came from attributing a
        real window); otherwise the table constant, zeroed when the
        backend cannot overlap at all."""
        h = self.profile.hide or {}
        if key in h:
            return min(max(float(h[key]), 0.0), 1.0)
        return table if self.profile.overlap_capable else 0.0

    def hide_fractions(self, c: PlanCandidate) -> Dict[str, float]:
        """The per-axis hidable fractions this candidate is scored with
        (override-aware) — also what the bench's profile_attribution
        section prints next to the measured ones."""
        mp_mode = ("allreduce" if self.spec.moe_on
                   else (c.mp_overlap or "allreduce"))
        return {
            "mp": self._hide(f"mp:{mp_mode}", _HIDE_MP[
                c.mp_overlap if not self.spec.moe_on else "allreduce"]),
            "dp": (self._hide("dp:bucketed", _HIDE_DP_BUCKETED)
                   if c.comm_bucket_mb > 0
                   else self._hide("dp:monolithic", _HIDE_DP_MONOLITHIC)),
            # engine_kwargs PINS the prefetched unquantized gather
            # (Zero3Config()), so candidates are scored with the
            # overlapped fraction they will actually run; the [False]
            # table entry prices a hand-built no-prefetch engine, and a
            # measured dp:zero3_ag profile override wins over both
            "z3ag": self._hide("dp:zero3_ag", _HIDE_DP_ZERO3_AG[True]),
            "ep": self._hide("ep:overlap" if c.moe_overlap else "ep:plain",
                             _HIDE_EP[bool(c.moe_overlap)]),
            "pp": self._hide("pp", _HIDE_PP),
        }

    def exposed_comm_s(self, c: PlanCandidate) -> Tuple[float,
                                                        Dict[str, float]]:
        wire = self.wire_bytes(c)
        bw = self.profile.ici_gbs * 1e9
        hide = self.hide_fractions(c)
        exp = {ax: wire[ax] / bw * (1 - hide[ax])
               for ax in ("mp", "dp", "ep", "pp", "z3ag")}
        return sum(exp.values()), wire

    # -- (c) collective dispatch count --------------------------------------
    def n_collectives(self, c: PlanCandidate) -> int:
        sp = self.spec
        n = 0.0
        M, P, V = c.micro_batches, c.pp, c.vpp
        if c.mp > 1:
            if sp.moe_on:
                pairs = 3.0 * (M + P - 1) * (sp.layers // 2) / c.pp
                per_pair = 2
            else:
                pairs = 2.0 * (V * M + P - 1) * (sp.layers / c.pp) / V
                per_pair = {None: 2, "seq_parallel": 4,
                            "collective_matmul": 4 * (c.mp - 1)}[
                    c.mp_overlap]
            n += pairs * per_pair + 4  # + embed/head/CE boundary
        if c.dp > 1:
            if c.comm_bucket_mb > 0:
                n_buckets = max(1.0, math.ceil(
                    self._grad_local_bytes(c)
                    / (c.comm_bucket_mb * (1 << 20))))
            else:
                n_buckets = 1.0  # XLA fuses the monolithic pmean
            if c.zero_stage >= 3:
                # replicated-leaf pmean only; the sharded leaves' AG/RS
                # execute per (tick, layer, leaf kind): 2 gathers (fwd +
                # remat replay) + 1 cotangent reduce-scatter each, plus
                # the once-per-step pairs for embeddings/head
                n += n_buckets
                sizes = c.mesh_dims()
                for _, _, spec_axes, shape in sp.leaves:
                    if not _leaf_dp_shardable(shape, spec_axes, c.dp):
                        continue
                    if any("pp" in axes for _, axes in spec_axes):
                        layers_exec = self._ticks(c) * shape[0] \
                            / (c.pp * c.vpp)
                        n += 3.0 * layers_exec
                    else:
                        n += 2.0
            else:
                n += n_buckets * (2 if c.zero_stage else 1)
        if c.pp > 1:
            n += 2.0 * self._ticks(c)
        if c.ep > 1:
            chunks = 2 if c.moe_overlap else 1
            n += 4.0 * (M + P - 1) * (sp.layers // 2) / c.pp * chunks
        return int(round(n))

    # -- memory --------------------------------------------------------------
    def hbm_bytes(self, c: PlanCandidate, *, moment_itemsize: int = 4,
                  optimizer_slots: int = 2) -> Tuple[float,
                                                     Dict[str, float]]:
        """Per-chip analytic HBM: per-leaf params/grads from the model's
        own spec tree (the hbm_audit accounting without a Mesh),
        optimizer slots with the zero1 per-leaf dp sharding rule, and an
        activation estimate for the fully-rematted pipeline (saved stage
        inputs per tick + one block's working set + attention scores +
        the vocab-parallel logits). Cross-check against compiled
        ``memory_analysis`` with hbm_audit.audit_plan_compile."""
        sp = self.spec
        sizes = c.mesh_dims()
        params = grads = opt = 0.0
        for n, item, spec_axes, shape in sp.leaves:
            local = n / _shard_product(spec_axes, sizes)
            shardable = _leaf_dp_shardable(shape, spec_axes, c.dp)
            pb = local * item
            # the zero stage axis: stage >= 1 shards the slots, stage
            # >= 2 the grad buffer, stage 3 the resident params (each by
            # the SAME per-leaf rule the engine's zero_dims applies)
            params += pb / c.dp if (c.zero_stage >= 3 and shardable) \
                else pb
            grads += pb / c.dp if (c.zero_stage >= 2 and shardable) \
                else pb
            slot = local * moment_itemsize * optimizer_slots
            if c.zero_stage >= 1 and shardable:
                slot /= c.dp
            opt += slot
        dt = sp.act_itemsize
        b_rank = self.B // (c.dp * c.ep)
        mb = b_rank // c.micro_batches
        s_sp = self.S // (c.mp if c.mp_overlap else 1)
        H, FF = sp.hidden, sp.ffn
        act = self._ticks(c) * mb * s_sp * H * dt          # saved inputs
        act += mb * self.S * dt * (2 * H + (4 * H + 2 * FF) / c.mp)
        if c.flash_attention:
            # the fused kernel never materializes scores in HBM — its
            # rematted working set is the (out, lse) residual pair, O(S)
            act += mb * self.S * ((H / c.mp) * dt
                                  + (sp.heads / c.mp) * 4.0)
        else:
            act += mb * (sp.heads / c.mp) * self.S ** 2 * dt  # attn scores
        act += b_rank * self.S * (sp.vocab / c.mp) * (dt + 8)  # logits+CE
        act += 2.0 * b_rank * self.S * H * dt              # embed in/out
        if sp.moe_on:
            from ...incubate.distributed.models.moe.gate import \
                compute_capacity
            E = sp.moe_experts
            C = compute_capacity(mb * self.S, E, 1,
                                 sp.cfg.moe_capacity_factor)
            act += 4.0 * E * C * H * dt                    # a2a buffers
        if c.zero_stage >= 3:
            # stage-3 live working set: the scan carry holds the current
            # block's gathered params plus the prefetched next block's
            _, _, _, per_layer = self._z3_leaf_split(c)
            act += 2.0 * per_layer
        parts = {"params": params, "grads": grads, "opt": opt, "act": act}
        return 1.10 * sum(parts.values()), parts

    # -- the verdict ---------------------------------------------------------
    def predict(self, c: PlanCandidate) -> Prediction:
        units = self.compute_units(c)
        t_comp = units / self.rate
        t_comm, wire = self.exposed_comm_s(c)
        ncoll = self.n_collectives(c)
        t_launch = ncoll * self.t_launch
        step = t_comp + t_comm + t_launch + self.step_overhead_s
        hbm, hbm_parts = self.hbm_bytes(c)
        toks = self.B * self.S
        mfu = (toks * self.model_flops_per_token()
               / (c.world * self.profile.peak_flops * step))
        return Prediction(
            step_s=step, compute_s=t_comp, exposed_comm_s=t_comm,
            launch_s=t_launch, bubble_frac=self.bubble_frac(c),
            comm_frac=(t_comm + t_launch) / step, mfu=mfu,
            hbm_bytes=hbm, n_collectives=ncoll, compute_units=units,
            wire=wire, hbm=hbm_parts)

    def calibrate(self, anchors: Sequence[Tuple[PlanCandidate, float]]
                  ) -> "CostModel":
        """Fit (compute rate, per-collective launch overhead, fixed
        per-step overhead) to measured anchor step times — the
        measured-or-peak leg of the model. Wire terms stay at the
        profile's bandwidth (known offset). One anchor fits the rate
        only; two fit rate + per-step overhead; three or more
        least-squares all three over
        ``measured ~= units/rate + n_coll*t_launch + overhead + wire``.
        Returns a NEW CostModel; self is untouched."""
        import numpy as np
        units = []
        ncoll = []
        rhs = []
        for cand, measured in anchors:
            wire_s, _ = self.exposed_comm_s(cand)
            units.append(self.compute_units(cand))
            ncoll.append(float(self.n_collectives(cand)))
            rhs.append(max(measured - wire_s, 1e-9))
        b = np.asarray(rhs)
        t_launch, overhead = self.t_launch, self.step_overhead_s
        if len(anchors) >= 3:
            # a joint 3-parameter lstsq is ill-conditioned (units and
            # collective counts correlate across realistic anchors and
            # timing noise then tips the fit into degenerate corners), so
            # fit SEQUENTIALLY: t_launch from the anchor pair with the
            # closest compute units but different collective counts (their
            # time difference is almost purely dispatch count)...
            best = None
            for i in range(len(anchors)):
                for j in range(i + 1, len(anchors)):
                    dn = abs(ncoll[i] - ncoll[j])
                    if dn < 1:
                        continue
                    du = abs(units[i] - units[j]) / max(units[i], units[j])
                    if best is None or du < best[0]:
                        best = (du, i, j)
            if best is not None and best[0] < 0.25:
                _, i, j = best
                t_launch = max((b[i] - b[j]) / (ncoll[i] - ncoll[j]), 0.0)
        if len(anchors) == 1:
            inv_rate = (b[0] - ncoll[0] * t_launch - overhead) / units[0]
        else:
            # ...then (rate, fixed overhead) over all anchors with the
            # launch term subtracted
            A = np.asarray([[u, 1.0] for u in units])
            sol, *_ = np.linalg.lstsq(A, b - np.asarray(ncoll) * t_launch,
                                      rcond=None)
            inv_rate, overhead = sol[0], max(sol[1], 0.0)
        inv_rate = max(inv_rate, 1e-18)
        return CostModel(self.spec, self.profile, global_batch=self.B,
                         seq=self.S, rate_flops=1.0 / inv_rate,
                         collective_launch_s=t_launch,
                         step_overhead_s=overhead)


# ---------------------------------------------------------------------------
# Top-level plan(): generate -> prune (constraints + HBM) -> score -> rank.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ScoredPlan:
    candidate: PlanCandidate
    prediction: Prediction

    def row(self) -> Dict[str, Any]:
        p = self.prediction
        return {"candidate": str(self.candidate),
                "mesh": self.candidate.mesh_dims(),
                "step_ms": round(p.step_s * 1e3, 3),
                "mfu_pct": round(p.mfu * 100, 2),
                "comm_frac": round(p.comm_frac, 4),
                "bubble_frac": round(p.bubble_frac, 4),
                "hbm_gb": round(p.hbm_bytes / 1e9, 3),
                "n_collectives": p.n_collectives}


@dataclasses.dataclass
class PlanReport:
    spec: ModelSpec
    profile: HardwareProfile
    global_batch: int
    seq: int
    ranked: List[ScoredPlan]
    pruned: List[Tuple[PlanCandidate, str]]
    n_generated: int = 0

    def top(self, k: int) -> List[ScoredPlan]:
        return self.ranked[:k]

    def to_json(self, top_k: Optional[int] = None) -> Dict[str, Any]:
        rows = self.ranked if top_k is None else self.ranked[:top_k]
        return {
            "model": type(self.spec.cfg).__name__,
            "family": self.spec.family,
            "profile": dataclasses.asdict(self.profile),
            "global_batch": self.global_batch, "seq": self.seq,
            "n_generated": self.n_generated,
            "n_valid": len(self.ranked),
            "n_pruned": len(self.pruned),
            "ranked": [s.row() for s in rows],
            "pruned": [{"candidate": str(c), "reason": r}
                       for c, r in self.pruned],
        }


def plan(cfg, *, world: int, global_batch: int, seq: int,
         family: str = "gpt", profile: Optional[HardwareProfile] = None,
         hbm_gb: Optional[float] = None, cost_model: Optional[CostModel]
         = None, **gen_options) -> PlanReport:
    """The planner entry point: enumerate, constraint-prune, HBM-prune,
    score and rank every PlanCandidate for (cfg, world devices).

    hbm_gb overrides the profile's per-chip budget (the CLI's --hbm-gb /
    FLAGS_auto_parallel_hbm_gb). Extra kwargs go to
    generate_plan_candidates (micro_batch_options etc.)."""
    spec = ModelSpec.from_config(cfg, family)
    if profile is None:
        profile = profile_for(hbm_gb=hbm_gb)
    elif hbm_gb is not None and hbm_gb > 0:
        profile = dataclasses.replace(profile, hbm_gb=float(hbm_gb))
    cm = cost_model if cost_model is not None else CostModel(
        spec, profile, global_batch=global_batch, seq=seq)
    cands, pruned = generate_plan_candidates(
        spec, world, global_batch=global_batch, seq=seq, **gen_options)
    n_generated = len(cands) + len(pruned)
    budget = profile.hbm_gb * 1e9
    scored: List[ScoredPlan] = []
    for c in cands:
        pred = cm.predict(c)
        if pred.hbm_bytes > budget:
            pruned.append((c, f"analytic HBM {pred.hbm_bytes / 1e9:.2f} GB "
                              f"> budget {profile.hbm_gb:g} GB"))
            continue
        scored.append(ScoredPlan(c, pred))
    scored.sort(key=lambda s: s.prediction.step_s)
    return PlanReport(spec=spec, profile=profile, global_batch=global_batch,
                      seq=seq, ranked=scored, pruned=pruned,
                      n_generated=n_generated)


# ---------------------------------------------------------------------------
# Named model configs for the CLI / launcher.
# ---------------------------------------------------------------------------
PLAN_MODELS = ("gpt_tiny", "gpt1p3b", "gpt_moe_tiny", "llama_tiny")


def model_config_by_name(name: str, dtype=None):
    """(cfg, family) for the CLI's --model vocabulary."""
    import jax.numpy as jnp
    kw = {}
    if dtype is not None:
        kw = {"dtype": dtype,
              "param_dtype": jnp.float32 if dtype == jnp.float32 else dtype}
    if name == "gpt_tiny":
        from ...models.gpt import gpt_tiny
        return gpt_tiny(**kw), "gpt"
    if name in ("gpt1p3b", "gpt_1p3b"):
        from ...models.gpt import gpt_1p3b
        return gpt_1p3b(**kw), "gpt"
    if name == "gpt_moe_tiny":
        from ...models.gpt import gpt_moe_tiny
        return gpt_moe_tiny(**kw), "gpt"
    if name == "llama_tiny":
        from ...models.llama import llama_tiny
        return llama_tiny(**kw), "llama"
    raise ValueError(f"unknown model {name!r}; choose from {PLAN_MODELS}")
