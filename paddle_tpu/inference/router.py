"""Multi-replica serving: a fault-tolerant fleet router (ISSUE 16).

PR 13 hardened ONE serving replica (deadlines, shedding, preemption,
journaled replay, /healthz); this module is the layer above it — the
unit of production serving is a FLEET, and replica death is an expected
event the router absorbs, not an outage:

* **health-driven dispatch** — placement reads each replica's health
  state and prom snapshot (queue depth + running, recent-window TTFT
  p95, KV-pool utilization) and routes to the least-loaded ready
  replica. Every replica's own queue is bounded (its engine's admission
  control); the router adds a fleet-level bound on top
  (``FLAGS_router_queue_max``) so when every replica sheds, arrivals
  shed at the front door too instead of building an unbounded backlog.
* **journaled failover** — every replica rides its own PR 13
  :class:`~.resilient.ServingJournal`; a token is journaled *before*
  the client callback sees it. On replica death (process exit, step
  failure, heartbeat timeout, an armed fault site) the router requeues
  that replica's in-flight requests onto survivors with the delivered
  prefix folded into the prompt and ``max_new_tokens`` reduced by the
  watermark — token delivery stays exactly-once and greedy outputs stay
  bitwise-identical to an uninterrupted run (a greedy request's output
  is a pure function of its own prompt, independent of placement).
* **quarantine + respawn** — ``FLAGS_router_max_failures`` consecutive
  dispatch/step failures quarantine a replica: it is drained (SIGTERM
  grace for spawned replicas, drain+cancel for in-process ones) and
  probed with doubling backoff; a successful probe respawns it on the
  SAME journal (the PR 13 successor-resume path, driven automatically).
* **fleet front door** — :meth:`Router.serve_metrics` starts ONE stable
  /metrics + /healthz: ready iff ≥1 replica is ready, gauges
  ``replica_state_<i>`` / per-replica depth, counters
  ``router_failovers_total`` / ``router_requeued_total``, reason-tagged
  ``router_*`` JSONL events, and a ``router.json`` flight-recorder
  section so a fleet incident leaves forensics.

Two replica kinds share the lifecycle (``starting → ready ⇄
quarantined → dead``, ``draining`` on the way down):
:class:`InProcessReplica` builds engines from a factory (tier-1 tests,
the dryrun leg); :class:`SpawnedReplica` drives a
``paddle_tpu.inference.router_worker`` process per replica over a tiny
file protocol — an append-only ``inbox.<gen>.jsonl`` of request lines
(the generation bumps on every respawn so a successor never re-reads
work the router already reassigned), the worker's journal as the
delivery channel (the router tails it), and a ``health.json`` heartbeat.

``router_failover_check`` / ``router_spawn_check`` are the acceptance
harnesses run by tests/test_router.py and the ``__graft_entry__``
dryrun.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .resilient import ServingJournal
from .serving import NonFiniteSampleError

__all__ = ["Router", "ReplicaSet", "InProcessReplica", "SpawnedReplica",
           "router_failover_check", "router_spawn_check"]

_TERMINAL = ("done", "failed", "shed", "cancelled")
# numeric codes for the replica_state_<i> prom gauge
STATE_CODES = {"starting": 0, "ready": 1, "draining": 2, "quarantined": 3,
               "dead": 4}


def _faults():
    from ..distributed.resilience import faults
    return faults


def _emit(event: str, **fields):
    from ..observability import emit_event
    emit_event(event, role="router", **fields)


class _ReplicaBase:
    """Lifecycle state + router-side bookkeeping shared by both replica
    kinds. ``assigned`` is the router's view of in-flight work — the
    orphan set a failover requeues."""

    kind = "?"

    def __init__(self, idx: int, journal_path: Optional[str] = None):
        self.idx = idx
        self.state = "starting"
        self.journal_path = journal_path
        self.assigned: Dict[int, Dict[str, Any]] = {}
        self.consec_failures = 0
        self.quarantine_until = 0.0
        self.backoff_s: Optional[float] = None
        self.respawns = 0
        self.last_error: Optional[str] = None

    # -- overridden per kind -------------------------------------------------
    def start(self) -> None:
        raise NotImplementedError

    def submit(self, lid: int, spec: Dict[str, Any], prompt: np.ndarray,
               rem: int, deliver: Callable[[int, int], None]) -> None:
        raise NotImplementedError

    def poll(self, deliver) -> Tuple[List[Tuple[int, str, Optional[str]]],
                                     Optional[str]]:
        """Advance/observe the replica; returns (finished, death_reason)
        where finished is [(lid, status, error)] and death_reason is a
        non-None string when the replica must be failed over."""
        raise NotImplementedError

    def load(self) -> Tuple[float, float, float]:
        """Placement key: (pending requests, TTFT recent p95, pool
        utilization) — lower is better on every axis."""
        return (float(len(self.assigned)), 0.0, 0.0)

    def extra_stats(self) -> Dict[str, float]:
        """Sharing/speculation health counters for the fleet /metrics
        (ISSUE 17) — empty where the engine is out of process."""
        return {}

    def heartbeat_age(self) -> float:
        return 0.0

    def stop(self, grace_s: float, reason: str) -> None:
        raise NotImplementedError

    def drain(self, deliver) -> List[Tuple[int, str, Optional[str]]]:
        """Final observation pass after ``stop()`` — where delivery is
        asynchronous (a journal tail), pick up every durable record the
        replica wrote before it died. No-op where delivery is
        synchronous."""
        del deliver
        return []

    def pending(self) -> int:
        return len(self.assigned)

    def snapshot(self) -> Dict[str, Any]:
        return {"idx": self.idx, "kind": self.kind, "state": self.state,
                "pending": self.pending(),
                "consec_failures": self.consec_failures,
                "respawns": self.respawns,
                "journal": self.journal_path,
                "last_error": self.last_error}


class InProcessReplica(_ReplicaBase):
    """A replica backed by an in-process :class:`ServingEngine` built
    from a factory — the tier-1/test form. Its journal may be
    memory-only (``journal_path=None``): the process IS the failure
    domain, so the watermark only has to survive the engine, not the
    host."""

    kind = "inproc"

    def __init__(self, idx: int, make_engine: Callable[[], Any],
                 journal_path: Optional[str] = None):
        super().__init__(idx, journal_path)
        self._make_engine = make_engine
        self.engine = None
        self.journal = ServingJournal(journal_path)
        self._rid_map: Dict[int, int] = {}

    def start(self) -> None:
        _faults().maybe_fail("replica/spawn")
        self.engine = self._make_engine()
        self._rid_map = {}
        self.state = "ready"

    def submit(self, lid, spec, prompt, rem, deliver) -> None:
        rid = self.engine.add_request(
            prompt, rem, spec.get("temperature", 0.0), spec.get("eos_id"),
            on_token=(lambda r, t, lid=lid: deliver(lid, t)),
            deadline_s=spec.get("deadline_s"))
        self._rid_map[rid] = lid

    def poll(self, deliver):
        if self.engine is None or not self.engine.has_work():
            return [], None
        try:
            finished = self.engine.step()
        except NonFiniteSampleError as e:
            # circuit breaker parity with run_serving_resilient: the
            # poisoned request is FAILED (never requeued — it would
            # poison every survivor too); its siblings fail over
            lid = self._rid_map.get(e.rid)
            done = ([(lid, "failed", repr(e))] if lid is not None else [])
            return done, f"nonfinite:{e.rid}"
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            return [], repr(e)
        out = []
        for r in finished:
            lid = self._rid_map.get(r.rid)
            if lid is not None:
                out.append((lid, "done" if r.status == "ok" else r.status,
                            r.error))
        return out, None

    def load(self):
        eng = self.engine
        if eng is None:
            return (float(len(self.assigned)), 0.0, 0.0)
        s = eng.load_stats()
        return (s["pending"], s["ttft_p95"], s["pool_utilization"])

    def extra_stats(self):
        eng = self.engine
        if eng is None:
            return {}
        s = eng.load_stats()
        return {k: s[k] for k in ("kv_pages_shared", "kv_cow_copies_total",
                                  "spec_proposed_total",
                                  "spec_accepted_total") if k in s}

    def stop(self, grace_s, reason) -> None:
        # in-process drain: no SIGTERM to send — stop admission, shed the
        # queue and cancel in-flight (pages freed; the journal keeps every
        # delivered prefix for the failover resubmission)
        eng, self.engine = self.engine, None
        self._rid_map = {}
        if eng is not None:
            try:
                eng.drain()
                eng.shed_queue(reason)
                eng.cancel_all(reason)
            except Exception:
                pass

    def free_pool(self) -> Tuple[Optional[int], Optional[int]]:
        if self.engine is None:
            return None, None
        # free_pages() counts cached-free prefix pages as reclaimable —
        # the zero-leak failover gate must not read them as leaked
        return self.engine.free_pages(), self.engine._num_blocks - 1


class SpawnedReplica(_ReplicaBase):
    """A replica backed by a ``router_worker`` process — the real path.

    File protocol under ``workdir/replica<i>/``:

    * ``inbox.<gen>.jsonl`` — router appends one request line per
      dispatch plus a ``{"close": true}`` sentinel; the generation bumps
      on every (re)spawn so a respawned worker NEVER re-reads work the
      router already reassigned to survivors (the double-delivery hole a
      shared inbox would open).
    * ``journal.jsonl``     — the worker's :class:`ServingJournal`, and
      the delivery channel: the worker journals each token BEFORE the
      router can observe it; the router tails complete lines only (a
      torn tail from a mid-write kill is left for the next poll). The
      SAME file rides across respawns — the successor-resume contract.
    * ``health.json``       — heartbeat, atomically replaced each worker
      loop; staleness past ``FLAGS_router_heartbeat_timeout_s`` is
      treated as death.
    * ``out.<gen>.log`` / ``err.<gen>.log`` — worker stdio (files, not
      pipes: nothing blocks on an unread pipe mid-run); the final
      ``RESULT {json}`` line carries the pool-leak accounting.
    """

    kind = "spawn"

    def __init__(self, idx: int, workdir: str, *, two_program: bool = False,
                 fault: str = ""):
        self.rdir = os.path.join(workdir, f"replica{idx}")
        os.makedirs(self.rdir, exist_ok=True)
        super().__init__(idx, os.path.join(self.rdir, "journal.jsonl"))
        self.two_program = two_program
        self._fault = fault  # armed for the FIRST spawn only
        self.gen = 0
        self.proc = None
        self._inbox = None
        self._journal_off = 0
        self._spawn_ts = 0.0
        self.exit_code: Optional[int] = None  # current generation
        self.exit_codes: List[int] = []       # every dead generation's rc
        self._statuses: Dict[int, str] = {}

    def start(self) -> None:
        import subprocess
        import sys
        _faults().maybe_fail("replica/spawn")
        self.gen += 1
        self._close_inbox_handle()
        self._inbox = open(os.path.join(self.rdir,
                                        f"inbox.{self.gen}.jsonl"),
                           "a", encoding="utf-8")
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   FLAGS_fault_inject=self._fault,
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.pop("XLA_FLAGS", None)  # no inherited dryrun device counts
        self._fault = ""  # a respawn must not re-arm the injected crash
        args = [sys.executable, "-m", "paddle_tpu.inference.router_worker",
                self.rdir, "--gen", str(self.gen)]
        if self.two_program:
            args.append("--two")
        out = open(os.path.join(self.rdir, f"out.{self.gen}.log"), "w")
        err = open(os.path.join(self.rdir, f"err.{self.gen}.log"), "w")
        self.proc = subprocess.Popen(args, env=env, stdout=out, stderr=err)
        out.close()
        err.close()
        self._spawn_ts = time.time()
        self.exit_code = None
        # 'starting' until the FIRST fresh heartbeat: a cold worker is
        # still compiling — failover traffic must land on warm survivors,
        # not queue behind a respawn's startup
        self.state = "starting"

    def heartbeat_fresh(self) -> bool:
        """True once THIS generation's worker has written a heartbeat
        (a stale file left by the previous generation does not count)."""
        try:
            with open(os.path.join(self.rdir, "health.json"),
                      encoding="utf-8") as f:
                rec = json.load(f)
            return float(rec.get("ts", 0.0)) >= self._spawn_ts - 1.0
        except Exception:
            return False

    def _close_inbox_handle(self):
        if self._inbox is not None:
            try:
                self._inbox.close()
            except Exception:
                pass
            self._inbox = None

    def submit(self, lid, spec, prompt, rem, deliver) -> None:
        del deliver  # delivery rides the journal tail, not a callback
        rec = {"lid": int(lid), "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(rem),
               "temperature": float(spec.get("temperature", 0.0)),
               "eos_id": spec.get("eos_id"),
               "deadline_s": spec.get("deadline_s")}
        self._inbox.write(json.dumps(rec) + "\n")
        self._inbox.flush()

    def send_close(self) -> None:
        if self._inbox is not None:
            self._inbox.write('{"close": true}\n')
            self._inbox.flush()

    def _read_tail(self, deliver):
        finished: List[Tuple[int, str, Optional[str]]] = []
        # tail COMPLETE journal lines appended since the last read —
        # journal-first in the worker means every token seen here was
        # durable before the client callback fires in this process
        try:
            with open(self.journal_path, "r", encoding="utf-8") as f:
                f.seek(self._journal_off)
                data = f.read()
        except OSError:
            data = ""
        end = data.rfind("\n")
        if end >= 0:
            for line in data[:end].splitlines():
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn interior line (mid-write kill)
                lid = int(rec.get("lid", -1))
                if "tok" in rec:
                    deliver(lid, int(rec["tok"]))
                elif "status" in rec:
                    st = str(rec["status"])
                    self._statuses[lid] = st
                    if lid in self.assigned and st in _TERMINAL:
                        finished.append((lid, st, None))
            self._journal_off += end + 1
        return finished

    def poll(self, deliver):
        finished = self._read_tail(deliver)
        rc = self.proc.poll() if self.proc is not None else None
        if rc is not None:
            self.exit_code = rc
            self.exit_codes.append(rc)
            return finished, f"process_exit rc={rc}"
        return finished, None

    def drain(self, deliver):
        # the worker keeps journaling between a poll's tail read and the
        # moment its death is observed (and a heartbeat-timed-out worker
        # may still be writing) — once stop() has made the journal final,
        # this picks up those durable records so the failover watermark
        # counts every token the client was (or will be) handed
        return self._read_tail(deliver)

    def heartbeat_age(self) -> float:
        try:
            with open(os.path.join(self.rdir, "health.json"),
                      encoding="utf-8") as f:
                rec = json.load(f)
            return max(0.0, time.time() - float(rec.get("ts", 0.0)))
        except Exception:
            # no heartbeat yet: age from spawn (startup/compile counts
            # against the timeout — a worker that never comes up is dead)
            return max(0.0, time.time() - self._spawn_ts)

    def stop(self, grace_s, reason) -> None:
        import signal
        self._close_inbox_handle()
        p, self.proc = self.proc, None
        if p is None or p.poll() is not None:
            return
        try:
            p.send_signal(signal.SIGTERM)  # drain: finish what fits
            p.wait(timeout=grace_s)
        except Exception:
            try:
                p.kill()
                p.wait(timeout=5.0)
            except Exception:
                pass

    def wait(self, timeout: float) -> Optional[int]:
        if self.proc is None:
            return self.exit_code
        try:
            self.exit_code = self.proc.wait(timeout=timeout)
        except Exception:
            return None
        return self.exit_code

    def result(self) -> Optional[Dict[str, Any]]:
        """Parse the worker's final ``RESULT {json}`` line (pool-leak
        accounting) from the current generation's stdout log."""
        try:
            with open(os.path.join(self.rdir, f"out.{self.gen}.log"),
                      encoding="utf-8") as f:
                for line in f:
                    if line.startswith("RESULT "):
                        return json.loads(line[len("RESULT "):])
        except OSError:
            return None
        return None


class ReplicaSet:
    """An ordered fleet of replicas plus construction helpers."""

    def __init__(self, replicas: Sequence[_ReplicaBase]):
        self.replicas = list(replicas)

    @classmethod
    def in_process(cls, make_engine: Callable[[], Any], n: int = 2, *,
                   journal_dir: Optional[str] = None) -> "ReplicaSet":
        reps = []
        for i in range(n):
            jp = (os.path.join(journal_dir, f"replica{i}.jsonl")
                  if journal_dir else None)
            reps.append(InProcessReplica(i, make_engine, jp))
        return cls(reps)

    @classmethod
    def spawned(cls, workdir: str, n: int = 2, *,
                two_program: bool = False,
                faults: Optional[Dict[int, str]] = None) -> "ReplicaSet":
        faults = faults or {}
        return cls([SpawnedReplica(i, workdir, two_program=two_program,
                                   fault=faults.get(i, ""))
                    for i in range(n)])

    def __len__(self):
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    def __getitem__(self, i):
        return self.replicas[i]

    def states(self) -> List[str]:
        return [r.state for r in self.replicas]

    def ready(self) -> List[_ReplicaBase]:
        return [r for r in self.replicas if r.state == "ready"]


class Router:
    """Fault-tolerant request router over a :class:`ReplicaSet`.

    ``submit`` enqueues; ``step`` is one scheduling round (probe
    quarantined replicas, dispatch least-loaded, advance/observe every
    ready replica, heartbeat-check, harvest); ``run`` drives every
    submitted request to a terminal status. Tokens reach ``on_token``
    exactly once, already journaled by the owning replica."""

    def __init__(self, replica_set: ReplicaSet, *,
                 max_failures: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 replica_cap: int = 0,
                 heartbeat_timeout_s: Optional[float] = None,
                 backoff_s: Optional[float] = None,
                 grace_s: Optional[float] = None):
        from ..flags import flag
        from ..observability import PromRegistry
        from ..observability.flight_recorder import register_router
        self.replica_set = replica_set
        self.max_failures = int(max_failures if max_failures is not None
                                else flag("router_max_failures"))
        self.queue_max = int(queue_max if queue_max is not None
                             else flag("router_queue_max"))
        self.replica_cap = int(replica_cap)
        self.heartbeat_timeout_s = float(
            heartbeat_timeout_s if heartbeat_timeout_s is not None
            else flag("router_heartbeat_timeout_s"))
        self.backoff0_s = float(backoff_s if backoff_s is not None
                                else flag("router_quarantine_backoff_s"))
        self.grace_s = float(grace_s if grace_s is not None
                             else flag("preempt_grace_s"))
        self.requests: List[Dict[str, Any]] = []
        self.queue: List[int] = []          # lids awaiting dispatch
        self.statuses: Dict[int, str] = {}
        self.delivered: Dict[int, List[int]] = {}
        self.errors: Dict[int, str] = {}
        self.owner: Dict[int, int] = {}     # lid -> replica idx (current)
        self.steps = 0
        self.failovers = 0
        self.requeues = 0
        self.sheds = 0
        self._prom = PromRegistry(namespace="paddle_tpu_router")
        self._server = None
        for r in self.replica_set:
            self._try_start(r, probe=False)
        self._refresh_gauges()
        register_router(self)

    # -- front door ----------------------------------------------------------
    @property
    def prom(self):
        return self._prom

    def fleet_health(self) -> str:
        """Fleet readiness: ready iff at least one replica is ready —
        ONE dead replica must not flip the front door to 503."""
        return "ready" if self.replica_set.ready() else "degraded"

    def serve_metrics(self, port: int = 0):
        from ..observability.prom import MetricsServer
        self._server = MetricsServer(self._prom, port=port,
                                     health_fn=self.fleet_health)
        return self._server

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               eos_id: Optional[int] = None, on_token=None,
               deadline_s: Optional[float] = None) -> int:
        """Enqueue a request; returns its stable fleet-wide lid. The
        router queue is the fleet-level backpressure bound: past
        ``queue_max`` the arrival is SHED loudly (event + counter), the
        same contract as one engine's bounded queue."""
        lid = len(self.requests)
        spec = {"prompt": np.asarray(prompt, np.int32),
                "max_new_tokens": int(max_new_tokens),
                "temperature": temperature, "eos_id": eos_id,
                "on_token": on_token, "deadline_s": deadline_s}
        self.requests.append(spec)
        self.delivered[lid] = []
        if self.queue_max and len(self.queue) >= self.queue_max:
            self.statuses[lid] = "shed"
            self.sheds += 1
            self._prom.counter_inc("router_shed_total",
                                   help="arrivals shed at the fleet door")
            _emit("router_shed", lid=lid, reason="router_queue_full",
                  queue_depth=len(self.queue))
            return lid
        self.statuses[lid] = "pending"
        self.queue.append(lid)
        return lid

    # -- delivery ------------------------------------------------------------
    def _deliver(self, rep: _ReplicaBase, lid: int, tok: int):
        # in-process replicas journal-first HERE; spawned replicas
        # already journaled in the worker before the tail read saw it
        if isinstance(rep, InProcessReplica):
            rep.journal.append(lid, tok)
        self.delivered[lid].append(int(tok))
        cb = self.requests[lid].get("on_token")
        if cb is not None:
            cb(lid, tok)

    # -- dispatch ------------------------------------------------------------
    def _eligible(self) -> List[_ReplicaBase]:
        out = []
        for r in self.replica_set.ready():
            if self.replica_cap and r.pending() >= self.replica_cap:
                continue
            out.append(r)
        return out

    def _dispatch(self):
        while self.queue:
            cands = self._eligible()
            if not cands:
                return  # fleet backpressure: hold in the bounded queue
            rep = min(cands, key=lambda r: (*r.load(), r.idx))
            lid = self.queue[0]
            spec = self.requests[lid]
            pre = self.delivered[lid]
            rem = spec["max_new_tokens"] - len(pre)
            eos = spec.get("eos_id")
            if rem <= 0 or (eos is not None and pre and pre[-1] == eos):
                self.queue.pop(0)
                self._finish(lid, "done", None)
                continue
            prompt = spec["prompt"]
            if pre:
                prompt = np.concatenate(
                    [prompt, np.asarray(pre, np.int32)])
            try:
                _faults().maybe_fail("router/dispatch")
                rep.submit(lid, spec, prompt, rem,
                           lambda l, t, rep=rep: self._deliver(rep, l, t))
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self._charge_failure(rep, f"dispatch: {e!r}")
                continue
            self.queue.pop(0)
            self.statuses[lid] = "running"
            self.owner[lid] = rep.idx
            rep.assigned[lid] = spec
            rep.consec_failures = 0
            self._prom.counter_inc("router_dispatches_total",
                                   help="requests handed to a replica")

    # -- failure handling ----------------------------------------------------
    def _charge_failure(self, rep: _ReplicaBase, reason: str):
        """One consecutive-failure charge; quarantine past the budget."""
        rep.consec_failures += 1
        rep.last_error = reason
        _emit("router_dispatch_failed", replica=rep.idx, reason=reason,
              consec_failures=rep.consec_failures)
        if rep.consec_failures >= self.max_failures:
            self._quarantine(rep, reason)

    def _failover(self, rep: _ReplicaBase, reason: str):
        """Replica death: requeue its journaled in-flight requests onto
        survivors, watermark preserved (the delivered prefix rides the
        next dispatch's prompt — exactly-once by construction). The
        replica is stopped FIRST and its journal drained before the
        watermarks are taken: tokens journaled between the detecting
        poll's tail read and the death (or by a heartbeat-timed-out
        worker still writing) must count, or a survivor would
        re-generate them and the client would see them twice."""
        rep.stop(self.grace_s, "failover")
        for lid, status, err in rep.drain(
                lambda l, t, rep=rep: self._deliver(rep, l, t)):
            rep.assigned.pop(lid, None)
            self._finish(lid, status, err, replica=rep)
        orphans = sorted(lid for lid in rep.assigned
                         if self.statuses.get(lid) not in _TERMINAL)
        pre_counts = {lid: len(self.delivered[lid]) for lid in orphans}
        rep.assigned.clear()
        for lid in reversed(orphans):
            self.statuses[lid] = "pending"
            self.queue.insert(0, lid)  # orphans keep their original order
        self.failovers += 1
        self.requeues += len(orphans)
        self._prom.counter_inc("router_failovers_total",
                               help="replica deaths absorbed by requeue")
        self._prom.counter_inc("router_requeued_total", len(orphans),
                               help="in-flight requests replayed onto "
                                    "survivors")
        _emit("router_failover", replica=rep.idx, reason=reason,
              orphans=orphans, watermarks=pre_counts)
        from ..observability.flight_recorder import maybe_dump
        maybe_dump("router_failover",
                   extra={"replica": rep.idx, "reason": reason,
                          "orphans": orphans})
        rep.consec_failures += 1
        rep.last_error = reason
        if rep.consec_failures >= self.max_failures:
            self._quarantine(rep, reason)
        else:
            self._try_start(rep, probe=False)

    def _quarantine(self, rep: _ReplicaBase, reason: str):
        rep.stop(self.grace_s, "quarantined")
        rep.state = "quarantined"
        rep.backoff_s = (self.backoff0_s if rep.backoff_s is None
                         else min(rep.backoff_s * 2.0, 30.0))
        rep.quarantine_until = time.monotonic() + rep.backoff_s
        _emit("router_quarantine", replica=rep.idx, reason=reason,
              backoff_s=rep.backoff_s,
              consec_failures=rep.consec_failures)

    def _try_start(self, rep: _ReplicaBase, *, probe: bool) -> bool:
        try:
            rep.start()
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            rep.last_error = repr(e)
            rep.consec_failures += 1
            if probe:
                # failed probe: stay quarantined, backoff doubles
                rep.backoff_s = min((rep.backoff_s or self.backoff0_s)
                                    * 2.0, 30.0)
                rep.quarantine_until = time.monotonic() + rep.backoff_s
                _emit("router_probe", replica=rep.idx, ok=False,
                      error=repr(e), backoff_s=rep.backoff_s)
            else:
                self._quarantine(rep, f"start: {e!r}")
            return False
        if probe or rep.consec_failures:
            rep.respawns += 1
            self._prom.counter_inc("router_respawns_total",
                                   help="replicas respawned onto their "
                                        "journal")
            _emit("router_probe", replica=rep.idx, ok=True,
                  respawns=rep.respawns)
        if probe:
            # a successful probe proved the replica can come back: fresh
            # failure budget. A plain restart does NOT reset the count —
            # 'consecutive' survives crash-restart loops, only a
            # successful dispatch clears it.
            rep.consec_failures = 0
        rep.backoff_s = None
        return True

    # -- the scheduling round ------------------------------------------------
    def step(self):
        self.steps += 1
        now = time.monotonic()
        # 0) promote warmed-up replicas; catch startup deaths
        for rep in self.replica_set:
            if rep.state != "starting":
                continue
            if isinstance(rep, SpawnedReplica):
                rc = rep.proc.poll() if rep.proc is not None else -1
                if rc is not None:
                    rep.exit_code = rc
                    rep.exit_codes.append(rc)
                    self._failover(rep, f"process_exit rc={rc} (startup)")
                elif rep.heartbeat_fresh():
                    rep.state = "ready"
            else:
                rep.state = "ready"
        # 1) probe quarantined replicas whose backoff expired
        for rep in self.replica_set:
            if rep.state == "quarantined" and now >= rep.quarantine_until:
                self._try_start(rep, probe=True)
        # 2) health-driven dispatch
        self._dispatch()
        # 3) advance/observe every ready replica (round-robin: one engine
        #    step per in-process replica per round keeps interleaving —
        #    and any armed global fault-site hit counter — deterministic)
        for rep in self.replica_set:
            if rep.state != "ready":
                continue
            finished, death = rep.poll(
                lambda l, t, rep=rep: self._deliver(rep, l, t))
            for lid, status, err in finished:
                rep.assigned.pop(lid, None)
                self._finish(lid, status, err, replica=rep)
            if death is not None:
                self._failover(rep, death)
        # 4) heartbeat: a wedged replica is failed over like a dead one
        for rep in self.replica_set:
            if rep.state != "ready":
                continue
            if (_faults().maybe_trigger("replica/heartbeat")
                    or rep.heartbeat_age() > self.heartbeat_timeout_s):
                self._failover(rep, "heartbeat_timeout")
        self._refresh_gauges()

    def _finish(self, lid: int, status: str, err: Optional[str],
                replica: Optional[_ReplicaBase] = None):
        if self.statuses.get(lid) in _TERMINAL:
            return
        if status in ("shed", "cancelled") and replica is not None:
            # the replica dropped it (deadline/overload/drain) without
            # finishing: the fleet still owns the request — requeue with
            # the watermark rather than surfacing a replica-local shed
            if self.statuses.get(lid) not in _TERMINAL:
                self.statuses[lid] = "pending"
                self.queue.append(lid)
                self.requeues += 1
                self._prom.counter_inc("router_requeued_total")
                return
        self.statuses[lid] = status
        if err:
            self.errors[lid] = err
        if status == "done":
            for rep in self.replica_set:
                if isinstance(rep, InProcessReplica) and \
                        rep.journal.statuses.get(lid) is None and \
                        self.owner.get(lid) == rep.idx:
                    rep.journal.mark(lid, "done")
        self._prom.counter_inc(f"router_{status}_total",
                               help="requests by terminal status")

    def _refresh_gauges(self):
        self._prom.gauge_set("router_queue_depth", len(self.queue),
                             help="requests waiting at the fleet door")
        self._prom.gauge_set("replicas_ready",
                             len(self.replica_set.ready()),
                             help="replicas currently routable")
        for rep in self.replica_set:
            i = rep.idx
            self._prom.gauge_set(f"replica_state_{i}",
                                 STATE_CODES.get(rep.state, -1),
                                 help="0=starting 1=ready 2=draining "
                                      "3=quarantined 4=dead")
            pend, ttft, util = rep.load()
            self._prom.gauge_set(f"replica_queue_depth_{i}", pend)
            self._prom.gauge_set(f"replica_pool_utilization_{i}", util)
            if ttft:
                self._prom.gauge_set(f"replica_ttft_p95_{i}", ttft)
            for name, v in rep.extra_stats().items():
                # kv_pages_shared / kv_cow_copies_total /
                # spec_proposed_total / spec_accepted_total (ISSUE 17) —
                # accepted/proposed is the fleet speculation health rate
                self._prom.gauge_set(f"replica_{name}_{i}", v)

    def has_work(self) -> bool:
        return (bool(self.queue)
                or any(self.statuses.get(lid) == "running"
                       for lid in range(len(self.requests))))

    def run(self, max_steps: int = 100000, *, poll_s: float = 0.005,
            deadline_s: Optional[float] = None
            ) -> Tuple[Dict[int, List[int]], Dict[str, Any]]:
        """Drive every submitted request to a terminal status; returns
        ``(results, info)`` shaped like ``run_serving_resilient`` —
        results maps lid to its delivered tokens."""
        t_end = (time.monotonic() + deadline_s
                 if deadline_s is not None else None)
        spawned = any(isinstance(r, SpawnedReplica)
                      for r in self.replica_set)
        for _ in range(max_steps):
            if not self.has_work():
                break
            if t_end is not None and time.monotonic() > t_end:
                break
            before = sum(len(v) for v in self.delivered.values())
            self.step()
            after = sum(len(v) for v in self.delivered.values())
            if spawned and after == before:
                time.sleep(poll_s)  # workers self-step; don't spin hot
        info = {"steps": self.steps, "failovers": self.failovers,
                "requeued": self.requeues, "sheds": self.sheds,
                "statuses": dict(self.statuses),
                "replica_states": self.replica_set.states(),
                "leftover": sorted(
                    lid for lid, s in self.statuses.items()
                    if s not in _TERMINAL)}
        results = {lid: list(self.delivered.get(lid, []))
                   for lid in range(len(self.requests))}
        _emit("router_run_end", **{k: info[k] for k in
                                   ("steps", "failovers", "requeued",
                                    "sheds", "leftover")})
        return results, info

    def close(self, *, timeout: float = 60.0):
        """Drain the fleet down: close every spawned inbox (the worker
        exits once its work is done), wait, and stop the front door."""
        for rep in self.replica_set:
            if isinstance(rep, SpawnedReplica):
                rep.send_close()
        for rep in self.replica_set:
            if isinstance(rep, SpawnedReplica):
                if rep.wait(timeout) is None:
                    rep.stop(self.grace_s, "close")
                rep.state = "dead"
        if self._server is not None:
            self._server.stop()
            self._server = None

    def snapshot(self) -> Dict[str, Any]:
        """Host-state fleet snapshot for flight-recorder bundles
        (``router.json``): per-replica lifecycle + failure counters,
        queue, per-lid status/watermark."""
        return {
            "fleet_health": self.fleet_health(),
            "steps": self.steps, "failovers": self.failovers,
            "requeued": self.requeues, "sheds": self.sheds,
            "queue": list(self.queue),
            "replicas": [r.snapshot() for r in self.replica_set],
            "requests": {
                lid: {"status": self.statuses.get(lid),
                      "delivered": len(self.delivered.get(lid, [])),
                      "owner": self.owner.get(lid)}
                for lid in range(len(self.requests))},
        }


# -- acceptance harnesses ----------------------------------------------------
def router_failover_check(workdir: str, *, ragged: bool = False,
                          n_replicas: int = 2,
                          fault: str = "serving/step:5"
                          ) -> Dict[str, Any]:
    """In-process acceptance (tier-1 + dryrun leg): a 2-replica fleet,
    replica 0's engine killed mid-generation by an armed ``serving/step``
    fault (raise form — the hit counter is global, so with strict
    round-robin stepping hit 5 lands on replica 0's 3rd step, after it
    has delivered tokens). Asserts every request completes with greedy
    outputs bitwise-identical to ``gpt_generate``, exactly-once delivery,
    EXACTLY one ``router_failover`` event, fleet /healthz 200 at every
    poll, and full capacity (every replica ready) after recovery."""
    import urllib.request
    import jax.numpy as jnp
    from ..models.generation import gpt_generate
    from ..observability import EventLog, get_event_log, set_event_log
    from .replay_worker import workload
    from .serving import ServingEngine

    cfg, params, prompts, news = workload()

    def make_engine():
        # decode_burst=2 stretches each request across several engine
        # steps so the armed serving/step hit lands MID-generation (a
        # full burst would finish the whole workload before it fires)
        return ServingEngine(params, cfg, max_batch=2, block_size=8,
                             num_blocks=24, max_blocks_per_seq=8, chunk=8,
                             decode_burst=2, ragged=ragged,
                             adaptive_mix=False)

    golden = {}
    for lid, (p, n) in enumerate(zip(prompts, news)):
        out = gpt_generate(params, cfg, jnp.asarray(p, jnp.int32)[None], n)
        golden[lid] = np.asarray(out)[0, len(p):].tolist()

    log_path = os.path.join(workdir, "router_events.jsonl")
    prev_log = get_event_log()
    set_event_log(EventLog(log_path))
    faults = _faults()
    faults.configure(fault)
    healthz_polls = 0
    try:
        rs = ReplicaSet.in_process(make_engine, n=n_replicas,
                                   journal_dir=workdir)
        router = Router(rs)
        server = router.serve_metrics(port=0)
        delivered_cb: Dict[int, List[int]] = {i: [] for i in golden}
        for lid, (p, n) in enumerate(zip(prompts, news)):
            router.submit(p, n, on_token=lambda l, t: delivered_cb[l]
                          .append(int(t)))
        url = f"http://127.0.0.1:{server.port}/healthz"
        tokens_at_failover = None
        while router.has_work():
            router.step()
            code = urllib.request.urlopen(url, timeout=5).getcode()
            assert code == 200, f"fleet /healthz flapped: {code}"
            healthz_polls += 1
            if router.failovers and tokens_at_failover is None:
                tokens_at_failover = sum(len(v) for v in
                                         router.delivered.values())
        results = {lid: router.delivered[lid] for lid in golden}
        router.close()
    finally:
        faults.configure("")
        set_event_log(prev_log)

    assert results == golden, (results, golden)
    assert delivered_cb == golden, "on_token delivery not exactly-once"
    assert router.failovers == 1, router.failovers
    with open(log_path, encoding="utf-8") as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    fo = [e for e in evs if e.get("event") == "router_failover"]
    assert len(fo) == 1, fo
    assert all(s == "ready" for s in router.replica_set.states()), \
        router.replica_set.states()
    # zero leaked pages on every live engine after the full fleet run
    for rep in router.replica_set:
        free, total = rep.free_pool()
        if free is not None:
            assert free == total, (rep.idx, free, total)
    total_tokens = sum(len(v) for v in golden.values())
    return {"requests": len(golden), "tokens": total_tokens,
            "tokens_pre_failover": tokens_at_failover or 0,
            "failovers": router.failovers, "requeued": router.requeues,
            "healthz_polls": healthz_polls,
            "failed_replica": fo[0].get("replica"), "ragged": ragged}


def router_spawn_check(workdir: str, *, ragged: bool = False,
                       timeout: float = 300.0) -> Dict[str, Any]:
    """Cross-process acceptance (ISSUE 16 satellite): a 2-replica SPAWNED
    fleet, replica 0 hard-killed (``serving/step:3:kill`` — os._exit in
    the worker, a real crash) mid-generation. Every request must complete
    on replica 1 with exactly-once delivery (pre-kill journal tokens +
    post-failover tokens concatenate to golden, no dupes/gaps), bitwise
    greedy outputs, zero leaked KV pages on the survivor, fleet /healthz
    200 throughout, and replica 0 respawned to ready on the same
    journal."""
    import subprocess
    import sys
    import urllib.error
    import urllib.request
    from ..distributed.resilience.faults import FAULT_EXIT_CODE
    from .replay_worker import workload

    cfg, params, prompts, news = workload()
    # golden comes from a SPAWNED uninterrupted run (the kill_replay_check
    # pattern), not in-process generation: the fleet workers are clean
    # processes, while the calling process may carry arbitrary global
    # jax/flag state — in-process numerics need not match theirs bitwise.
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    g_dir = os.path.join(workdir, "golden")
    os.makedirs(g_dir, exist_ok=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", FLAGS_fault_inject="",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.inference.replay_worker",
         g_dir] + ([] if ragged else ["--two"]),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    g_out, g_err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, (proc.returncode, g_err)
    golden: Dict[int, List[int]] = {}
    for line in g_out.splitlines():
        if line.startswith("RESULT "):
            rec = json.loads(line[len("RESULT "):])
            golden = {int(k): v for k, v in rec["delivered"].items()}
    assert golden, ("no RESULT from golden run", g_out, g_err)

    rs = ReplicaSet.spawned(workdir, n=2, two_program=not ragged,
                            faults={0: "serving/step:3:kill"})
    # generous heartbeat budget: this check asserts the SCRIPTED kill is
    # the death cause — on a loaded CI box a live worker can stall past
    # the default timeout and get SIGTERM-drained first, which is
    # legitimate router behavior but not what is under test here
    router = Router(rs, heartbeat_timeout_s=60.0)
    server = router.serve_metrics(port=0)
    url = f"http://127.0.0.1:{server.port}/healthz"
    t_end = time.monotonic() + timeout
    # warm the whole fleet up BEFORE submitting: dispatch is health-driven
    # (cold replicas are not routable), so submitting against a
    # half-warmed fleet would send everything to whichever worker
    # heartbeated first — including the armed one's workload
    while not all(s == "ready" for s in rs.states()):
        assert time.monotonic() < t_end, (
            "fleet never warmed up", router.snapshot())
        router.step()
        time.sleep(0.05)
    for lid, (p, n) in enumerate(zip(prompts, news)):
        router.submit(p, n)
    healthz_polls = 0
    while router.has_work():
        assert time.monotonic() < t_end, (
            "spawned fleet did not converge", router.snapshot())
        router.step()
        try:
            code = urllib.request.urlopen(url, timeout=5).getcode()
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 200, f"fleet /healthz flapped: {code}"
        healthz_polls += 1
        time.sleep(0.02)
    # recovery to FULL capacity: keep stepping (healthz still 200 — one
    # ready survivor suffices) until the respawned replica 0 heartbeats
    # its way back to ready
    while not all(s == "ready" for s in rs.states()):
        assert time.monotonic() < t_end, (
            "fleet did not recover full capacity", router.snapshot())
        router.step()
        code = urllib.request.urlopen(url, timeout=5).getcode()
        assert code == 200, f"fleet /healthz flapped post-run: {code}"
        healthz_polls += 1
        time.sleep(0.05)
    results = {lid: router.delivered[lid] for lid in golden}

    # bitwise parity + exactly-once at the client
    assert results == golden, (results, golden)
    assert router.failovers >= 1
    r0, r1 = rs[0], rs[1]
    assert FAULT_EXIT_CODE in r0.exit_codes, r0.exit_codes
    # exactly-once ACROSS the process boundary: replica 0's journal holds
    # only pre-kill tokens (the respawned generation got no reassigned
    # work — the inbox generation bump guarantees it); replica 1's holds
    # the rest. Their per-lid concatenation must equal golden exactly.
    def journal_toks(rep):
        toks: Dict[int, List[int]] = {}
        with open(rep.journal_path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break
                if "tok" in rec:
                    toks.setdefault(int(rec["lid"]), []).append(
                        int(rec["tok"]))
        return toks
    pre, post = journal_toks(r0), journal_toks(r1)
    assert any(pre.values()), "kill fired before any delivery"
    for lid, out_g in golden.items():
        both = pre.get(lid, []) + post.get(lid, [])
        assert both == out_g, (lid, pre.get(lid), post.get(lid), out_g)
    # full capacity after respawn: BOTH replicas ready, r0 on gen 2
    assert all(s == "ready" for s in rs.states()), rs.states()
    assert r0.gen == 2 and r0.respawns >= 1, (r0.gen, r0.respawns)
    router.close(timeout=timeout)
    res1 = r1.result()
    assert res1 is not None, "survivor produced no RESULT"
    assert res1["free_blocks"] == res1["pool_blocks"], res1
    return {"requests": len(golden),
            "tokens_pre_kill": sum(len(v) for v in pre.values()),
            "tokens_post_failover": sum(len(v) for v in post.values()),
            "failovers": router.failovers, "requeued": router.requeues,
            "healthz_polls": healthz_polls,
            "survivor_free_blocks": res1["free_blocks"],
            "survivor_pool_blocks": res1["pool_blocks"],
            "ragged": ragged}
