"""Sparse tensors (reference: paddle/phi/core/sparse_coo_tensor.h /
sparse_csr_tensor.h, kernels paddle/phi/kernels/sparse/, Python
python/paddle/sparse/).

TPU design: wraps jax.experimental.sparse BCOO (TPU-lowerable; XLA turns
sparse@dense matmuls into gather/scatter + MXU tiles). CSR is kept as a
view-format conversion — BCOO is the compute format on TPU.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_sparse", "to_dense", "to_sparse_coo", "add", "matmul",
           "masked_matmul", "nnz", "relu", "tanh"]

SparseCooTensor = jsparse.BCOO


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True):
    """indices: [ndim, nnz] (reference layout); values: [nnz]."""
    del place, stop_gradient
    indices = jnp.asarray(indices, jnp.int32).T  # BCOO wants [nnz, ndim]
    values = jnp.asarray(values, dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in jnp.max(indices, axis=0))
    return jsparse.BCOO((values, indices), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """Build from CSR triplets; stored as BCOO (the TPU compute format)."""
    crows = np.asarray(crows)
    cols = np.asarray(cols)
    values = jnp.asarray(values, dtype)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.asarray(np.stack([rows, cols]), jnp.int32).T
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def is_sparse(x) -> bool:
    return isinstance(x, jsparse.JAXSparse)


def to_dense(x):
    return x.todense() if is_sparse(x) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim: Optional[int] = None):
    del sparse_dim
    return jsparse.BCOO.fromdense(jnp.asarray(x))


def nnz(x) -> int:
    return int(x.nse)


def add(a, b):
    if is_sparse(a) and is_sparse(b):
        return jsparse.bcoo_add(a, b) if hasattr(jsparse, "bcoo_add") else \
            to_sparse_coo(a.todense() + b.todense())
    return to_dense(a) + to_dense(b)


def matmul(a, b):
    """sparse @ dense (or dense @ sparse) — XLA lowers the gather/dot."""
    return a @ b


def masked_matmul(a, b, mask):
    """(a @ b) sampled at mask's sparsity pattern (reference:
    paddle.sparse.masked_matmul) — SDDMM."""
    dense = jnp.asarray(a) @ jnp.asarray(b)
    idx = mask.indices  # [nnz, 2]
    vals = dense[idx[:, 0], idx[:, 1]]
    return jsparse.BCOO((vals, mask.indices), shape=mask.shape)


def _unary(fn):
    def op(x):
        if is_sparse(x):
            return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape)
        return fn(jnp.asarray(x))
    return op


relu = _unary(jax.nn.relu)
tanh = _unary(jnp.tanh)
