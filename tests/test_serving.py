"""Serving-tier tests (VERDICT r2 #4): continuous batching + chunked
prefill over the paged pool must reproduce the one-shot gpt_generate
goldens exactly (greedy), stream tokens, and recycle blocks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import (ServingEngine,
                                          generate_static_batch)
from paddle_tpu.models import gpt as G
from paddle_tpu.models.generation import gpt_generate

CFG = G.GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                  max_seq_len=128, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return G.init_hybrid_params(CFG, jax.random.PRNGKey(0))


def golden(params, prompt, n):
    out = gpt_generate(params, CFG, jnp.asarray(prompt, jnp.int32)[None], n)
    return np.asarray(out)[0, len(prompt):].tolist()


def test_single_request_matches_generate(params):
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, CFG.vocab_size, (11,))
    eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                        num_blocks=32, max_blocks_per_seq=8, chunk=4)
    rid = eng.add_request(prompt, max_new_tokens=7)
    res = eng.run()
    assert res[rid] == golden(params, prompt, 7)


def test_concurrent_ragged_requests_match_generate(params):
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, CFG.vocab_size, (n,))
               for n in (5, 13, 9, 16, 3)]
    news = [6, 3, 9, 4, 8]
    eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                        num_blocks=24, max_blocks_per_seq=8, chunk=8)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, news)]
    res = eng.run()
    for rid, p, n in zip(rids, prompts, news):
        assert res[rid] == golden(params, p, n), rid


def test_streaming_callback_order(params):
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, CFG.vocab_size, (6,))
    seen = []
    eng = ServingEngine(params, CFG, max_batch=1, block_size=8,
                        num_blocks=16, max_blocks_per_seq=4, chunk=8)
    rid = eng.add_request(prompt, 5, on_token=lambda r, t: seen.append((r, t)))
    res = eng.run()
    assert [t for _, t in seen] == res[rid]
    assert all(r == rid for r, _ in seen)


def test_blocks_recycled_across_many_requests(params):
    """More total work than the pool could ever hold at once — finishing
    requests must return their blocks (admit/evict)."""
    rng = np.random.RandomState(3)
    eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                        num_blocks=9, max_blocks_per_seq=4, chunk=8)
    total_free = len(eng.free_blocks)
    prompts = [rng.randint(0, CFG.vocab_size, (8,)) for _ in range(6)]
    rids = [eng.add_request(p, 4) for p in prompts]
    res = eng.run()
    assert len(res) == 6
    assert len(eng.free_blocks) == total_free  # everything returned
    for rid, p in zip(rids, prompts):
        assert res[rid] == golden(params, p, 4)


def test_eos_stops_early(params):
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, CFG.vocab_size, (9,))
    g = golden(params, prompt, 10)
    eos = g[3]
    eng = ServingEngine(params, CFG, max_batch=1, block_size=8,
                        num_blocks=16, max_blocks_per_seq=8, chunk=8)
    rid = eng.add_request(prompt, 10, eos_id=eos)
    res = eng.run()
    assert res[rid] == g[:4]


def test_oversized_request_rejected_per_request(params):
    """A request that can NEVER fit is rejected per-request
    (status='failed', naming the binding cap) — it must not abort the
    engine step and strand its queued siblings (ISSUE 13 satellite)."""
    rng = np.random.RandomState(21)
    sib = rng.randint(0, CFG.vocab_size, (9,))
    eng = ServingEngine(params, CFG, max_batch=1, block_size=8,
                        num_blocks=16, max_blocks_per_seq=2, chunk=8)
    bad = eng.add_request(np.zeros(20, np.int32), 10)
    good = eng.add_request(sib, 5)
    res = eng.run()
    assert res.statuses[bad] == "failed"
    assert res[bad] == []
    assert res.statuses[good] == "ok"
    assert res[good] == golden(params, sib, 5)  # sibling unharmed


def test_tp_sharded_decode_matches_generate(params):
    """Megatron-TP serving over an mp mesh axis (VERDICT r3 #8): sharded
    qkv/proj/fc + head-sharded KV pools + vocab-parallel logits must
    reproduce the single-device goldens exactly."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("mp",))
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, CFG.vocab_size, (n,)) for n in (9, 14, 5)]
    news = [6, 4, 8]
    eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                        num_blocks=24, max_blocks_per_seq=8, chunk=8,
                        mesh=mesh, mp_axis="mp")
    rids = [eng.add_request(p, n) for p, n in zip(prompts, news)]
    res = eng.run()
    for rid, p, n in zip(rids, prompts, news):
        assert res[rid] == golden(params, p, n), rid


def test_adaptive_burst_frees_slots_early(params):
    """With a queue waiting, the burst shortens to the earliest finisher
    (power-of-two programs) so freed slots re-admit before the next
    burst — total decode steps spent must shrink vs the fixed burst."""
    rng = np.random.RandomState(8)
    prompts = [rng.randint(0, CFG.vocab_size, (6,)) for _ in range(6)]
    news = [2, 3, 2, 9, 2, 3]  # short finishers + queue pressure

    def run(adaptive):
        eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                            num_blocks=32, max_blocks_per_seq=8, chunk=8,
                            decode_burst=8, adaptive_burst=adaptive)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, news)]
        while eng.has_work():
            eng.step()
        return eng.decode_microsteps

    s_adaptive = run(adaptive=True)
    s_fixed = run(adaptive=False)
    # adaptive spends fewer DEVICE decode steps (it trades them for more
    # dispatches — a win only when dispatch overhead is low, hence opt-in)
    assert s_adaptive <= s_fixed
    # and outputs still match goldens
    eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                        num_blocks=32, max_blocks_per_seq=8, chunk=8,
                        decode_burst=8, adaptive_burst=True)
    rids = [eng.add_request(p, n) for p, n in zip(prompts, news)]
    res = eng.run()
    for rid, p, n in zip(rids, prompts, news):
        assert res[rid] == golden(params, p, n), rid


def test_int8_serving_close_to_fp(params):
    """W8A8 serving (int8=True): weights quantized per output channel,
    activations per call — generated tokens track the fp engine closely
    (greedy, short decodes; W8A8 error can flip late low-margin tokens,
    so assert high agreement rather than exact match)."""
    rng = np.random.RandomState(12)
    prompts = [rng.randint(0, CFG.vocab_size, (n,)) for n in (9, 13)]
    news = [6, 6]

    def run(int8):
        eng = ServingEngine(params, CFG, max_batch=2, block_size=8,
                            num_blocks=24, max_blocks_per_seq=8, chunk=8,
                            int8=int8)
        rids = [eng.add_request(p, n) for p, n in zip(prompts, news)]
        res = eng.run()
        return [res[r] for r in rids]

    fp = run(False)
    q8 = run(True)
    total = sum(len(o) for o in fp)
    agree = sum(a == b for o1, o2 in zip(fp, q8)
                for a, b in zip(o1, o2))
    assert agree / total >= 0.75, (fp, q8)
    # first token (largest margin) must agree per request
    for o1, o2 in zip(fp, q8):
        assert o1[0] == o2[0]


def test_static_batch_mixed_prompt_lengths(params):
    """The static baseline buckets mixed-length prompts by length and pads
    to the bucket max; equal-length groups still match goldens exactly."""
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, CFG.vocab_size, (n,)) for n in (8, 8, 12, 12)]
    news = [5, 4, 6, 3]
    outs = generate_static_batch(params, CFG, prompts, news, batch_size=2)
    for p, n, o in zip(prompts, news, outs):
        assert o == golden(params, p, n)


def test_static_batch_baseline_matches_generate(params):
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, CFG.vocab_size, (8,)) for _ in range(4)]
    news = [3, 6, 2, 5]
    outs = generate_static_batch(params, CFG, prompts, news, batch_size=2)
    for p, n, o in zip(prompts, news, outs):
        assert o == golden(params, p, n)
