"""Multi-process (2 procs x 4 virtual CPU devices) reference-pattern tests
(reference: test/legacy_test/test_dist_base.py:954 TestDistBase._run_cluster
:1206 — subprocess spawn + env rendezvous, loss parity vs the single-process
golden run; SURVEY §4 item 2).

The worker half lives in tests/mp_worker.py; this file is the launcher half:
it computes the single-process golden on the in-process 8-device mesh, spawns
the 2-process cluster, and compares.
"""

import os
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.mp_smoke import ClusterUnsupported, spawn_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")


@pytest.fixture(scope="module")
def cluster_results(tmp_path_factory):
    ckpt = str(tmp_path_factory.mktemp("mp_ckpt"))
    try:
        return spawn_cluster([sys.executable, WORKER], nproc=2,
                             devices_per_proc=4, sentinel="RESULT ",
                             extra_env={"MP_TEST_CKPT_DIR": ckpt},
                             timeout=240)
    except ClusterUnsupported as e:
        # this jax build can't run cross-process CPU collectives at all —
        # skip (clear reason) rather than error the whole spawn family
        pytest.skip(f"mp spawn unsupported on this platform: {e}")


def test_two_process_loss_parity_vs_single_process(cluster_results):
    """dp2 x mp4 over 2 processes must track the identical single-process
    8-device run step for step (the reference's check_with_place contract,
    test_dist_base.py:1694)."""
    import paddle_tpu.distributed as dist
    from tests.mp_worker import run_training

    mesh = dist.build_mesh({"dp": 2, "pp": 1, "mp": 4})
    golden, _ = run_training(mesh)

    for res in cluster_results:
        assert len(res["losses"]) == len(golden)
        np.testing.assert_allclose(res["losses"], golden, rtol=0, atol=5e-5)


def test_two_process_ranks_agree(cluster_results):
    r0, r1 = sorted(cluster_results, key=lambda r: r["rank"])
    assert (r0["rank"], r1["rank"]) == (0, 1)
    assert r0["world"] == r1["world"] == 2
    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=0, atol=0)


def test_two_process_collective_suite(cluster_results):
    """all_reduce/all_gather/reduce_scatter/broadcast over both the
    cross-host (dp) and intra-host (mp) axes; goldens asserted inside the
    worker, cross-rank consistency here."""
    r0, r1 = sorted(cluster_results, key=lambda r: r["rank"])
    c0, c1 = r0["collectives"], r1["collectives"]
    # dp reduce_scatter: rank p keeps element [p] of the summed vector
    # (sum = 2*arange(2) + 1 = [1, 3])
    assert c0["reduce_scatter_dp"] == 1.0
    assert c1["reduce_scatter_dp"] == 3.0
    for key in ("all_reduce_dp", "all_gather_dp", "broadcast_dp",
                "all_reduce_mp", "all_gather_mp", "reduce_scatter_mp",
                "broadcast_mp"):
        assert c0[key] == c1[key], key


def test_two_process_distributed_checkpoint(cluster_results):
    for res in cluster_results:
        assert res["ckpt_ok"] is True


def _spawn_and_check(n, golden, mode, timeout=240):
    """spawn_and_check that converts platform incapability into a skip."""
    from paddle_tpu.distributed import mp_smoke
    try:
        mp_smoke.spawn_and_check(n, golden, mode=mode, timeout=timeout)
    except ClusterUnsupported as e:
        pytest.skip(f"mp spawn unsupported on this platform: {e}")


def test_two_process_ring_attention_parity():
    """Ring attention with the SEP axis spanning both processes: the
    ring's edge hops (2 of n with the contiguous hybrid layout) are
    cross-process ppermutes — the long-context DCN path at this box's
    fidelity — with loss+grad-descent series parity vs the
    single-process run."""
    from paddle_tpu.distributed import mp_smoke

    golden = mp_smoke.golden_for(8, "sepring")
    assert all(np.isfinite(golden)), golden
    _spawn_and_check(8, golden, mode="sepring")


@pytest.mark.parametrize("mode", ["pp1f1b", "ppzbh1"])
def test_two_process_pipeline_parity(mode):
    """pp2 x mp4 with the PIPELINE axis on the process boundary: each stage
    lives on its own process and every 1F1B/ZBH1 ppermute hop crosses it —
    loss parity vs the identical single-process run (VERDICT r3 #4; the
    reference's cross-node p2p integration,
    fleet/meta_parallel/pp_utils/p2p_communication.py:570)."""
    from paddle_tpu.distributed import mp_smoke

    golden = mp_smoke.golden_for(8, mode)
    assert all(np.isfinite(golden)), golden
    _spawn_and_check(8, golden, mode=mode)


def test_two_process_vpp_parity():
    """Interleaved virtual-pipeline (vpp2) with the pp axis on the
    process boundary: every circular-permute hop of the V-chunk schedule
    — including the rank-(P-1) -> rank-0 wrap — crosses the boundary
    (ISSUE 9 satellite / VERDICT missing #6)."""
    from paddle_tpu.distributed import mp_smoke

    golden = mp_smoke.golden_for(8, "ppvpp")
    assert all(np.isfinite(golden)), golden
    _spawn_and_check(8, golden, mode="ppvpp")


def test_two_process_epmoe_parity():
    """GPT-MoE with the EXPERT-parallel axis on the process boundary:
    the index-dispatch expert all-to-alls cross it every layer, and the
    spec-aware ep gradient combine (pmean for replicated leaves, 1/ep
    rescale for the expert bank) must reproduce the single-process run
    (ISSUE 9 satellite / VERDICT missing #6)."""
    from paddle_tpu.distributed import mp_smoke

    golden = mp_smoke.golden_for(8, "epmoe")
    assert all(np.isfinite(golden)), golden
    _spawn_and_check(8, golden, mode="epmoe")


def test_hybrid_mesh_construction_virtual():
    """Single-process unit check of the hybrid construction path: feed
    build_mesh devices tagged with fake process indices and assert inner
    axes stay intra-process (the create_hybrid_device_mesh contract)."""
    from paddle_tpu.distributed.topology import _hybrid_device_array

    class FakeDev:
        def __init__(self, pid, i):
            self.process_index = pid
            self.id = pid * 100 + i
            self.platform = "cpu"

        def __repr__(self):
            return f"d{self.process_index}.{self.id % 100}"

    devs = [FakeDev(p, i) for p in range(4) for i in range(4)]
    # dp4 x mp4 over 4 procs x 4 local: mp intra-proc, dp across procs
    arr = _hybrid_device_array((4, 4), devs)
    for i in range(4):
        assert len({d.process_index for d in arr[i]}) == 1
    assert [arr[i, 0].process_index for i in range(4)] == [0, 1, 2, 3]

    # straddling axis: dp8 x mp2 over 4 procs x 4 local — dp splits into
    # (dcn 4, ici 2)
    arr = _hybrid_device_array((8, 2), devs)
    for i in range(8):
        assert len({d.process_index for d in arr[i]}) == 1
    assert [arr[i, 0].process_index for i in range(8)] == [0, 0, 1, 1,
                                                           2, 2, 3, 3]

    # uneven per-process counts must raise
    with pytest.raises(ValueError):
        _hybrid_device_array((4, 4), devs[:12] + devs[:4])

    # non-divisible inner axis must raise, not silently route mp over DCN:
    # 6 procs x 4 local, inner degree 6 (6 % 4 != 0)
    devs24 = [FakeDev(p, i) for p in range(6) for i in range(4)]
    with pytest.raises(ValueError):
        _hybrid_device_array((4, 6), devs24)


def test_two_process_zero1_parity():
    """ZeRO-1 over a dp axis that SPANS two real processes (round 5): the
    grad reduce-scatter, param all-gather and the axes-aware global-norm
    clip psum all cross the process boundary — loss parity vs the
    identical single-process run (reference: DygraphShardingOptimizer
    stage-1 across trainers)."""
    from paddle_tpu.distributed import mp_smoke

    golden = mp_smoke.golden_for(8, "z1dpmp")
    assert all(np.isfinite(golden)), golden
    _spawn_and_check(8, golden, mode="z1dpmp")


def test_two_process_zero3_parity():
    """ZeRO-3 over a dp axis that SPANS two real processes (round 9):
    params resident as cross-process dp shards, every layer's per-block
    param all-gather (and its psum_scatter transpose in the backward)
    crosses the boundary every step, plus the stage-3 global-norm clip
    psum — loss parity vs the identical single-process run."""
    from paddle_tpu.distributed import mp_smoke

    golden = mp_smoke.golden_for(8, "z3dpmp")
    assert all(np.isfinite(golden)), golden
    _spawn_and_check(8, golden, mode="z3dpmp")
