"""paddle.nn equivalent namespace."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import ParamAttr  # noqa: F401
from .layer.layers import Layer, Parameter, functional_call, functional_train_graph  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue, clip_grad_norm_, global_norm)

from .layer import (activation, common, container, conv, loss, norm, pooling,
                    transformer)

__all__ = (
    ["Layer", "Parameter", "functional_call", "functional_train_graph",
     "ParamAttr", "functional", "initializer", "ClipGradByValue",
     "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grad_norm_",
     "global_norm"]
    + list(common.__all__) + list(conv.__all__) + list(norm.__all__)
    + list(activation.__all__) + list(container.__all__)
    + list(pooling.__all__) + list(loss.__all__) + list(transformer.__all__)
)
