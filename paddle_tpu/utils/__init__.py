from .compat import shard_map  # noqa: F401

__all__ = ["shard_map"]
