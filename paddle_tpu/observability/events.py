"""Structured JSONL event log — the crash-forensics channel.

One JSON object per line, flushed per event, so a SIGKILL mid-run leaves
every completed line readable (the same discipline as the resilience
commit protocol's atomic writes). Schema: every event carries

    {"ts": <unix seconds>, "pid": <os pid>, "event": "<kind>", ...fields}

Producers: the resilient runner (resume/commit/skip/SIGTERM/abort), the
TelemetryHost (decoded device-metric intervals), Model.fit (step reports)
and the serving engine (admits/completions). The process-global log is
bound to ``FLAGS_telemetry_jsonl``; pass an explicit :class:`EventLog`
where a private file is wanted (tests, multi-run drivers).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

__all__ = ["EventLog", "get_event_log", "set_event_log"]


class EventLog:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, event: str, **fields: Any) -> None:
        rec = {"ts": round(time.time(), 6), "pid": os.getpid(),
               "event": str(event)}
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()  # per-line durability: forensics-friendly

    def span(self, name: str):
        """Host span recorded BOTH as begin/end JSONL events and as a
        profiler HostEvent, so it lands in Profiler summaries/chrome
        traces too (the unified-trace contract)."""
        return _Span(self, name)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _Span:
    def __init__(self, log: EventLog, name: str):
        self._log = log
        self._name = name
        from ..profiler.utils import RecordEvent
        self._rec = RecordEvent(name)

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._log.emit("span_begin", name=self._name)
        self._rec.begin()
        return self

    def __exit__(self, *exc):
        self._rec.end()
        self._log.emit("span_end", name=self._name,
                       duration_s=round(time.perf_counter() - self._t0, 6))
        return False


def _jsonable(x):
    try:
        return float(x)
    except (TypeError, ValueError):
        return repr(x)


_GLOBAL: Optional[EventLog] = None
_GLOBAL_PATH: Optional[str] = None
_EXPLICIT = False
_LOCK = threading.Lock()


def get_event_log() -> Optional[EventLog]:
    """The process event log: an explicitly installed one
    (:func:`set_event_log`) wins; otherwise the log bound to
    FLAGS_telemetry_jsonl (None when the flag is empty), re-bound if the
    flag changed since the last call."""
    global _GLOBAL, _GLOBAL_PATH
    with _LOCK:
        if _EXPLICIT:
            return _GLOBAL
    from ..flags import flag
    path = str(flag("telemetry_jsonl") or "")
    with _LOCK:
        if _EXPLICIT:
            return _GLOBAL
        if _GLOBAL is not None and _GLOBAL_PATH == path:
            return _GLOBAL
        if _GLOBAL is not None:
            _GLOBAL.close()
            _GLOBAL, _GLOBAL_PATH = None, None
        if path:
            _GLOBAL = EventLog(path)
            _GLOBAL_PATH = path
    return _GLOBAL


def emit_event(event: str, **fields) -> None:
    """Emit one event iff a process log is configured — the one copy of
    the get_event_log-guarded emit the resilience/checkpoint layers use
    for lifecycle forensics (resume/commit/reshard/carry decisions)."""
    log = get_event_log()
    if log is not None:
        log.emit(event, **fields)


def set_event_log(log: Optional[EventLog]) -> Optional[EventLog]:
    """Install an explicit process log (tests/drivers) that shadows the
    flag binding; set_event_log(None) restores flag-driven behavior.
    Returns the previous log (not closed — caller owns both)."""
    global _GLOBAL, _GLOBAL_PATH, _EXPLICIT
    with _LOCK:
        prev, _GLOBAL = _GLOBAL, log
        _GLOBAL_PATH = log.path if log is not None else None
        _EXPLICIT = log is not None
    return prev
