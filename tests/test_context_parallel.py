"""Context-parallel (SEP) tests: ring attention and Ulysses vs the
single-device full-attention golden, forward AND gradients (SURVEY §4
parity pattern; the reference has no ring attention — golden is local
math)."""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

# the repo's version-proof shard_map (replication check off, as every
# engine uses it): the einsum ring's scan carry legitimately mixes
# replicated and varying values, which the raw check_rep=True default
# rejects — correctness is asserted numerically against the dense golden
from paddle_tpu.utils import shard_map

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_parallel import (
    SegmentParallel, ring_attention, sep_reduce_gradients, split_sequence,
    ulysses_attention)


def full_attention(q, k, v, causal):
    """Golden: [B, S, H, D] dense softmax attention in fp32."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / math.sqrt(D)
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


def make_qkv(B=2, S=32, H=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D).astype(np.float32)) * 0.5
    return mk(), mk(), mk()


def sep_mesh(n=4):
    return dist.build_mesh({"sep": n}, devices=jax.devices()[:n])


def run_sharded(fn, mesh, q, k, v):
    spec = P(None, "sep", None, None)
    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return sharded(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_forward_parity(causal):
    mesh = sep_mesh(4)
    q, k, v = make_qkv()
    golden = full_attention(q, k, v, causal)
    out = run_sharded(
        functools.partial(ring_attention, axis="sep", causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grad_parity(causal):
    mesh = sep_mesh(4)
    q, k, v = make_qkv()

    def loss_golden(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal) ** 2)

    def loss_ring(q, k, v):
        spec = P(None, "sep", None, None)
        f = shard_map(
            functools.partial(ring_attention, axis="sep", causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return jnp.sum(f(q, k, v) ** 2)

    g_gold = jax.grad(loss_golden, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_gold):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_forward_parity(causal):
    mesh = sep_mesh(4)
    q, k, v = make_qkv(H=4)  # heads == axis size
    golden = full_attention(q, k, v, causal)
    out = run_sharded(
        functools.partial(ulysses_attention, axis="sep", causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_parity_and_gate():
    """Ulysses with GQA: kv heads all-to-all over their own (smaller)
    count when divisible by the axis; loud typed error when not."""
    mesh = sep_mesh(2)
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(1, 32, 8, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 32, 4, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 32, 4, 8).astype(np.float32))
    golden = full_attention_gqa(q, k, v, True)
    out = run_sharded(
        functools.partial(ulysses_attention, axis="sep", causal=True),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=2e-4, atol=2e-4)

    mesh8 = sep_mesh(8)
    q8 = jnp.asarray(rng.randn(1, 64, 8, 8).astype(np.float32))
    k8 = jnp.asarray(rng.randn(1, 64, 4, 8).astype(np.float32))
    spec = P(None, "sep")
    f = shard_map(functools.partial(ulysses_attention, axis="sep"),
                  mesh=mesh8, in_specs=(spec, spec, spec), out_specs=spec)
    with pytest.raises(ValueError, match="kv heads"):
        jax.jit(f)(q8, k8, k8)  # 4 kv heads over 8 ranks


def test_ulysses_grad_parity():
    mesh = sep_mesh(4)
    q, k, v = make_qkv(H=8)

    def loss_golden(q, k, v):
        return jnp.sum(full_attention(q, k, v, True) ** 2)

    def loss_u(q, k, v):
        spec = P(None, "sep", None, None)
        f = shard_map(
            functools.partial(ulysses_attention, axis="sep", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return jnp.sum(f(q, k, v) ** 2)

    g_gold = jax.grad(loss_golden, argnums=(0, 1, 2))(q, k, v)
    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_gold):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_ring_attention_jit_under_mesh():
    mesh = sep_mesh(8)
    q, k, v = make_qkv(S=64, H=8)
    spec = P(None, "sep", None, None)
    f = jax.jit(shard_map(
        functools.partial(ring_attention, axis="sep", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    out = f(q, k, v)
    golden = full_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=2e-5, atol=2e-5)


class TestSegmentParallel:
    def test_wrapper_attention_and_split(self):
        mesh = sep_mesh(4)
        from paddle_tpu import nn
        model = nn.Linear(8, 8)
        sp = SegmentParallel(model, mesh=mesh, mode="ring")
        q, k, v = make_qkv()
        xs = sp.split_inputs(q)
        assert xs.sharding.shard_shape(xs.shape)[1] == q.shape[1] // 4
        spec = P(None, "sep", None, None)
        f = shard_map(lambda a, b, c: sp.attention(a, b, c, causal=True),
                      mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        out = f(q, k, v)
        golden = full_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                                   rtol=2e-5, atol=2e-5)

    def test_sep_reduce_gradients(self):
        mesh = sep_mesh(4)
        grads = {"w": jnp.ones((8, 8))}

        def f(g):
            return sep_reduce_gradients(g, axes=("sep",))

        out = shard_map(f, mesh=mesh, in_specs=({"w": P()},),
                        out_specs={"w": P()})(grads)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


@pytest.mark.parametrize("impl", ["tiled", "einsum"])
def test_ring_attention_impls_agree(impl):
    """Both ring tiers produce the same output/grads as the golden."""
    mesh = sep_mesh(4)
    q, k, v = make_qkv(S=64)
    spec = P(None, "sep", None, None)
    f = shard_map(
        functools.partial(ring_attention, axis="sep", causal=True, impl=impl),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = f(q, k, v)
    golden = full_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=5e-5, atol=5e-5)
    g = jax.grad(lambda q: jnp.sum(f(q, k, v) ** 2))(q)
    gg = jax.grad(lambda q: jnp.sum(full_attention(q, k, v, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gg),
                               rtol=5e-4, atol=5e-4)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [True])
def test_ring_attention_long_context_4k(causal):
    """VERDICT r1 item 3: parity + grads at S_local >= 4k. The tiled path
    keeps per-step score memory O(block) on TPU; here (CPU mesh, composed
    tiles) it validates the ring/merge/vjp math at scale: S_global = 8192
    over 2 ranks -> S_local = 4096."""
    mesh = sep_mesh(2)
    B, S, H, D = 1, 8192, 2, 64
    rng = np.random.RandomState(7)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D).astype(np.float32)) * 0.2
    q, k, v = mk(), mk(), mk()
    spec = P(None, "sep", None, None)
    f = shard_map(
        functools.partial(ring_attention, axis="sep", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = f(q, k, v)
    golden = full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=1e-4, atol=1e-4)
    g = jax.grad(lambda v: jnp.sum(f(q, k, v) ** 2))(v)
    gg = jax.grad(lambda v: jnp.sum(full_attention(q, k, v, causal) ** 2))(v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gg),
                               rtol=1e-3, atol=1e-3)


def full_attention_gqa(q, k, v, causal):
    """Golden with fewer kv heads: repeat kv over query groups."""
    H, H_kv = q.shape[2], k.shape[2]
    k = jnp.repeat(k, H // H_kv, axis=2)
    v = jnp.repeat(v, H // H_kv, axis=2)
    return full_attention(q, k, v, causal)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gqa_grad_parity(causal):
    """GQA tiled ring (kv heads indexed per group, VERDICT r2 #5): forward
    and gradient parity vs the repeated-kv dense golden."""
    mesh = sep_mesh(4)
    rng = np.random.RandomState(7)
    B, S, H, H_kv, D = 2, 32, 4, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, S, H_kv, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, S, H_kv, D).astype(np.float32)) * 0.5

    spec = P(None, "sep")
    ring = shard_map(
        functools.partial(ring_attention, axis="sep", causal=causal,
                          impl="tiled"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(ring)(q, k, v)
    ref = full_attention_gqa(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def loss_ring(q, k, v):
        return jnp.sum(jax.jit(ring)(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention_gqa(q, k, v, causal) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_einsum_gqa_parity(causal):
    """round-4: the einsum tier handles GQA via grouped einsum (no KV
    repeat) — parity vs the dense GQA golden (was a hard raise)."""
    mesh = sep_mesh(4)
    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(2, 64, 4, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 64, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 64, 2, 8).astype(np.float32))
    spec = P(None, "sep")
    f = shard_map(
        functools.partial(ring_attention, axis="sep", causal=causal,
                          impl="einsum"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(f)(q, k, v)
    golden = full_attention_gqa(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=2e-4, atol=2e-4)

    # gradient parity too (the tiled tier's GQA test asserts the same)
    g_ring = jax.jit(jax.grad(
        lambda q_, k_, v_: jnp.sum(f(q_, k_, v_) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q_, k_, v_: jnp.sum(full_attention_gqa(q_, k_, v_,
                                                      causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-3, atol=3e-3, err_msg=nm)


def test_einsum_ring_odd_length_chunk_padding():
    """round-3: the einsum tier is chunked (O(S_local x 512) scores).
    S_local = 521 > 512 and odd: n_chunks=2, chunk=261, S_pad=522 — the
    jnp.pad branch and the idx < S pad-key masking BOTH execute (any
    S_local <= 512 is its own exact chunk and would skip them)."""
    mesh = sep_mesh(2)
    q, k, v = make_qkv(B=1, S=2 * 521, H=2, D=8)
    spec = P(None, "sep")
    f = shard_map(
        functools.partial(ring_attention, axis="sep", causal=True,
                          impl="einsum"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(f)(q, k, v)
    golden = full_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                               rtol=2e-4, atol=2e-4)
    g = jax.grad(lambda q: jnp.sum(jax.jit(f)(q, k, v) ** 2))(q)
    g_ref = jax.grad(lambda q: jnp.sum(full_attention(q, k, v, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=3e-4, atol=3e-4)
