"""Context (sequence) parallelism: ring attention and Ulysses all-to-all.

The reference snapshot has no ring attention — its long-context answers are
Megatron-SP (sequence_parallel_utils.py), the SEP axis (segment_parallel.py:26,
sequence split for the non-attention parts) and long-seq CUDA kernels
(flash_attn varlen / flashmask, SURVEY §5 "Long-context"). On TPU, true
context parallelism over the ICI ring is the idiomatic design (SURVEY §5:
"ring attention over ICI ... or Ulysses all-to-all"), so this module is the
SEP axis done TPU-first:

* ``ring_attention`` — q stays local, k/v blocks rotate around the mesh axis
  with lax.ppermute; per-step contributions merge through their logsumexp,
  so no device ever materializes full-sequence K/V or scores. The rotation
  is a lax.scan: XLA overlaps each step's ppermute (ICI) with the block
  matmuls (MXU). Two tiers (round 2):

  - impl="tiled" (default where shapes allow): each ring step runs the
    Pallas flash kernel on the visiting K/V block — scores stay tiled in
    VMEM, O(block) not O(S_local^2) HBM — and a hand-written custom_vjp
    runs the REVERSE ring for the backward: dk/dv accumulators travel
    with the rotating blocks and arrive home after n steps, dq
    accumulates locally; per (q-shard, kv-block) tile the flash backward
    kernels run with the *global* logsumexp/delta (standard ring-attention
    backward). lax.switch picks full/diagonal/skip per step from the
    block's global position, so causal rings skip past-diagonal blocks
    entirely.
  - impl="einsum": the round-1 XLA-composed online-softmax ring (kept for
    shapes the kernel can't take: S_local not a lane multiple on TPU).

* ``ulysses_attention`` — all-to-all swaps the sequence shard for a head
  shard ([B, S/n, H, D] -> [B, S, H/n, D]), runs ordinary full attention on
  the local heads (Pallas flash kernel on TPU), and swaps back. Cheaper than
  the ring when heads divide the axis (two all-to-alls vs n ppermutes) but
  caps the parallel degree at num_heads.

Both are per-shard functions: call them inside shard_map with the sequence
dim sharded over `axis`.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from ....enforce import InvalidArgumentError
from jax import lax

__all__ = ["ring_attention", "ulysses_attention"]

_NEG_INF = -1e30


def ring_attention(q, k, v, axis: str = "sep", causal: bool = False,
                   sm_scale: Optional[float] = None, remat: bool = True,
                   impl: str = "auto"):
    """Blockwise ring attention over mesh axis `axis`.

    q/k/v: this rank's sequence shard, [B, S_local, H, D] (paddle layout).
    Returns [B, S_local, H, D]. Global sequence order is the concatenation
    of shards by rank; causal masking uses global positions.

    impl: "tiled" (Pallas flash tiles + hand-written ring vjp), "einsum"
    (XLA-composed online softmax), or "auto" (tiled where the kernel takes
    the shape: D <= 256, and S_local % 128 == 0 on real TPU).
    """
    B, S, H, D = q.shape
    H_kv = k.shape[2]
    if H % max(H_kv, 1) != 0:
        raise InvalidArgumentError(
            f"ring attention GQA needs q heads divisible by kv heads "
            f"(got q {H}, kv {H_kv})")
    if impl == "auto":
        lanes_ok = S % 128 == 0 or jax.default_backend() == "cpu"
        impl = "tiled" if D <= 256 and lanes_ok else "einsum"
    if impl == "tiled":
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
        return _ring_tiled(q, k, v, axis, bool(causal), float(scale))
    g = H // H_kv  # grouped einsum handles GQA without repeating KV
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    q32 = (q * scale).astype(q.dtype)
    q_pos = rank * S + jnp.arange(S)  # [S] global positions of local queries

    # kv blocks rotate "backward" (rank r sends to r+1), so after t steps
    # this rank holds the block originating at rank - t (mod n): every rank
    # sees every block after n steps.
    perm = [(i, (i + 1) % n) for i in range(n)]

    # chunk the visiting block's key dim so the per-step scores tensor is
    # [B, H, Sq, chunk] instead of [B, H, Sq, S_local] — removes the
    # O(S_local^2) HBM wall this tier had (VERDICT r2 weak-3); S_local is
    # padded up to a chunk multiple and pad keys masked by position.
    n_chunks = -(-S // 512)
    chunk = -(-S // n_chunks)  # balanced: pad waste < n_chunks elements
    S_pad = n_chunks * chunk
    k_off = jnp.arange(chunk)

    def chunk_step(q32, k_blk, v_blk, src, m, l, acc, c):
        k_c = lax.dynamic_slice_in_dim(k_blk, c * chunk, chunk, axis=1)
        v_c = lax.dynamic_slice_in_dim(v_blk, c * chunk, chunk, axis=1)
        if g > 1:
            # GQA: grouped einsum — each kv head serves its g query heads
            # via index sharing, never a repeated KV copy (round 4; the
            # tier previously raised and forced the tiled path)
            qr = q32.reshape(B, S, H_kv, g, D)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qr, k_c,
                           preferred_element_type=jnp.float32)
            s = s.reshape(B, H, S, chunk)               # [B,H,Sq,chunk]
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q32, k_c,
                           preferred_element_type=jnp.float32)
        idx = c * chunk + k_off
        valid = idx < S                                 # pad keys are dead
        if causal:
            k_pos = src * S + idx
            valid = valid[None, :] & (k_pos[None, :] <= q_pos[:, None])
        else:
            valid = jnp.broadcast_to(valid[None, :], (S, chunk))
        s = jnp.where(valid[None, None], s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)                     # [B,H,Sq]
        m_new = jnp.maximum(m, m_cur)
        # fully-masked rows keep m = -inf; guard the shift to avoid inf-inf
        shift = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift[..., None])
        p = jnp.where(valid[None, None], p, 0.0)
        alpha = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - shift))
        l = l * alpha + jnp.sum(p, axis=-1)
        if g > 1:
            pr = p.reshape(B, H_kv, g, S, chunk).astype(v_c.dtype)
            pv = jnp.einsum("bhgqk,bkhd->bqhgd", pr, v_c,
                            preferred_element_type=jnp.float32)
            pv = pv.reshape(B, S, H, D)
        else:
            pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_c.dtype), v_c,
                            preferred_element_type=jnp.float32)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + pv
        return m_new, l, acc

    def body(carry, t):
        k_blk, v_blk, m, l, acc = carry
        src = (rank - t) % n
        # python loop (few, static chunks): an inner lax.scan would be a
        # closed_call, which shard_map can't evaluate eagerly under remat
        for c in range(S_pad // chunk):
            m, l, acc = chunk_step(q32, k_blk, v_blk, src, m, l, acc, c)
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, m, l, acc), None

    if remat:
        body = jax.checkpoint(body)

    if S_pad != S:
        # the rotating block carries its pad tail (chunk-multiple length);
        # pad keys are masked by position inside chunk_step
        k = jnp.pad(k, ((0, 0), (0, S_pad - S), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, S_pad - S), (0, 0), (0, 0)))
    m0 = _pvary(jnp.full((B, H, S), _NEG_INF, jnp.float32), axis)
    l0 = _pvary(jnp.zeros((B, H, S), jnp.float32), axis)
    acc0 = _pvary(jnp.zeros((B, S, H, D), jnp.float32), axis)
    (k_blk, v_blk, m, l, acc), _ = lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(n))
    inv = jnp.where(l == 0.0, 0.0, 1.0 / jnp.maximum(l, 1e-37))
    out = acc * inv.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# tiled ring: Pallas flash tiles per step + hand-written ring backward
# ---------------------------------------------------------------------------

def _ring_perm(axis):
    n = lax.axis_size(axis)
    return [(i, (i + 1) % n) for i in range(n)]


def _tile_modes(rank, t, n):
    """0 = full (block is globally before the local queries), 1 = diagonal
    (same-rank block: causal within), 2 = skip (block is entirely after)."""
    src = (rank - t) % n
    return jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))


def _expand_kv(x3, h, h_kv):
    """[B*H_kv, S, D] -> [B*H, S, D] by repeating each kv head over its
    query group (composed fallback only — the kernel indexes instead)."""
    b = x3.shape[0] // h_kv
    g = h // h_kv
    return jnp.repeat(x3.reshape(b, h_kv, *x3.shape[1:]), g,
                      axis=1).reshape(b * h, *x3.shape[1:])


def _reduce_kv_grad(g3, h, h_kv):
    """[B*H, S, D] per-query-head kv grads -> [B*H_kv, S, D] group sums."""
    b = g3.shape[0] // h
    g = h // h_kv
    return g3.reshape(b, h_kv, g, *g3.shape[1:]).sum(axis=2).reshape(
        b * h_kv, *g3.shape[1:])


def _tile_fwd(q3, k3, v3, causal, scale, h, h_kv, vma):
    """One (q-shard × kv-block) tile: (o f32, lse f32). Pallas flash kernel
    compiled (GQA native via kv index maps); a composed per-tile reference
    on CPU (pallas interpret mode can't run under shard_map's varying-axis
    checking)."""
    from ....kernels.pallas import flash_attention as _fa
    if not _fa._interpret():
        blk = _fa._pick_block(q3.shape[1])
        o, lse = _fa._fwd(q3, k3, v3, scale, causal, blk, blk, h=h,
                          h_kv=h_kv, save_lse=True, vma=vma)
        return o.astype(jnp.float32), lse
    if h_kv != h:
        k3 = _expand_kv(k3, h, h_kv)
        v3 = _expand_kv(v3, h, h_kv)
    s = jnp.einsum("bqd,bkd->bqk", q3, k3,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[1], s.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool), sk - sq)[None],
                      s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    lse = jnp.where(m <= _NEG_INF * 0.5, _NEG_INF,
                    m + jnp.log(jnp.maximum(l, 1e-37)))
    inv = jnp.where(l == 0.0, 0.0, 1.0 / jnp.maximum(l, 1e-37))
    o = jnp.einsum("bqk,bkd->bqd", p.astype(v3.dtype), v3,
                   preferred_element_type=jnp.float32) * inv[..., None]
    return o, lse


def _tile_bwd(q3, k3, v3, out3, lse, do3, causal, scale, h, h_kv, vma):
    """Per-tile (dq, dk, dv) with the GLOBAL lse (p = exp(s - lse_global)
    is the globally-normalized tile probability)."""
    from ....kernels.pallas import flash_attention as _fa
    if not _fa._interpret():
        blk = _fa._pick_block(q3.shape[1])
        dq, dk, dv, _ = _fa._bwd_impl(q3, k3, v3, out3, lse, do3, scale,
                                      causal, blk, blk, h=h, h_kv=h_kv,
                                      vma=vma)
        return dq, dk, dv
    kv_shape = k3.shape
    if h_kv != h:
        k3 = _expand_kv(k3, h, h_kv)
        v3 = _expand_kv(v3, h, h_kv)
    s = jnp.einsum("bqd,bkd->bqk", q3, k3,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[1], s.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool), sk - sq)[None],
                      s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])
    p = jnp.where((lse <= _NEG_INF * 0.5)[..., None], 0.0, p)
    do32 = do3.astype(jnp.float32)
    delta = jnp.sum(do32 * out3.astype(jnp.float32), axis=-1)  # [BH,S]
    dv = jnp.einsum("bqk,bqd->bkd", p, do32)
    dp = jnp.einsum("bqd,bkd->bqk", do32, v3.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, k3.astype(jnp.float32))
    dk = jnp.einsum("bqk,bqd->bkd", ds, q3.astype(jnp.float32))
    if h_kv != h:
        dk = _reduce_kv_grad(dk, h, h_kv)
        dv = _reduce_kv_grad(dv, h, h_kv)
        assert dk.shape == kv_shape, (dk.shape, kv_shape)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


def _ring_fwd_step(q3, k3, v3, mode, scale, h, h_kv, axis):
    """One visiting block, switched on the block's causal mode."""
    bh, s, d = q3.shape
    vma = (axis,)

    def full(args):
        o, lse = _tile_fwd(*args, False, scale, h, h_kv, vma)
        return o, lse

    def diag(args):
        o, lse = _tile_fwd(*args, True, scale, h, h_kv, vma)
        return o, lse

    def skip(args):
        # outputs must match the compute branches' varying-axis type
        return (_pvary(jnp.zeros((bh, s, d), jnp.float32), axis),
                _pvary(jnp.full((bh, s), _NEG_INF, jnp.float32), axis))

    if mode is None:  # non-causal ring: every block is a full tile
        return full((q3, k3, v3))
    return lax.switch(mode, [full, diag, skip], (q3, k3, v3))


def _ring_bwd_step(q3, k3, v3, out3, lse, do3, mode, scale, h, h_kv, axis):
    """One visiting block of the reverse ring."""
    vma = (axis,)

    def full(args):
        return _tile_bwd(*args, False, scale, h, h_kv, vma)

    def diag(args):
        return _tile_bwd(*args, True, scale, h, h_kv, vma)

    def skip(args):
        q3, k3, v3, _, _, _ = args
        return (jnp.zeros_like(q3), jnp.zeros_like(k3), jnp.zeros_like(v3))

    if mode is None:  # non-causal ring: every block is a full tile
        return full((q3, k3, v3, out3, lse, do3))
    return lax.switch(mode, [full, diag, skip],
                      (q3, k3, v3, out3, lse, do3))


def _merge_lse(acc, lse, o_b, lse_b):
    """Merge a block's normalized output into the running one through
    logsumexp weights. _NEG_INF (finite) keeps empty/empty merges NaN-free;
    rows that never see a key keep lse ~ _NEG_INF and zero output."""
    lse_c = jnp.logaddexp(lse, lse_b)
    w = jnp.exp(lse - lse_c)[..., None]
    w_b = jnp.exp(lse_b - lse_c)[..., None]
    return acc * w + o_b * w_b, lse_c


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_tiled(q, k, v, axis, causal, scale):
    out, _ = _ring_tiled_fwd(q, k, v, axis, causal, scale)
    return out


def _ring_tiled_fwd(q, k, v, axis, causal, scale):
    from ....kernels.pallas.flash_attention import _prep, _unprep
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, S, H, D = q.shape
    H_kv = k.shape[2]
    q3, k3, v3 = _prep(q), _prep(k), _prep(v)

    def body(carry, t):
        k_blk, v_blk, acc, lse = carry
        mode = _tile_modes(rank, t, n) if causal else None
        o_b, lse_b = _ring_fwd_step(q3, k_blk, v_blk, mode, scale,
                                    H, H_kv, axis)
        acc, lse = _merge_lse(acc, lse, o_b, lse_b)
        k_blk = lax.ppermute(k_blk, axis, _ring_perm(axis))
        v_blk = lax.ppermute(v_blk, axis, _ring_perm(axis))
        return (k_blk, v_blk, acc, lse), None

    acc0 = _pvary(jnp.zeros(q3.shape, jnp.float32), axis)
    lse0 = _pvary(jnp.full(q3.shape[:2], _NEG_INF, jnp.float32), axis)
    (_, _, acc, lse), _ = lax.scan(body, (k3, v3, acc0, lse0),
                                   jnp.arange(n))
    out3 = acc.astype(q.dtype)
    return _unprep(out3, B, H), (q3, k3, v3, out3, lse, B, H, H_kv)


def _ring_tiled_bwd(axis, causal, scale, res, g):
    from ....kernels.pallas.flash_attention import _prep, _unprep
    q3, k3, v3, out3, lse, B, H, H_kv = res
    do3 = _prep(g)
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)

    def body(carry, t):
        k_blk, v_blk, dk_blk, dv_blk, dq_acc = carry
        mode = _tile_modes(rank, t, n) if causal else None
        dq_c, dk_c, dv_c = _ring_bwd_step(q3, k_blk, v_blk, out3, lse, do3,
                                          mode, scale, H, H_kv, axis)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_blk = dk_blk + dk_c.astype(jnp.float32)
        dv_blk = dv_blk + dv_c.astype(jnp.float32)
        # dk/dv accumulators travel WITH their block; after n rotations the
        # block (and its completed gradient) is home again
        perm = _ring_perm(axis)
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        dk_blk = lax.ppermute(dk_blk, axis, perm)
        dv_blk = lax.ppermute(dv_blk, axis, perm)
        return (k_blk, v_blk, dk_blk, dv_blk, dq_acc), None

    z = _pvary(jnp.zeros(k3.shape, jnp.float32), axis)
    dq0 = _pvary(jnp.zeros(q3.shape, jnp.float32), axis)
    (_, _, dk3, dv3, dq3), _ = lax.scan(
        body, (k3, v3, z, z, dq0), jnp.arange(n))
    return (_unprep(dq3.astype(q3.dtype), B, H),
            _unprep(dk3.astype(k3.dtype), B, H_kv),
            _unprep(dv3.astype(v3.dtype), B, H_kv))


_ring_tiled.defvjp(_ring_tiled_fwd, _ring_tiled_bwd)


def _pvary(x, axis):
    """Mark a freshly-created constant as device-varying over `axis`
    (shard_map's varying-axis type system; no-op on older jax). pcast
    first: lax.pvary is deprecated where both exist."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis,))
    return x


def ulysses_attention(q, k, v, axis: str = "sep", causal: bool = False,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None):
    """DeepSpeed-Ulysses style sequence parallelism: trade the sequence
    shard for a head shard with one all-to-all each way.

    q/k/v: [B, S_local, H, D] with H divisible by the axis size.
    A custom `attn_fn` is called as attn_fn(q, k, v, causal) on the
    head-sharded full-sequence arrays (sm_scale is pre-folded into q).
    """
    n = lax.axis_size(axis)
    B, S, H, D = q.shape
    H_kv = k.shape[2]
    if H % n != 0 or H_kv % n != 0:
        raise InvalidArgumentError(
            f"ulysses needs q heads ({H}) AND kv heads ({H_kv}) divisible "
            f"by the axis size ({n}) — the all-to-all trades the sequence "
            "shard for a head shard on both; repeat kv heads upstream or "
            "use ring attention for H_kv < n")

    def to_heads(x):
        # split heads across ranks, gather the full sequence
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)  # [B, S*n, H/n, D]

    def to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)  # [B, S_local, H, D]

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if sm_scale is not None:
        # fold a custom scale into q (inner attention uses 1/sqrt(D))
        qh = qh * (sm_scale * math.sqrt(D))
    if attn_fn is None:
        from ....nn import functional as F
        out = F.scaled_dot_product_attention(qh, kh, vh, is_causal=causal)
    else:
        out = attn_fn(qh, kh, vh, causal)
    return to_seq(out)
