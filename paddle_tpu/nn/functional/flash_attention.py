"""Attention ops.

Reference surface: python/paddle/nn/functional/flash_attention.py
(flash_attention :242, scaled_dot_product_attention :976, flashmask_attention
:1098) backed by paddle/phi/kernels/gpu/flash_attn_kernel.cu (CUDA
FlashAttention-2).

TPU design: the reference implementation below is a pure XLA composition
(softmax(QK^T)V) — already MXU-bound and fused by XLA for moderate sequence
lengths. The memory-optimal tiled kernel lives in
paddle_tpu.kernels.pallas.flash_attention and is dispatched through the op
registry when running on TPU (O(S) VMEM instead of O(S^2) HBM for the scores
matrix). All layouts are [batch, seq, heads, head_dim] (paddle convention).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...ops import get_op, register_op

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sdp_kernel", "flashmask_attention"]


def _sdpa_reference(query, key, value, attn_mask=None, dropout_p=0.0,
                    is_causal=False, scale=None, training=True,
                    segment_ids=None):
    """XLA-composed attention. q: [B, S, H, D]; k/v may carry fewer (GQA)
    heads ([B, S, H_kv, D], H % H_kv == 0) — repeated on the fly.

    segment_ids: int32 [B, S] packed-sequence ids — position i attends to j
    only when segment_ids[b, i] == segment_ids[b, j] (the packed/varlen
    layout; on TPU this rides the Pallas kernel's in-kernel segment masking
    instead of an O(S^2) mask array).

    Contract shared with the Pallas fast path: attn_mask is a *constant*
    (no gradient flows into it — the reference's flash kernels likewise
    never produce a mask gradient), and fully-masked query rows produce
    zeros, not a uniform average."""
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids)
        seg_mask = (seg[:, :, None] == seg[:, None, :])[:, None]  # [B,1,S,S]
        attn_mask = (seg_mask if attn_mask is None
                     else seg_mask & attn_mask if attn_mask.dtype == jnp.bool_
                     else jnp.where(seg_mask, attn_mask, -1e30))
    if key.ndim == 4 and key.shape[2] != query.shape[2]:
        g = query.shape[2] // key.shape[2]
        key = jnp.repeat(key, g, axis=2)
        value = jnp.repeat(value, g, axis=2)
    q = jnp.swapaxes(query, 1, 2)  # [B, H, S, D]
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    # logits in fp32 for numerical stability (bf16 inputs stay on the MXU)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if is_causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((q_len, k_len), bool), k_len - q_len)
        logits = jnp.where(mask, logits, -1e30)
    if attn_mask is not None:
        attn_mask = jax.lax.stop_gradient(attn_mask)
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if is_causal or attn_mask is not None:
        # a fully-masked row softmaxes to a uniform average of V; emit
        # zeros instead (matches the flash kernel's l==0 guard)
        probs = jnp.where(
            jnp.max(logits, axis=-1, keepdims=True) <= -1e29, 0.0, probs)
    if dropout_p > 0.0 and training:
        from ...random import next_key
        keep = 1.0 - dropout_p
        m = jax.random.bernoulli(next_key(), keep, probs.shape)
        probs = jnp.where(m, probs / keep, 0.0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.swapaxes(out, 1, 2)  # [B, S, H, D]


@register_op("scaled_dot_product_attention", tags=["attention", "fusion"],
             dispatch=True)
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None, segment_ids=None):
    """attn_mask is a constant (bool keep-mask or additive float): no
    gradient flows into it on either the Pallas fast path or the composed
    fallback — matching the reference flash kernels, which never emit a
    mask gradient. Compose attention manually for a *learned* bias.

    segment_ids: optional int32 [B, S] for packed (varlen) batches — the
    zero-padding-free path the reference serves via flash_attn varlen
    (flash_attn_kernel.cu cu_seqlens)."""
    del name
    return _sdpa_reference(query, key, value, attn_mask, dropout_p, is_causal,
                           training=training, segment_ids=segment_ids)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity:
    returns (out, softmax) tuple."""
    del fixed_seed_offset, rng_name, name
    if return_softmax:
        raise NotImplementedError(
            "return_softmax is not supported by the fused attention path "
            "(the tiled kernel never materializes the softmax matrix); "
            "compose it manually with softmax(q @ k^T) if needed")
    out = get_op("scaled_dot_product_attention").dispatch(
        query, key, value, None, dropout, causal, training)
    return out, None


def _segments_from_cu(cu_seqlens, total):
    """Position → sequence id for cu_seqlens-packed layouts (shared by the
    composed fallback and the Pallas fast path so both mask identically)."""
    return jnp.searchsorted(cu_seqlens, jnp.arange(total),
                            side="right").astype(jnp.int32)


@register_op("flash_attn_unpadded", tags=["attention", "fusion"],
             dispatch=True)
def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    """Varlen attention over packed sequences. q: [total_q, H, D].

    Implemented by segment-masking within one attention call: position i may
    attend to j iff they fall in the same cu_seqlens segment (and j<=i for
    causal). This keeps static shapes for XLA. On TPU the registry routes
    this through the Pallas kernel's in-kernel segment-id masking (the
    analogue of the reference's cu_seqlens varlen kernel,
    flash_attn_kernel.cu:213) — see _flash_attn_unpadded_pallas."""
    tq = query.shape[0]
    tk = key.shape[0]
    seg_q = _segments_from_cu(cu_seqlens_q, tq)
    seg_k = _segments_from_cu(cu_seqlens_k, tk)
    mask = seg_q[:, None] == seg_k[None, :]
    if causal:
        pos_q = jnp.arange(tq) - jnp.take(cu_seqlens_q, seg_q - 1)
        pos_k = jnp.arange(tk) - jnp.take(cu_seqlens_k, seg_k - 1)
        mask = mask & (pos_k[None, :] <= pos_q[:, None])
    if return_softmax:
        raise NotImplementedError(
            "return_softmax is not supported by flash_attn_unpadded")
    out = _sdpa_reference(query[None], key[None], value[None],
                          attn_mask=mask[None, None], dropout_p=dropout,
                          is_causal=False, scale=scale, training=training)[0]
    return out, None


@register_op("flashmask_attention", tags=["attention", "fusion"],
             dispatch=True)
def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None):
    """Sparse-mask attention (reference: flash_attention.py:1098).

    startend_row_indices: [B, H_mask, S_k, C] per-key-column row indices:
      C=1 causal:  LT rows >= r1 masked;
      C=2 causal:  LT rows in [r1, r2) masked;
      C=2 full:    LT rows >= r1 masked, UT rows < r2 masked;
      C=4 full:    LT rows in [r1, r2) and UT rows in [r3, r4) masked
    where LT/UT are the strict lower/upper triangles (reference doc,
    flash_attention.py:1325-1332). Composed as an additive mask; on TPU the
    registry routes the causal C=1/2 forms straight into the Pallas kernel
    (no dense mask is ever built) — see _flashmask_pallas."""
    B, S = query.shape[0], query.shape[1]
    Sk = key.shape[1]
    mask = None
    if startend_row_indices is not None:
        idx = startend_row_indices
        rows = jnp.arange(S)[None, None, :, None]   # query positions
        cols = jnp.arange(Sk)[None, None, None, :]  # key positions
        lt = rows > cols   # strict lower triangle
        ut = rows < cols   # strict upper triangle

        def col(c):
            return idx[..., c][:, :, None, :]  # [B, H, 1, Sk]

        C = idx.shape[-1]
        # causal forms keep the kernel's per-column band contract
        # (start <= q < end, un-scoped — the causal tril already owns the
        # upper triangle); bidirectional forms scope each band to its
        # triangle per the reference doc (flash_attention.py:1325-1332)
        if C == 1:
            banned = (rows >= col(0)) if causal else lt & (rows >= col(0))
        elif C == 2 and causal:
            banned = (rows >= col(0)) & (rows < col(1))
        elif C == 2:
            banned = (lt & (rows >= col(0))) | (ut & (rows < col(1)))
        elif C == 4:
            banned = ((lt & (rows >= col(0)) & (rows < col(1)))
                      | (ut & (rows >= col(2)) & (rows < col(3))))
        else:
            from ...enforce import enforce_in
            enforce_in(C, (1, 2, 4),
                       f"startend_row_indices last dim must be 1, 2 or 4, "
                       f"got {C}", op="flashmask_attention")
        mask = ~banned
    if causal:
        cm = jnp.tril(jnp.ones((S, Sk), bool), Sk - S)[None, None]
        mask = cm if mask is None else (mask & cm)
    if window_size is not None:
        w = window_size if isinstance(window_size, int) else window_size[0]
        rows = jnp.arange(S)[:, None]
        cols = jnp.arange(Sk)[None, :]
        # bottom-right aligned like the causal tril above (and the Pallas
        # fast path): key col k visible iff k >= q + (Sk - S) - w
        wm = (cols >= rows + (Sk - S) - w)[None, None]
        mask = wm if mask is None else (mask & wm)
    out = get_op("scaled_dot_product_attention").dispatch(
        query, key, value, mask, dropout, False, True)
    return out, None


class sdp_kernel:
    """Context manager selecting attention backends (API parity shim)."""

    def __init__(self, enable_math=True, enable_flash=True, enable_mem_efficient=True):
        self.enable_flash = enable_flash

    def __enter__(self):
        from ...flags import flag, set_flags
        self._saved = flag("enable_pallas_kernels")
        set_flags({"enable_pallas_kernels": self.enable_flash})
        return self

    def __exit__(self, *a):
        from ...flags import set_flags
        set_flags({"enable_pallas_kernels": self._saved})
        return False
