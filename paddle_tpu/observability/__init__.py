"""Unified observability: in-program telemetry, step/MFU accounting and
exportable traces across training and serving.

The subsystem the perf work steers by (ISSUE 4): the bench's single
tokens/s + MFU pair says nothing about WHERE step time goes; this package
makes the split measurable without leaving the compiled program:

* :mod:`.metrics` — ``observe(name, scalar)`` inside jitted code; a
  fixed-shape ring buffer rides the train-step carry (like
  ``opt_state["fp8_meta"]``) and the host fetches it once per
  ``FLAGS_telemetry_interval`` steps. Built-in series: loss, grad
  global-norm, nonfinite counts, dp-collective wire bytes (from the
  comm_overlap bucket plans), FP8 amax/scale drift. Strict no-op
  (bitwise-identical program) when ``FLAGS_telemetry`` is off.
* :mod:`.step_timer` / :mod:`.flops` — compile vs steady-state split,
  per-phase breakdown, analytic GPT/Llama FLOPs (fwd/bwd/remat-aware)
  for MFU, comms fraction measured or estimated from bucket plans.
* :mod:`.events` — flushed-per-line, size-capped JSONL event log with
  host/role-tagged records (crash forensics; the resilient runner logs
  resumes/skips/commits/SIGTERM through it) + ``merge_event_streams``
  for one role-tagged timeline over trainer + serving logs.
* :mod:`.trace` — chrome-trace spans unified with ``paddle_tpu.profiler``.
* :mod:`.prom` — Prometheus text-format scrape surface (counters,
  gauges, summaries, bucketed histograms, recent-window p50/p95
  quantiles) for the serving engine and the fleet view.
* :mod:`.profile_reader` — the MEASUREMENT half (ISSUE 11): capture a
  windowed profile of a compiled step (while-trip-aware compiled-HLO op
  census + micro-benchmarked rates), attribute per-op time into compute
  vs hidden/exposed collective time by kind, and derive a measured
  ``HardwareProfile`` JSON the auto-parallel planner consumes directly.
* :mod:`.aggregate` — fleet telemetry: per-process step-time windows +
  prom snapshots gathered through the distributed store into rank-0
  gauges, with straggler detection (``straggler_detected`` events).
* :mod:`.flight_recorder` — hang flight recorder: watchdog timeouts and
  resilience SIGTERM/abort paths dump a bounded crash bundle (telemetry
  ring tail, recent events, open spans, heartbeat ages, active profile
  window).

Entry points: ``models.hybrid_engine.build_train_step(telemetry=)``,
``Model.fit``, ``distributed.resilience.run_resilient``,
``inference.ServingEngine`` and ``bench.py``. See README "Observability".
"""

from .aggregate import TelemetryAggregator, detect_stragglers
from .events import (EventLog, emit_event, get_event_log,
                     merge_event_streams, set_event_log)
from .flight_recorder import (FlightRecorder, get_flight_recorder,
                              set_flight_recorder)
from .flops import (collective_seconds, gpt_flops_per_token,
                    gpt_moe_flops_per_token, llama_flops_per_token, mfu,
                    param_count, peak_flops, plan_wire_bytes,
                    transformer_flops_per_token)
from .metrics import (BUILTIN_SERIES, TelemetryConfig, TelemetryHost,
                      buffer_specs, collecting, ep_a2a_wire_bytes,
                      init_buffer, mp_comm_scope, mp_wire_bytes,
                      note_ep_comm, note_mp_comm, note_zero3_comm, observe,
                      telemetry_from_flags, update_buffer,
                      zero3_ag_wire_bytes)
from . import numerics
from .numerics import (DetectorConfig, NumericsConfig, NumericsGuard,
                       NumericsMonitor, numerics_from_flags,
                       resolve_numerics)
from .profile_reader import (MeasuredRates, ProfileWindow,
                             capture_step_profile, derive_hardware_profile,
                             hlo_census, load_profile_json,
                             measure_collective_rates, measure_compute_rate,
                             save_profile_json)
from .prom import MetricsServer, PromRegistry, serve_registry
from .step_timer import StepTimer
from .trace import capture_spans, span, write_chrome_trace

__all__ = [
    "TelemetryConfig", "TelemetryHost", "telemetry_from_flags", "observe",
    "collecting", "BUILTIN_SERIES", "init_buffer", "buffer_specs",
    "update_buffer", "mp_wire_bytes", "note_mp_comm", "mp_comm_scope",
    "ep_a2a_wire_bytes", "note_ep_comm",
    "zero3_ag_wire_bytes", "note_zero3_comm",
    "StepTimer",
    "gpt_flops_per_token", "gpt_moe_flops_per_token",
    "llama_flops_per_token",
    "transformer_flops_per_token", "param_count", "mfu", "peak_flops",
    "collective_seconds", "plan_wire_bytes",
    "EventLog", "emit_event", "get_event_log", "set_event_log",
    "merge_event_streams",
    "PromRegistry", "MetricsServer", "serve_registry",
    "span", "capture_spans", "write_chrome_trace",
    "hlo_census", "capture_step_profile", "derive_hardware_profile",
    "save_profile_json", "load_profile_json", "measure_compute_rate",
    "measure_collective_rates", "MeasuredRates", "ProfileWindow",
    "TelemetryAggregator", "detect_stragglers",
    "FlightRecorder", "get_flight_recorder", "set_flight_recorder",
    "numerics", "NumericsConfig", "NumericsMonitor", "NumericsGuard",
    "DetectorConfig", "numerics_from_flags", "resolve_numerics",
]
