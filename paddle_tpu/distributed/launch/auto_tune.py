"""Launcher-wired auto-tuner (reference: launch/main.py auto-tuner mode —
``--auto_tuner_json`` drives subprocess trials of the user's own training
script, reading one metric back per trial, then launches the real job
with the winner).

The candidate vocabulary is the auto_tuner planner's
:class:`PlanCandidate` — the REAL hybrid-engine surface (dp/mp/pp/ep,
schedule, vpp, micro_batches, zero_stage, comm_bucket_mb, mp_overlap, ...).
With ``FLAGS_auto_parallel_plan`` (default on) and a model named in the
tuner json, the analytic planner generates, HBM-prunes and RANKS the
candidates first, so only the top ``FLAGS_auto_parallel_topk`` pay for a
real subprocess trial; without model information the trial loop sweeps
the constraint-valid factorizations unranked.

Trial protocol (what the training script sees):
  PADDLE_AUTO_TUNER_CANDIDATE = JSON dict of the PlanCandidate fields
  PADDLE_AUTO_TUNER_TRIAL     = "1" (run a few steps, then exit 0)
  PADDLE_AUTO_TUNER_METRIC_FILE = path — write ONE float (higher=better)

Script-side helpers: ``candidate_from_env()`` parses the candidate into a
PlanCandidate (``cand.build_mesh()`` / ``cand.engine_kwargs()`` feed it
straight into ``build_hybrid_train_step``); ``report_metric(value)``
writes the metric file.
"""

from __future__ import annotations
from ...enforce import InvalidArgumentError

import dataclasses
import json
import os
import tempfile
from typing import List, Optional

from ..auto_tuner import (AutoTuner, ModelSpec, PlanCandidate,
                          generate_plan_candidates, model_config_by_name,
                          plan as plan_candidates)

__all__ = ["run_auto_tune", "candidate_from_env", "report_metric"]


def candidate_from_env() -> Optional[PlanCandidate]:
    raw = os.environ.get("PADDLE_AUTO_TUNER_CANDIDATE")
    if not raw:
        return None
    d = json.loads(raw)
    return PlanCandidate(**d)


def is_trial() -> bool:
    return os.environ.get("PADDLE_AUTO_TUNER_TRIAL") == "1"


def report_metric(value: float) -> None:
    path = os.environ.get("PADDLE_AUTO_TUNER_METRIC_FILE")
    if path:
        with open(path, "w") as f:
            f.write(repr(float(value)))


def _candidate_env(cand: PlanCandidate) -> str:
    return json.dumps(dataclasses.asdict(cand), sort_keys=True)


def _launcher_profile(cfg_json: dict):
    """Hardware profile for the launcher's analytic ranking WITHOUT
    touching jax.devices(): the launcher must never initialize a backend
    — on a TPU host that would lock libtpu before the trial subprocesses
    spawn and every trial would fail to acquire the chip. Resolution:
    explicit json "profile" name > env sniff (JAX_PLATFORMS=cpu) >
    generic TPU default. (The planner math is trace/shape-only and never
    initializes a backend either.)"""
    from ..auto_tuner import KNOWN_PROFILES
    name = cfg_json.get("profile")
    if name is None:
        plat = os.environ.get("JAX_PLATFORMS", "")
        name = "cpu" if plat.startswith("cpu") else "tpu-v5e"
    if name not in KNOWN_PROFILES:
        # a measured-profile JSON path (observability.profile_reader
        # capture artifact) — still backend-free: just a file read
        from ..auto_tuner.planner import resolve_profile
        return resolve_profile(name)
    return KNOWN_PROFILES[name]


def _candidates_for(cfg_json: dict, world: int) -> List[PlanCandidate]:
    """Candidate list for the trial loop: planner-ranked top-k when the
    json names a model (or carries shape fields to build one) and
    FLAGS_auto_parallel_plan is on; constraint-valid factorizations
    unranked when the flag is off; and with NO model information at all,
    the raw mesh factorization x micro-batch sweep with no pruning —
    a fabricated proxy model would silently drop configs (e.g. mp=8 on
    an 8-head model) that are valid for the user's real one."""
    from ...flags import flag

    model = cfg_json.get("model")
    dim_keys = ("num_layers", "num_heads", "hidden_size", "vocab_size")
    micro_opts = tuple(cfg_json.get("micro_batch_options", (1, 2, 4, 8)))
    if model is None and not any(k in cfg_json for k in dim_keys):
        out = []
        for dp in (d for d in range(1, world + 1) if world % d == 0):
            for mp in (m for m in range(1, world // dp + 1)
                       if (world // dp) % m == 0):
                pp = world // (dp * mp)
                for mb in micro_opts:
                    out.append(PlanCandidate(dp=dp, mp=mp, pp=pp,
                                             micro_batches=mb))
        return out
    if model is not None:
        cfg, family = model_config_by_name(model)
    else:
        from ...models.gpt import GPTConfig
        import jax.numpy as jnp
        cfg = GPTConfig(
            vocab_size=cfg_json.get("vocab_size", 1024),
            hidden_size=cfg_json.get("hidden_size", 64),
            num_layers=cfg_json.get("num_layers", 4),
            num_heads=cfg_json.get("num_heads", 4),
            max_seq_len=max(cfg_json.get("seq_len", 128), 128),
            dtype=jnp.float32, param_dtype=jnp.float32)
        family = "gpt"
    gb = int(cfg_json.get("global_batch", max(8, world)))
    seq = int(cfg_json.get("seq_len", cfg.max_seq_len))
    gen_kw = {"micro_batch_options": micro_opts}
    if bool(cfg_json.get("analytic_rank", flag("auto_parallel_plan"))):
        report = plan_candidates(
            cfg, world=world, global_batch=gb, seq=seq, family=family,
            profile=_launcher_profile(cfg_json),
            hbm_gb=(cfg_json.get("hbm_gb")
                    or float(flag("auto_parallel_hbm_gb")) or None),
            **gen_kw)
        top_k = int(cfg_json.get("top_k", flag("auto_parallel_topk")))
        return [s.candidate for s in report.top(top_k)]
    spec = ModelSpec.from_config(cfg, family)
    cands, _ = generate_plan_candidates(spec, world, global_batch=gb,
                                        seq=seq, **gen_kw)
    return cands


def run_auto_tune(ctx) -> Optional[str]:
    """Run the candidate search with the user's own training script as the
    trial body. Returns the winning candidate env string (or None)."""
    from .controllers import CollectiveController

    if ctx.args.nnodes != 1:
        # per-node sweeps would race to different winners and hand ranks
        # inconsistent meshes; a store-synchronized multi-node sweep is
        # future work (the reference's auto-tuner is likewise driven from
        # one launcher)
        raise InvalidArgumentError(
            "--auto_tune currently supports single-node jobs only "
            "(nnodes=1); run the sweep on one node and pass the winning "
            "candidate to the multi-node job via "
            "PADDLE_AUTO_TUNER_CANDIDATE")

    cfg = {}
    if ctx.args.auto_tuner_json:
        with open(ctx.args.auto_tuner_json) as f:
            cfg = json.load(f)
    world = ctx.args.nnodes * ctx.nproc
    cands = _candidates_for(cfg, world)

    def run_trial(cand: PlanCandidate) -> Optional[float]:
        fd, metric_file = tempfile.mkstemp(prefix="autotune_")
        os.close(fd)
        try:
            trial_ctx = _clone(ctx)
            trial_ctx.envs.update({
                "PADDLE_AUTO_TUNER_CANDIDATE": _candidate_env(cand),
                "PADDLE_AUTO_TUNER_TRIAL": "1",
                "PADDLE_AUTO_TUNER_METRIC_FILE": metric_file,
            })
            trial_ctx.args.job_id = (f"{ctx.args.job_id}-tune-"
                                     f"{str(cand).replace(' ', '_')}")
            rc = CollectiveController(trial_ctx).run()
            if rc != 0:
                return None
            with open(metric_file) as f:
                raw = f.read().strip()
            return float(raw) if raw else None
        finally:
            os.unlink(metric_file)

    tuner = AutoTuner(run_trial,
                      max_trials=cfg.get("max_trials"),
                      max_time_s=cfg.get("max_time_s"))
    best = tuner.tune(cands)
    print(tuner.summary())
    if best is None:
        return None
    print(f"auto-tuner winner: {best}")
    return _candidate_env(best)


def _clone(ctx):
    """Fresh Context for a trial: same argv surface, isolated env/args so
    trial job_ids and env markers don't leak into the real run."""
    import argparse

    new = object.__new__(type(ctx))
    new.args = argparse.Namespace(**vars(ctx.args))
    new.node = ctx.node
    new.nproc = ctx.nproc
    new.envs = dict(ctx.envs)
    return new
