"""Tensor-op golden tests vs numpy (reference pattern:
test/legacy_test/test_*_op.py OpTest forward-vs-numpy)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_creation():
    assert paddle.zeros([2, 3]).shape == (2, 3)
    assert paddle.ones([2], dtype="int32").dtype == np.int32
    assert np.allclose(np.asarray(paddle.arange(5)), np.arange(5))
    assert paddle.full([2, 2], 7.0)[0, 0] == 7.0
    assert paddle.eye(3).shape == (3, 3)


def test_elementwise_math():
    x = np.random.randn(4, 5).astype(np.float32)
    y = np.random.randn(4, 5).astype(np.float32)
    assert np.allclose(np.asarray(paddle.add(x, y)), x + y, atol=1e-6)
    assert np.allclose(np.asarray(paddle.exp(x)), np.exp(x), rtol=1e-5)
    assert np.allclose(np.asarray(paddle.clip(x, -0.5, 0.5)), np.clip(x, -0.5, 0.5))
    assert np.allclose(np.asarray(paddle.rsqrt(np.abs(x) + 1)),
                       1 / np.sqrt(np.abs(x) + 1), rtol=1e-5)


def test_matmul_transpose_flags():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(5, 4).astype(np.float32)
    out = paddle.matmul(a, b, transpose_y=True)
    assert np.allclose(np.asarray(out), a @ b.T, atol=1e-5)


def test_split_paddle_semantics():
    x = np.arange(24).reshape(2, 12)
    parts = paddle.split(paddle.to_tensor(x), [3, 4, -1], axis=1)
    assert [p.shape[1] for p in parts] == [3, 4, 5]
    parts2 = paddle.split(paddle.to_tensor(x), 3, axis=1)
    assert all(p.shape[1] == 4 for p in parts2)


def test_reductions():
    x = np.random.randn(3, 4, 5).astype(np.float32)
    assert np.allclose(np.asarray(paddle.sum(x, axis=1)), x.sum(1), atol=1e-5)
    assert np.allclose(np.asarray(paddle.mean(x, axis=[0, 2])), x.mean((0, 2)), atol=1e-6)
    assert np.allclose(np.asarray(paddle.logsumexp(x, axis=-1)),
                       np.log(np.exp(x).sum(-1)), rtol=1e-5)
    assert np.allclose(np.asarray(paddle.std(x)), x.std(ddof=1), rtol=1e-4)


def test_indexing_ops():
    x = np.random.randn(4, 6).astype(np.float32)
    idx = np.array([2, 0, 1])
    assert np.allclose(np.asarray(paddle.gather(x, idx, axis=0)), x[idx])
    assert np.allclose(np.asarray(paddle.index_select(x, idx, axis=1)), x[:, idx])
    v, i = paddle.topk(paddle.to_tensor(x), 3, axis=1)
    ref = np.sort(x, axis=1)[:, ::-1][:, :3]
    assert np.allclose(np.asarray(v), ref, atol=1e-6)


def test_scatter_put_along_axis():
    x = np.zeros((3, 4), np.float32)
    idx = np.array([[0], [1], [2]])
    out = paddle.put_along_axis(paddle.to_tensor(x), idx, 1.0, axis=1, reduce="add")
    assert np.asarray(out).sum() == 3.0


def test_shape_manipulation():
    x = np.arange(24).reshape(2, 3, 4)
    assert paddle.flatten(paddle.to_tensor(x), 1, 2).shape == (2, 12)
    assert paddle.unsqueeze(paddle.to_tensor(x), [0, 2]).shape == (1, 2, 1, 3, 4)
    assert paddle.squeeze(paddle.unsqueeze(paddle.to_tensor(x), 0), 0).shape == (2, 3, 4)
    assert paddle.tile(paddle.to_tensor(x), [1, 2, 1]).shape == (2, 6, 4)
    assert paddle.roll(paddle.to_tensor(x), 1, axis=0).shape == (2, 3, 4)


def test_linalg():
    a = np.random.randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    L = np.asarray(paddle.cholesky(spd))
    assert np.allclose(L @ L.T, spd, atol=1e-4)
    assert np.allclose(np.asarray(paddle.inverse(spd)) @ spd, np.eye(4), atol=1e-4)
    assert abs(float(paddle.det(spd)) - np.linalg.det(spd)) / abs(np.linalg.det(spd)) < 1e-4


def test_logic():
    x = np.array([1.0, np.nan, np.inf])
    assert np.asarray(paddle.isnan(x)).tolist() == [False, True, False]
    assert np.asarray(paddle.isinf(x)).tolist() == [False, False, True]
    assert bool(paddle.allclose(np.ones(3), np.ones(3)))


def test_einsum_cumsum():
    a = np.random.randn(2, 3).astype(np.float32)
    b = np.random.randn(3, 4).astype(np.float32)
    assert np.allclose(np.asarray(paddle.einsum("ij,jk->ik", a, b)), a @ b, atol=1e-5)
    assert np.allclose(np.asarray(paddle.cumsum(a, axis=1)), np.cumsum(a, 1), atol=1e-6)


def test_cast_dtype():
    x = paddle.to_tensor([1.5, 2.5])
    assert paddle.cast(x, "int32").dtype == np.int32
    assert paddle.cast(x, "bfloat16").dtype == paddle.bfloat16
