"""BERT family + fused_attention/fused_feedforward tests (reference
analogs: test_fused_attention_op.py, test_fused_feedforward_op.py, and
the BERT pretraining baseline config)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.incubate.nn.functional import (fused_attention,
                                               fused_feedforward)
from paddle_tpu.models import bert as B


CFG = B.BertConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                   intermediate_size=64, max_position_embeddings=32,
                   hidden_dropout=0.0)


def test_bert_forward_shapes():
    model = B.BertModel(CFG)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    seq, pooled = model(ids)
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)


def test_bert_padding_mask_changes_output():
    model = B.BertModel(CFG)
    model.eval()
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (1, 8)))
    full, _ = model(ids, attention_mask=jnp.ones((1, 8)))
    half_mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]])
    masked, _ = model(ids, attention_mask=half_mask)
    # visible positions change when later tokens are masked out
    assert not np.allclose(np.asarray(full[:, 0]), np.asarray(masked[:, 0]))


def test_bert_pretraining_loss_decreases():
    model = B.BertForPretraining(CFG)
    model.train()
    from paddle_tpu.nn import functional_call, functional_train_graph
    params, _, buffers = functional_train_graph(model)
    opt = paddle.optimizer.AdamW(1e-3)
    state = jax.jit(opt.init_state)(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 128, (4, 16)))
    mlm_labels = jnp.asarray(np.where(rng.rand(4, 16) < 0.15,
                                      np.asarray(ids), -100))
    nsp_labels = jnp.asarray(rng.randint(0, 2, (4,)))

    @jax.jit
    def step(p, s):
        def loss_fn(p):
            (mlm, nsp), _ = functional_call(model, p, buffers, ids)
            return B.bert_pretrain_loss(mlm, nsp, mlm_labels, nsp_labels)
        l, g = jax.value_and_grad(loss_fn)(p)
        p, s = opt.apply(p, g, s, 1e-3)
        return p, s, l

    losses = []
    for _ in range(10):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_fused_attention_matches_composition():
    rng = np.random.RandomState(2)
    B_, S, H, heads = 2, 8, 16, 4
    hd = H // heads
    x = jnp.asarray(rng.randn(B_, S, H).astype(np.float32))
    qkv_w = jnp.asarray(rng.randn(3, heads, hd, H).astype(np.float32) * 0.2)
    qkv_b = jnp.asarray(rng.randn(3, heads, hd).astype(np.float32) * 0.1)
    lin_w = jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.2)
    lin_b = jnp.asarray(rng.randn(H).astype(np.float32) * 0.1)
    ln_s = jnp.ones(H); ln_b = jnp.zeros(H)

    out = fused_attention(x, qkv_w, lin_w, pre_layer_norm=False,
                          ln_scale=ln_s, ln_bias=ln_b, qkv_bias=qkv_b,
                          linear_bias=lin_b, training=False)

    # reference composition
    w2 = qkv_w.reshape(3 * H, H).T
    qkv = (x @ w2 + qkv_b.reshape(-1)).reshape(B_, S, 3, heads, hd)
    attn = F.scaled_dot_product_attention(qkv[:, :, 0], qkv[:, :, 1],
                                          qkv[:, :, 2], training=False)
    ref = x + attn.reshape(B_, S, H) @ lin_w + lin_b
    ref = F.layer_norm(ref, (H,), ln_s, ln_b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fused_attention_pre_ln():
    rng = np.random.RandomState(3)
    B_, S, H, heads = 1, 4, 8, 2
    x = jnp.asarray(rng.randn(B_, S, H).astype(np.float32))
    qkv_w = jnp.asarray(rng.randn(3, heads, H // heads, H)
                        .astype(np.float32) * 0.2)
    lin_w = jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.2)
    out = fused_attention(x, qkv_w, lin_w, pre_layer_norm=True,
                          pre_ln_scale=jnp.ones(H),
                          pre_ln_bias=jnp.zeros(H), training=False)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_fused_feedforward_matches_composition():
    rng = np.random.RandomState(4)
    B_, S, H, FF = 2, 6, 12, 24
    x = jnp.asarray(rng.randn(B_, S, H).astype(np.float32))
    w1 = jnp.asarray(rng.randn(H, FF).astype(np.float32) * 0.3)
    b1 = jnp.asarray(rng.randn(FF).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(FF, H).astype(np.float32) * 0.3)
    b2 = jnp.asarray(rng.randn(H).astype(np.float32) * 0.1)
    ln_s, ln_b = jnp.ones(H), jnp.zeros(H)
    out = fused_feedforward(x, w1, w2, b1, b2, ln2_scale=ln_s, ln2_bias=ln_b,
                            dropout1_rate=0.0, dropout2_rate=0.0,
                            activation="gelu", training=False)
    ref = x + (F.gelu(x @ w1 + b1) @ w2 + b2)
    ref = F.layer_norm(ref, (H,), ln_s, ln_b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)