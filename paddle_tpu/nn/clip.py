"""Gradient clipping (reference: python/paddle/nn/clip.py).

Clip objects are callables over a gradient pytree — pure and jit-safe, so
they compose with optimizer.apply under pjit. The distributed variant
(global norm across model-parallel shards) lives in
distributed/fleet/meta_parallel/hybrid_parallel_optimizer.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "global_norm"]


def _is_selected_rows(x) -> bool:
    from ..framework.selected_rows import SelectedRows
    return isinstance(x, SelectedRows)


def sum_squares(grads) -> jax.Array:
    """fp32 sum of squares over a grad tree — global_norm²'s accumulation
    term, exposed so split-backward tiers (param_stream's two-pass clip)
    can accumulate it segment by segment with identical numerics."""
    leaves = [g for g in jax.tree.leaves(grads, is_leaf=_is_selected_rows)
              if g is not None]
    vals = [g.value if _is_selected_rows(g) else g for g in leaves]
    if not vals:
        return jnp.zeros(())
    return sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in vals)


def global_norm(grads) -> jax.Array:
    return jnp.sqrt(sum_squares(grads))


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree.map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm:
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            n = jnp.linalg.norm(g.astype(jnp.float32))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return jax.tree.map(clip_one, grads)


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def scale_from_norm(self, norm):
        """Clip coefficient for a precomputed global norm — the ONE
        definition of the formula (param_stream's two-pass streamed clip
        must match this bit-for-bit for dense parity)."""
        return jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))

    def __call__(self, grads):
        scale = self.scale_from_norm(global_norm(grads))

        def scale_one(g):
            if _is_selected_rows(g):
                # scale VALUES only — mapping over the node would also
                # scale the integer row indices
                from ..framework.selected_rows import SelectedRows
                return SelectedRows(g.rows,
                                    (g.value * scale).astype(g.value.dtype),
                                    g.height)
            return (g * scale).astype(g.dtype)

        return jax.tree.map(scale_one, grads, is_leaf=_is_selected_rows)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """Eager helper over Parameter.grad slots (torch-compat surface)."""
    from .layer.layers import Parameter
    params = [p for p in parameters if isinstance(p, Parameter) and p.grad is not None]
    if not params:
        return jnp.zeros(())
    total = global_norm([p.grad for p in params])
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(total, 1e-12))
    for p in params:
        p.grad = p.grad * scale
    return total
