"""Launcher tests (reference pattern: subprocess-spawn with env rendezvous,
test_dist_base.py:954; elastic restart fleet/elastic)."""

import json
import os
import sys
import textwrap

import pytest

from paddle_tpu import _native
from paddle_tpu.distributed.launch import (CollectiveController, Context,
                                           launch)
from paddle_tpu.distributed.launch.elastic import ElasticManager
from paddle_tpu.distributed.store import TCPStore

NATIVE = _native.load() is not None
pytestmark = pytest.mark.skipif(not NATIVE, reason="native build unavailable")


def test_launch_two_workers_env(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        out = sys.argv[1]
        info = {k: os.environ.get(k) for k in
                ["PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
                 "PADDLE_TRAINER_ENDPOINTS", "PADDLE_LOCAL_RANK",
                 "JAX_PROCESS_ID", "JAX_NUM_PROCESSES"]}
        with open(os.path.join(out, "rank_%s.json" %
                               os.environ["PADDLE_TRAINER_ID"]), "w") as f:
            json.dump(info, f)
    """))
    rc = launch(["--nproc_per_node", "2", "--log_dir",
                 str(tmp_path / "log"), str(script), str(tmp_path)])
    assert rc == 0
    for r in range(2):
        info = json.load(open(tmp_path / f"rank_{r}.json"))
        assert info["PADDLE_TRAINER_ID"] == str(r)
        assert info["PADDLE_TRAINERS_NUM"] == "2"
        assert info["JAX_PROCESS_ID"] == str(r)
        assert len(info["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 2


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(7)\n")
    rc = launch(["--nproc_per_node", "1", "--log_dir",
                 str(tmp_path / "log"), str(script)])
    assert rc == 7


def test_elastic_restart_recovers(tmp_path):
    """Worker fails on first attempt (marker file absent), succeeds on the
    restart — elastic_level 1 must retry and exit 0."""
    script = tmp_path / "flaky.py"
    marker = tmp_path / "attempted"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        marker = {str(marker)!r}
        if not os.path.exists(marker):
            open(marker, "w").write("1")
            sys.exit(101)
        sys.exit(0)
    """))
    rc = launch(["--nproc_per_node", "1", "--elastic_level", "1",
                 "--max_restarts", "2", "--log_dir", str(tmp_path / "log"),
                 str(script)])
    assert rc == 0
    assert marker.exists()


def test_elastic_level0_no_restart(tmp_path):
    script = tmp_path / "flaky.py"
    marker = tmp_path / "attempted"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        marker = {str(marker)!r}
        if not os.path.exists(marker):
            open(marker, "w").write("1")
            sys.exit(101)
        sys.exit(0)
    """))
    rc = launch(["--nproc_per_node", "1", "--log_dir",
                 str(tmp_path / "log"), str(script)])
    assert rc == 101


def test_hung_worker_detected_via_heartbeat(tmp_path):
    """A worker that registers a heartbeat then deadlocks must be detected
    and the job failed (level 0 → exit ELASTIC_EXIT_CODE=101)."""
    script = tmp_path / "hang.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, %r)
        from paddle_tpu.distributed.launch.elastic import worker_heartbeat
        em = worker_heartbeat(interval=0.1)
        em.interval = 0.1
        time.sleep(0.5)   # heartbeat alive...
        em.stop()         # ...then the 'hang': beats stop, process lives
        time.sleep(60)
    """ % os.getcwd()))
    import time
    t0 = time.time()
    rc = launch(["--nproc_per_node", "1", "--elastic_level", "1",
                 "--max_restarts", "0", "--log_dir", str(tmp_path / "log"),
                 str(script)])
    assert rc == 101
    assert time.time() - t0 < 40, "hang was not detected promptly"


def test_finished_rank_not_judged_hung(tmp_path):
    """Rank 0 exits cleanly early; rank 1 keeps training past rank 0's
    heartbeat staleness. The job must still succeed — finished ranks are
    excluded from hang detection."""
    script = tmp_path / "uneven.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, %r)
        from paddle_tpu.distributed.launch.elastic import worker_heartbeat
        em = worker_heartbeat(interval=0.2)
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        if rank == 0:
            sys.exit(0)         # finishes immediately; hb goes stale
        time.sleep(7)           # > heartbeat_timeout while rank 0 is stale
        em.stop()
        sys.exit(0)
    """ % os.getcwd()))
    rc = launch(["--nproc_per_node", "2", "--elastic_level", "1",
                 "--max_restarts", "0", "--log_dir", str(tmp_path / "log"),
                 str(script)])
    assert rc == 0


def test_elastic_manager_heartbeats():
    store = TCPStore("127.0.0.1", 0, world_size=1, is_master=True)
    em = ElasticManager(store, "job1", np=2, heartbeat_interval=0.1,
                        heartbeat_timeout=0.5)
    em.register(0)
    em.start_heartbeat(0)
    import time
    time.sleep(0.3)
    assert 0 not in em.dead_members()
    assert 1 in em.dead_members()  # never heartbeated
    em.stop()
    time.sleep(0.7)
    assert 0 in em.dead_members()  # heartbeat went stale after stop
    assert em.desired_np() == 2
    em.set_desired_np(3)
    assert em.desired_np() == 3 and em.need_rescale()
    store.close()


def test_restart_count_env_increments(tmp_path):
    """Workers see PADDLE_RESTART_COUNT so they can auto-resume from a
    checkpoint after an elastic restart."""
    script = tmp_path / "counting.py"
    marker = tmp_path / "attempted"
    out = tmp_path / "counts.txt"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        with open({str(out)!r}, "a") as f:
            f.write(os.environ["PADDLE_RESTART_COUNT"] + "\\n")
        marker = {str(marker)!r}
        if not os.path.exists(marker):
            open(marker, "w").write("1")
            sys.exit(101)
        sys.exit(0)
    """))
    rc = launch(["--nproc_per_node", "1", "--elastic_level", "1",
                 "--max_restarts", "2", "--log_dir", str(tmp_path / "log"),
                 str(script)])
    assert rc == 0
    counts = out.read_text().split()
    assert counts == ["0", "1"]


def test_elastic_scale_out(tmp_path, monkeypatch):
    """Scale in/out (VERDICT r1 #8): changing the desired world size on
    the store rebuilds the pod at the new size. The pod starts at np=1;
    the worker itself requests np=2 on its first incarnation, then both
    ranks of the rebuilt pod write markers."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        out = sys.argv[1]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        r = os.environ["PADDLE_TRAINER_ID"]
        open(os.path.join(out, f"seen_np{n}_r{r}"), "w").write("1")
        if n == "1":
            # request a scale-out from inside the job, then idle so the
            # controller (not our exit) drives the rebuild
            from paddle_tpu.distributed.launch import scale_job
            ep = os.environ["PADDLE_ELASTIC_STORE_ENDPOINT"]
            scale_job(ep, os.environ["PADDLE_JOB_ID"], 2)
            time.sleep(30)
    """))
    import os as _os
    monkeypatch.setenv("PYTHONPATH", _os.pathsep.join(
        filter(None, ["/root/repo", _os.environ.get("PYTHONPATH")])))
    rc = launch(["--nproc_per_node", "1", "--elastic_level", "1",
                 "--max_restarts", "2", "--job_id", "scaletest",
                 "--log_dir", str(tmp_path / "log"), str(script),
                 str(tmp_path)])
    assert rc == 0
    assert (tmp_path / "seen_np1_r0").exists()
    assert (tmp_path / "seen_np2_r0").exists()
    assert (tmp_path / "seen_np2_r1").exists()


def test_auto_tune_picks_best_and_runs_real_job(tmp_path, monkeypatch):
    """--auto_tune trials the user's script over PlanCandidates (the
    planner vocabulary — JSON env protocol) and the real run sees the
    winner (reference launch/main.py auto-tuner mode)."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        from paddle_tpu.distributed.launch.auto_tune import (
            candidate_from_env, is_trial, report_metric)
        cand = candidate_from_env()
        if is_trial():
            # fake benchmark: prefer high mp, then micro_batches
            report_metric(cand.mp * 100 + cand.micro_batches)
        else:
            with open(os.path.join(sys.argv[1], "final.txt"), "w") as f:
                f.write(os.environ["PADDLE_AUTO_TUNER_CANDIDATE"])
    """))
    cfg = tmp_path / "tune.json"
    cfg.write_text(json.dumps({
        "global_batch": 4, "num_layers": 4, "num_heads": 4,
        "hidden_size": 32, "vocab_size": 64, "seq_len": 16,
        "micro_batch_options": [1, 2], "top_k": 8,
    }))
    import os as _os
    monkeypatch.setenv("PYTHONPATH", _os.pathsep.join(
        filter(None, ["/root/repo", _os.environ.get("PYTHONPATH")])))
    rc = launch(["--nproc_per_node", "1", "--auto_tune",
                 "--auto_tuner_json", str(cfg), "--job_id", "tunetest",
                 "--log_dir", str(tmp_path / "log"), str(script),
                 str(tmp_path)])
    assert rc == 0
    final = json.loads((tmp_path / "final.txt").read_text())
    # world=1 -> dp=mp=pp=ep=1; best micro_batches=2 by the metric
    assert (final["dp"], final["mp"], final["pp"], final["ep"]) == \
        (1, 1, 1, 1)
    assert final["micro_batches"] == 2, final
