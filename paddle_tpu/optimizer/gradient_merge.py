"""Gradient merge / accumulation (reference: DistributedStrategy
gradient_merge_configs {k_steps, avg} + the static gradient-merge pass
python/paddle/distributed/passes/auto_parallel_gradient_merge.py and
dygraph gradient accumulation in meta_parallel).

TPU design: a functional optimizer wrapper — grads accumulate in the
optimizer state pytree for k_steps, then one inner update fires (mean or
sum). Pure lax.cond control flow, so the whole k-step cycle lives inside
one jitted train step and composes with the hybrid engine and ZeRO
sharding (the accumulator inherits each parameter's sharding).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from ..enforce import (PreconditionNotMetError, enforce,
                       enforce_ge)
from jax import lax

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer:
    """Wraps any functional optimizer (init_state/apply) with k-step
    gradient accumulation. The accumulator is ALWAYS fp32, whatever the
    param/grad dtype — k bf16 adds of k-times-smaller micrograds lose
    low bits every step and the merged update drifts from the big-batch
    gradient (regression-tested against one big batch).

    comm_fn: accumulate-locally / communicate-once-per-k-steps. When set,
    the train engine hands this wrapper dp-UNreduced local gradients
    (`_skips_grad_sync`), they accumulate locally for k_steps, and
    comm_fn (e.g. ``comm_overlap.make_merge_comm_fn(dp_axis)`` — one
    bucketed reduction of the merged grad) runs on the merged gradient
    only when the inner update fires: 1/k the collective launches and
    bytes, identical math for the full-precision path (the dp mean
    commutes with the k-step sum)."""

    def __init__(self, inner, k_steps: int, avg: bool = True,
                 comm_fn=None):
        enforce_ge(k_steps, 1, op="GradientMergeOptimizer",
                   name="k_steps")
        self._inner = inner
        self.k_steps = int(k_steps)
        self.avg = avg
        self._comm_fn = comm_fn
        self._eager_count = 0
        self._eager_acc = None

    @property
    def _skips_grad_sync(self):
        # with a comm_fn the wrapper owns the dp reduction (at merge
        # time); otherwise inherit the inner's behavior (False for the
        # standard family)
        return (self._comm_fn is not None
                or getattr(self._inner, "_skips_grad_sync", False))

    # the hybrid optimizer swaps _grad_clip; it must land on the optimizer
    # that actually applies it (the inner), not shadow it on this wrapper
    @property
    def _grad_clip(self):
        return self._inner._grad_clip

    @_grad_clip.setter
    def _grad_clip(self, value):
        self._inner._grad_clip = value

    def init_state(self, params):
        return {
            "inner": self._inner.init_state(params),
            # fp32 accumulator regardless of param/grad dtype — see class
            # docstring (bf16 accumulation loses the k-step tail bits)
            "acc": jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "count": jnp.zeros((), jnp.int32),
        }

    def apply(self, params, grads, state, lr=None):
        acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), state["acc"], grads)
        count = state["count"] + 1
        k = self.k_steps

        def do_update(_):
            scale = 1.0 / k if self.avg else 1.0
            merged = jax.tree.map(lambda a: a * scale, acc)
            if self._comm_fn is not None:
                # the once-per-k-steps sync of the merged gradient
                merged = self._comm_fn(merged)
            new_params, new_inner = self._inner.apply(
                params, merged, state["inner"], lr)
            zeroed = jax.tree.map(jnp.zeros_like, acc)
            return new_params, new_inner, zeroed, jnp.zeros((), jnp.int32)

        def no_update(_):
            return params, state["inner"], acc, count

        new_params, new_inner, new_acc, new_count = lax.cond(
            count >= k, do_update, no_update, operand=None)
        return new_params, {"inner": new_inner, "acc": new_acc,
                            "count": new_count}

    # -- eager surface -------------------------------------------------------
    def step(self):
        """Eager accumulation over Parameter.grad slots: the inner step
        fires only every k-th call (matching apply())."""
        params = getattr(self._inner, "_parameter_list", None)
        enforce(params, "GradientMergeOptimizer.step() needs the inner "
                "optimizer constructed with `parameters`",
                op="GradientMergeOptimizer.step",
                error=PreconditionNotMetError)
        if self._eager_acc is None:
            self._eager_acc = [None] * len(params)
        for i, p in enumerate(params):
            if p.grad is None:
                continue
            g = jnp.asarray(p.grad, jnp.float32)
            self._eager_acc[i] = g if self._eager_acc[i] is None \
                else self._eager_acc[i] + g
        self._eager_count += 1
        if self._eager_count < self.k_steps:
            for p in params:
                p.grad = None  # consumed into the accumulator
            return None
        scale = 1.0 / self.k_steps if self.avg else 1.0
        for p, a in zip(params, self._eager_acc):
            p.grad = None if a is None else a * scale
        out = self._inner.step()
        self._eager_count = 0
        self._eager_acc = None
        return out

    def state_dict(self):
        inner_sd = (self._inner.state_dict()
                    if hasattr(self._inner, "state_dict") else {})
        return {"inner": inner_sd,
                "gm_count": self._eager_count,
                # copy: later step() calls mutate the live accumulator list
                "gm_acc": (None if self._eager_acc is None
                           else list(self._eager_acc))}

    def set_state_dict(self, sd):
        if "inner" in sd and hasattr(self._inner, "set_state_dict"):
            self._inner.set_state_dict(sd["inner"])
        self._eager_count = sd.get("gm_count", 0)
        acc = sd.get("gm_acc")
        self._eager_acc = None if acc is None else list(acc)

    def __getattr__(self, item):
        if item == "_inner":
            raise AttributeError(item)
        return getattr(self._inner, item)
