"""Schedule-overlapped, bucketed dp gradient synchronization.

Replaces the end-of-backward monolithic dp ``pmean`` with per-bucket
collectives issued while compute is still in flight:

* :func:`reduce_bucketed` — one fused flat collective per size-targeted
  bucket (``FLAGS_comm_bucket_mb``); optionally int8-quantized with
  error feedback (``FLAGS_comm_quantize=int8``, see quantize.py).
* :func:`reduce_scatter_tree` — the ZeRO-1 form: per-leaf
  ``psum_scatter`` over dp (each rank keeps only its update shard).
* :func:`microbatched_reduced_grads` — gradient accumulation inside
  ``lax.scan`` whose carry holds already-REDUCED buckets: microbatch
  m's collectives are issued inside iteration m, so XLA's async
  collectives + latency-hiding scheduler (``FLAGS_xla_latency_hiding_
  scheduler``) overlap them with microbatch m+1's forward/backward —
  the T3 (arXiv:2401.16677) fine-grained-overlap structure expressed
  as one jitted program.

Everything here runs INSIDE shard_map (explicit per-device values,
explicit named-axis collectives) and is deterministic: same inputs, same
program, same bits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .bucketing import (BucketPlan, build_bucket_plan, local_shape,
                        pack_bucket, unpack_bucket)
from .quantize import ef_quantized_psum

__all__ = ["CommOverlapConfig", "config_from_flags", "reduce_bucketed",
           "reduce_scatter_tree", "microbatched_reduced_grads",
           "ef_plan_for", "init_ef_residuals", "ef_residual_specs"]

_QUANT_MODES = ("", "int8")


@dataclasses.dataclass(frozen=True)
class CommOverlapConfig:
    """Knobs for the bucketed-overlap gradient sync.

    bucket_mb: target bucket size in MB of wire bytes (<= 0: one bucket).
    quantize: "" (full precision) or "int8" (error-feedback quantized
        all-reduce; needs example_params at build time for the residual
        state, and is only defined for the replicated dp path).
    microbatches: grad-accumulation slices inside the overlap scan; 1
        keeps the single-backward structure (buckets alone still let the
        scheduler overlap collectives with the optimizer update).
    reduce_dtype: wire dtype for the non-quantized path (e.g. bf16);
        None reduces in the gradients' own dtype.
    """
    bucket_mb: float = 4.0
    quantize: str = ""
    microbatches: int = 1
    reduce_dtype: Any = None

    def __post_init__(self):
        from ...enforce import enforce, enforce_in
        enforce_in(self.quantize, _QUANT_MODES, op="CommOverlapConfig",
                   name="quantize")
        enforce(self.microbatches >= 1,
                "microbatches must be >= 1", op="CommOverlapConfig",
                microbatches=self.microbatches)

    @property
    def bucket_bytes(self) -> float:
        return self.bucket_mb * (1 << 20)


def config_from_flags() -> Optional[CommOverlapConfig]:
    """The flag-driven opt-in: None (feature off) unless one of
    FLAGS_comm_bucket_mb / FLAGS_comm_quantize /
    FLAGS_comm_overlap_microbatches asks for it."""
    from ...flags import flag
    bmb = float(flag("comm_bucket_mb"))
    quant = str(flag("comm_quantize") or "")
    micro = max(int(flag("comm_overlap_microbatches")), 1)
    if bmb <= 0 and not quant and micro <= 1:
        return None
    return CommOverlapConfig(bucket_mb=bmb if bmb > 0 else 0.0,
                             quantize=quant, microbatches=micro)


def reduce_bucketed(grads, axis, *, axis_size: int,
                    plan: Optional[BucketPlan] = None,
                    bucket_bytes: float = 0.0, quantize: str = "",
                    residuals: Optional[List[jax.Array]] = None,
                    reduce_dtype=None, weight: float = 1.0,
                    mean: bool = True):
    """Bucketed dp reduction of a LOCAL grad pytree (inside shard_map).

    Packs each bucket into one flat buffer and issues ONE collective per
    bucket (int8 error-feedback psum when quantize="int8"). `weight`
    pre-scales the gradients (1/M for microbatch accumulation) BEFORE
    quantization so residuals carry consistently-scaled error. Returns
    ``(reduced_tree, new_residuals)``; new_residuals is None unless
    quantized. Elementwise identical to per-leaf ``lax.pmean`` for the
    full-precision path (psum of a concatenation == concatenation of
    psums)."""
    leaves, treedef = jax.tree.flatten(grads)
    if plan is None:
        plan = build_bucket_plan(leaves, bucket_bytes)
    out: List[Any] = list(leaves)
    new_residuals: Optional[List[jax.Array]] = [] if quantize else None
    for bucket in plan.buckets:
        if quantize == "int8":
            flat = pack_bucket(leaves, bucket, dtype=jnp.float32)
            if weight != 1.0:
                flat = flat * jnp.float32(weight)
            res = (residuals[bucket.index] if residuals is not None
                   else jnp.zeros_like(flat))
            red, new_res = ef_quantized_psum(
                flat, res, axis,
                mean_divisor=float(axis_size) if mean else 1.0)
            new_residuals.append(new_res)
        else:
            flat = pack_bucket(leaves, bucket, dtype=reduce_dtype)
            if weight != 1.0:
                flat = flat * jnp.asarray(weight, flat.dtype)
            red = lax.psum(flat, axis)
            if mean:
                red = red / axis_size
        for leaf_index, piece in unpack_bucket(red, bucket):
            out[leaf_index] = piece
    return jax.tree.unflatten(treedef, out), new_residuals


def reduce_scatter_tree(grads, zdims, axis, *, axis_size: int,
                        reduce_dtype=None, weight: float = 1.0,
                        mean: bool = True):
    """ZeRO-1 gradient reduction: psum_scatter each leaf over `axis`
    along its shard dim (zdim < 0: plain pmean — tiny replicated leaves).
    Same per-leaf semantics as the hybrid engine's monolithic pass, but
    callable per microbatch inside the overlap scan so the scatters hide
    under the next microbatch's compute. None grads pass through."""
    def one(g, zd):
        if g is None:
            return None
        gr = g.astype(reduce_dtype) if reduce_dtype is not None else g
        if weight != 1.0:
            gr = gr * jnp.asarray(weight, gr.dtype)
        if zd < 0:
            red = lax.pmean(gr, axis) if mean else lax.psum(gr, axis)
        else:
            red = lax.psum_scatter(gr, axis, scatter_dimension=zd,
                                   tiled=True)
            if mean:
                red = red / axis_size
        return red.astype(g.dtype)

    return jax.tree.map(one, grads, zdims,
                        is_leaf=lambda x: x is None)


def microbatched_reduced_grads(loss_fn: Callable, params,
                               batch_args: Sequence[jax.Array],
                               num_microbatches: int,
                               reduce_fn: Callable,
                               residuals=None, with_obs: bool = False):
    """Gradient accumulation with in-scan bucket reduction.

    Splits each batch arg's leading (local-batch) dim into
    `num_microbatches` slices and scans; every iteration computes that
    microbatch's grads and immediately reduces them via
    ``reduce_fn(grads, residuals) -> (reduced, new_residuals)`` (which
    must fold the 1/M weight), accumulating the REDUCED result into an
    fp32 carry. The collectives of iteration m sit in the program before
    iteration m+1's compute — exactly the structure the latency-hiding
    scheduler overlaps. Returns ``(mean_loss, grads, new_residuals)``
    with grads cast back to their original dtypes.

    with_obs=True additionally collects observability.observe() calls
    made inside loss_fn (threaded out of value_and_grad as aux so tracers
    never escape the scan) and returns a 4th element: a {name: value}
    dict averaged over the microbatches."""
    from ...enforce import enforce
    M = int(num_microbatches)
    b = batch_args[0].shape[0]
    enforce(M >= 1 and b % M == 0,
            "comm-overlap microbatches must divide the local batch",
            op="comm_overlap.microbatched_reduced_grads", batch=b,
            microbatches=M)
    if with_obs:
        from ...observability.metrics import collecting, obs_dict

        def fwd(p, *a):
            with collecting() as sink:
                loss = loss_fn(p, *a)
            return loss, obs_dict(sink)

        avg = jax.value_and_grad(fwd, has_aux=True)

        def vg(p, *a):
            (loss, obs), g = avg(p, *a)
            return loss, g, obs
    else:
        _vg = jax.value_and_grad(lambda p, *a: loss_fn(p, *a))

        def vg(p, *a):
            loss, g = _vg(p, *a)
            return loss, g, {}

    def one(mb_args, res):
        loss, g, obs = vg(params, *mb_args)
        red, res = reduce_fn(g, res)
        return loss, red, res, obs

    if M == 1:
        loss, red, res, obs = one(tuple(batch_args), residuals)
        return (loss, red, res, obs) if with_obs else (loss, red, res)

    slices = tuple(a.reshape((M, b // M) + a.shape[1:]) for a in batch_args)
    # carry structure via ABSTRACT eval — peeling a real first microbatch
    # out of the scan would compile the fwd/bwd body twice
    loss_sh, red_sh, _, obs_sh = jax.eval_shape(
        one, tuple(s[0] for s in slices), residuals)
    acc0 = jax.tree.map(lambda sd: jnp.zeros(sd.shape, jnp.float32),
                        red_sh)
    obs0 = jax.tree.map(lambda sd: jnp.zeros(sd.shape, jnp.float32),
                        obs_sh)

    def body(carry, mb):
        acc, res, lsum, osum = carry
        loss, red, res, obs = one(mb, res)
        acc = jax.tree.map(lambda a, r: a + r.astype(jnp.float32), acc, red)
        osum = jax.tree.map(lambda a, o: a + o.astype(jnp.float32),
                            osum, obs)
        return (acc, res, lsum + loss, osum), None

    (acc, res, lsum, osum), _ = lax.scan(
        body, (acc0, residuals, jnp.zeros((), loss_sh.dtype), obs0),
        slices)
    grads = jax.tree.map(lambda a, sd: a.astype(sd.dtype), acc, red_sh)
    obs = jax.tree.map(lambda o: o / M, osum)
    if with_obs:
        return lsum / M, grads, res, obs
    return lsum / M, grads, res


# ---------------------------------------------------------------------------
# Error-feedback residual state (persists across steps; threaded by the
# hybrid engine as opt_state["comm_ef"]).
# ---------------------------------------------------------------------------
def ef_plan_for(example_params, specs, mesh,
                bucket_bytes: float) -> BucketPlan:
    """Bucket plan over the LOCAL (per-device shard) gradient shapes — the
    shapes reduce_bucketed actually sees inside shard_map. Built once at
    build time so the residual state and the traced program agree."""
    leaves, treedef = jax.tree.flatten(example_params)
    spec_leaves = treedef.flatten_up_to(specs)
    local = [jax.ShapeDtypeStruct(local_shape(p.shape, s, mesh), p.dtype)
             for p, s in zip(leaves, spec_leaves)]
    return build_bucket_plan(local, bucket_bytes)


def ef_residual_specs(plan: BucketPlan, mesh) -> List[P]:
    """Residuals are fully device-varying (each rank's rounding error):
    one flat leading dim sharded over EVERY mesh axis."""
    return [P(tuple(mesh.axis_names))] * plan.n_buckets


def init_ef_residuals(plan: BucketPlan, mesh) -> List[jax.Array]:
    n_dev = int(mesh.devices.size)
    out = []
    for bucket, spec in zip(plan.buckets, ef_residual_specs(plan, mesh)):
        arr = jnp.zeros((n_dev * bucket.size,), jnp.float32)
        out.append(jax.device_put(arr, NamedSharding(mesh, spec)))
    return out
