"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).

Attention rides scaled_dot_product_attention (op-registry dispatched: XLA
composition everywhere, Pallas flash kernel on TPU)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
           "TransformerDecoderLayer", "TransformerDecoder", "Transformer"]


def _convert_attn_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    m = jnp.asarray(attn_mask)
    if m.dtype == jnp.bool_:
        return m
    return m.astype(jnp.float32)


from collections import namedtuple

_Cache = namedtuple("Cache", ["k", "v"])
_StaticCache = namedtuple("StaticCache", ["k", "v"])


class MultiHeadAttention(Layer):
    """(reference: nn/layer/transformer.py MultiHeadAttention; fused form
    paddle/phi/kernels/fusion/gpu/fused_attention_kernel.cu).

    Cache semantics follow the reference (transformer.py Cache/StaticCache):
    Cache holds previously-projected self-attention k/v to concatenate with
    the new step's; StaticCache holds encoder-memory k/v used as-is."""

    Cache = _Cache
    StaticCache = _StaticCache

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        from ...enforce import enforce
        enforce(self.head_dim * num_heads == embed_dim,
                f"embed_dim {embed_dim} not divisible by num_heads "
                f"{num_heads}", op="MultiHeadAttention")
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        B, S = x.shape[0], x.shape[1]
        return x.reshape(B, S, self.num_heads, self.head_dim)

    def gen_cache(self, key, value=None, type=None):
        """type=StaticCache: precompute k/v from encoder memory (used as-is in
        forward). type=Cache (default) with value=None: empty incremental
        cache. (key, value) pair: wrap directly."""
        if type is _StaticCache or (type is None and value is not None and value is not key):
            if value is None:
                value = key
            return _StaticCache(self._shape(self.k_proj(key)),
                                self._shape(self.v_proj(value)))
        if value is None:
            B = key.shape[0]
            empty = jnp.zeros((B, 0, self.num_heads, self.head_dim), key.dtype)
            return _Cache(empty, empty)
        return _Cache(key, value)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None,
                segment_ids=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._shape(self.q_proj(query))
        if isinstance(cache, _StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
            if cache is not None:
                k = jnp.concatenate([cache.k, k], axis=1)
                v = jnp.concatenate([cache.v, v], axis=1)
        mask = _convert_attn_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            is_causal=False, training=self.training,
            segment_ids=segment_ids)
        B, S = out.shape[0], out.shape[1]
        out = self.out_proj(out.reshape(B, S, self.embed_dim))
        if cache is not None:
            if isinstance(cache, _StaticCache):
                return out, cache
            return out, _Cache(k, v)
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None, segment_ids=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask,
                                 segment_ids=segment_ids)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None, segment_ids=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask, segment_ids=segment_ids)
            else:
                output, c = mod(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [l.gen_cache(src) for l in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, inc = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None or len(cache) < 2:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, _ = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        static = cache[1] if len(cache) > 1 else None
        return tgt, (inc, static) if static is not None else (inc,)

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory, type=_Cache)
        static = self.cross_attn.gen_cache(memory, memory, type=_StaticCache)
        return (incremental, static)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, c = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory):
        return [l.gen_cache(memory) for l in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        return jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, -jnp.inf)


def _clone_layer(layer):
    import copy
    return copy.deepcopy(layer)
