from .registry import (
    OpSchema,
    dispatch_stats,
    get_op,
    infer_meta,
    list_ops,
    register_op,
    register_pallas_impl,
)

__all__ = [
    "OpSchema", "dispatch_stats", "get_op", "infer_meta", "list_ops",
    "register_op", "register_pallas_impl",
]
