"""LocalSGD meta-optimizer (reference:
python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py
LocalSGDOptimizer: k_steps local updates, then parameter averaging across
the data-parallel group — trades per-step gradient all-reduce bandwidth
for periodic parameter synchronization).

TPU design: a pure optimizer WRAPPER that runs inside the explicit-SPMD
engine's shard_map. It sets ``_skips_grad_sync`` so
models.hybrid_engine.build_train_step hands it the UNreduced local
gradients; the inner optimizer advances each replica independently and
every ``k_steps`` the parameters are averaged with one ``lax.pmean`` over
the dp axis. Optimizer MOMENTS stay local (reference semantics — only
parameters synchronize); after a sync step all replicas hold identical
parameters, between syncs they drift on their local shards.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from ....enforce import enforce_ge
from jax import lax

__all__ = ["LocalSGD"]


class LocalSGD:
    _skips_grad_sync = True

    def __init__(self, inner, k_steps: int = 4, dp_axis: str = "dp"):
        enforce_ge(k_steps, 1, op="LocalSGD", name="k_steps")
        self._inner = inner
        self.k_steps = int(k_steps)
        self.dp_axis = dp_axis

    def init_state(self, params):
        return {"inner": self._inner.init_state(params),
                "count": jnp.zeros((), jnp.int32)}

    def get_lr(self):
        return self._inner.get_lr()

    def apply(self, params, grads, state, lr=None):
        new_p, new_inner = self._inner.apply(params, grads,
                                             state["inner"], lr)
        count = state["count"] + 1

        def sync(p):
            return jax.tree.map(
                lambda x: lax.pmean(x, self.dp_axis).astype(x.dtype), p)

        new_p = lax.cond(count % self.k_steps == 0, sync, lambda p: p,
                         new_p)
        return new_p, {"inner": new_inner, "count": count}
