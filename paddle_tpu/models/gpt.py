"""GPT model family (reference: the GPT/GPT-3 configs exercised by Fleet
hybrid-parallel — model definition test/legacy_test/auto_parallel_gpt_model.py,
used via test/auto_parallel/get_gpt_model.py:18; BASELINE configs 2/4).

Two executions of the same architecture:

* ``GPT`` — eager nn.Layer for single-device / GSPMD-auto use (Model.fit,
  generation). Attention rides the op-registry scaled_dot_product_attention
  (Pallas flash kernel on TPU).

* ``hybrid`` engine — functional stacked-parameter form for the explicit
  SPMD path: vocab-parallel embedding + Megatron TP inside each block (over
  'mp'; optionally sequence-parallel with ring collective-matmul overlap —
  FLAGS_mp_seq_parallel / FLAGS_mp_collective_matmul via
  distributed.comm_overlap.collective_matmul), scan+ppermute pipeline over
  'pp' (spmd_pipeline), dp gradient sync (monolithic pmean, or
  bucketed/overlapped/int8-quantized via distributed.comm_overlap —
  FLAGS_comm_bucket_mb et al.), all inside ONE shard_map/jit program. This is the TPU-native
  equivalent of the reference's PipelineParallel+TensorParallel meta_parallel
  stack (fleet/meta_parallel/pipeline_parallel.py:547,
  fleet/layers/mpu/mp_layers.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from ..enforce import enforce
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..quantization.fp8 import site_mm as _fp8_mm
from ..distributed.fleet.meta_parallel.pp_utils.spmd_pipeline import (
    spmd_pipeline, spmd_pipeline_interleaved, spmd_pipeline_zero_bubble,
    vpp_block_permutation, vpp_chunk_blocks, vpp_wrap_shard_params)

__all__ = ["GPTConfig", "GPT", "gpt_tiny", "gpt_small", "gpt_moe_tiny",
           "gpt_1p3b", "gpt_6p7b",
           "init_hybrid_params", "hybrid_param_specs", "hybrid_loss_fn",
           "build_hybrid_train_step", "split_streamed_params",
           "init_streamed_params", "streamed_fns", "GPT_FP8_SITES",
           "moe_telemetry_series"]

# the dense-stack GEMM sites that run fp8 under FLAGS_fp8 / amp O3 (the
# attention einsums, LM head and embedding stay bf16 — quantization.fp8)
GPT_FP8_SITES = ("qkv", "proj", "fc1", "fc2")


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden: Optional[int] = None
    max_seq_len: int = 1024
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16  # MXU-native compute dtype
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    # GPT-MoE (hybrid engine): > 0 replaces every SECOND layer's FFN with
    # a switch-routed (top-1, capacity-bounded) bank of this many experts,
    # dispatched over the 'ep' mesh axis — the alternating dense/MoE
    # layout of Switch-Transformer-style GPT variants. 0 = dense GPT,
    # bitwise-unchanged.
    moe_num_experts: int = 0
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2

    def __post_init__(self):
        if self.ffn_hidden is None:
            self.ffn_hidden = 4 * self.hidden_size
        enforce(self.hidden_size % self.num_heads == 0,
                "hidden_size must be divisible by num_heads", op="GPTConfig",
                hidden_size=self.hidden_size, num_heads=self.num_heads)
        enforce(self.moe_num_experts == 0 or self.num_layers % 2 == 0,
                "GPT-MoE stacks (dense, MoE) layer PAIRS so the pipeline "
                "scan stays homogeneous — num_layers must be even",
                op="GPTConfig", num_layers=self.num_layers)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def moe_on(self) -> bool:
        return self.moe_num_experts > 0


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                     num_heads=4, max_seq_len=256, **kw)


def gpt_small(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt_moe_tiny(**kw):
    kw.setdefault("moe_num_experts", 8)
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                     num_heads=4, max_seq_len=256, **kw)


def gpt_1p3b(**kw):
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


def gpt_6p7b(**kw):
    return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                     max_seq_len=2048, **kw)


# ---------------------------------------------------------------------------
# Eager nn.Layer form
# ---------------------------------------------------------------------------
class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        H = cfg.hidden_size
        self.cfg = cfg
        self.ln1 = nn.LayerNorm(H)
        self.qkv = nn.Linear(H, 3 * H, bias_attr=cfg.use_bias)
        self.proj = nn.Linear(H, H, bias_attr=cfg.use_bias)
        self.ln2 = nn.LayerNorm(H)
        self.fc1 = nn.Linear(H, cfg.ffn_hidden, bias_attr=cfg.use_bias)
        self.fc2 = nn.Linear(cfg.ffn_hidden, H, bias_attr=cfg.use_bias)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        cfg = self.cfg
        B, S, H = x.shape
        h = self.ln1(x)
        qkv = self.qkv(h).reshape(B, S, 3, cfg.num_heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                              dropout_p=cfg.dropout,
                                              training=self.training)
        attn = self.proj(attn.reshape(B, S, H))
        x = x + self.drop(attn)
        h = self.ln2(x)
        x = x + self.drop(self.fc2(F.gelu(self.fc1(h), approximate=True)))
        return x


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        from ..nn.initializer import Normal
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, tokens):
        B, S = tokens.shape
        pos = jnp.arange(S)[None, :]
        x = self.wte(tokens) + self.wpe(pos)
        x = self.drop(x).astype(self.cfg.dtype)
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        return self.lm_head(x.astype(jnp.float32))


# ---------------------------------------------------------------------------
# Hybrid (explicit SPMD) form: stacked params + shard_map engine
# ---------------------------------------------------------------------------
def init_hybrid_params(cfg: GPTConfig, key) -> Dict[str, Any]:
    """Stacked-parameter pytree. Blocks are stacked on a leading [L] axis so
    the pipeline can shard them over 'pp' and scan within a stage.

    GPT-MoE (cfg.moe_num_experts > 0): blocks become (dense, MoE) layer
    PAIRS stacked [L/2] — ``blocks = {"dense": {...}, "moe": {...}}`` —
    so the pipeline scan stays homogeneous while every second layer runs
    the switch-routed expert FFN. The MoE half carries its own attention
    sublayer (same TP layout) plus ``gate_w [L/2, H, E]`` and the stacked
    expert bank ``w1 [L/2, E, H, FF] / w2 [L/2, E, FF, H]`` that shards
    over 'ep' (and 'mp' on the expert hidden dim)."""
    H, L, FF, V = cfg.hidden_size, cfg.num_layers, cfg.ffn_hidden, cfg.vocab_size
    k = jax.random.split(key, 12)
    std = 0.02
    pd = cfg.param_dtype

    def nrm(key, shape, scale=std):
        return (scale * jax.random.normal(key, shape)).astype(pd)

    def dense_blocks(nl, kq, kp, k1, k2):
        return {
            "ln1_g": jnp.ones((nl, H), pd),
            "ln1_b": jnp.zeros((nl, H), pd),
            "qkv_w": nrm(kq, (nl, H, 3 * H)),
            "qkv_b": jnp.zeros((nl, 3 * H), pd),
            "proj_w": nrm(kp, (nl, H, H), std / math.sqrt(2 * L)),
            "proj_b": jnp.zeros((nl, H), pd),
            "ln2_g": jnp.ones((nl, H), pd),
            "ln2_b": jnp.zeros((nl, H), pd),
            "fc1_w": nrm(k1, (nl, H, FF)),
            "fc1_b": jnp.zeros((nl, FF), pd),
            "fc2_w": nrm(k2, (nl, FF, H), std / math.sqrt(2 * L)),
            "fc2_b": jnp.zeros((nl, H), pd),
        }

    if cfg.moe_on:
        L2, E = L // 2, cfg.moe_num_experts
        blocks = {
            "dense": dense_blocks(L2, k[2], k[3], k[4], k[5]),
            "moe": {
                "ln1_g": jnp.ones((L2, H), pd),
                "ln1_b": jnp.zeros((L2, H), pd),
                "qkv_w": nrm(k[7], (L2, H, 3 * H)),
                "qkv_b": jnp.zeros((L2, 3 * H), pd),
                "proj_w": nrm(k[8], (L2, H, H), std / math.sqrt(2 * L)),
                "proj_b": jnp.zeros((L2, H), pd),
                "ln2_g": jnp.ones((L2, H), pd),
                "ln2_b": jnp.zeros((L2, H), pd),
                "gate_w": nrm(k[9], (L2, H, E)),
                "w1": nrm(k[10], (L2, E, H, FF)),
                "b1": jnp.zeros((L2, E, FF), pd),
                "w2": nrm(k[11], (L2, E, FF, H), std / math.sqrt(2 * L)),
                "b2": jnp.zeros((L2, E, H), pd),
            },
        }
    else:
        blocks = dense_blocks(L, k[2], k[3], k[4], k[5])

    params = {
        "wte": nrm(k[0], (V, H)),
        "wpe": nrm(k[1], (cfg.max_seq_len, H)),
        "blocks": blocks,
        "lnf_g": jnp.ones((H,), pd),
        "lnf_b": jnp.zeros((H,), pd),
        "head_w": nrm(k[6], (H, V)),
    }
    return params


def hybrid_param_specs(cfg: GPTConfig) -> Dict[str, Any]:
    """PartitionSpecs: blocks stacked-L over 'pp'; Megatron shardings over
    'mp'; vocab-parallel embedding + head over 'mp'. GPT-MoE additionally
    shards the stacked expert bank's E dim over 'ep' and the expert
    hidden dim over 'mp' (w1 column-parallel, w2 row-parallel — one mp
    all-reduce per expert FFN); the gate stays replicated over ep/mp so
    routing is identical on every rank."""
    dense = {
        "ln1_g": P("pp"), "ln1_b": P("pp"),
        "qkv_w": P("pp", None, "mp"), "qkv_b": P("pp", "mp"),
        "proj_w": P("pp", "mp", None), "proj_b": P("pp"),
        "ln2_g": P("pp"), "ln2_b": P("pp"),
        "fc1_w": P("pp", None, "mp"), "fc1_b": P("pp", "mp"),
        "fc2_w": P("pp", "mp", None), "fc2_b": P("pp"),
    }
    if cfg.moe_on:
        blocks = {
            "dense": dense,
            "moe": {
                "ln1_g": P("pp"), "ln1_b": P("pp"),
                "qkv_w": P("pp", None, "mp"), "qkv_b": P("pp", "mp"),
                "proj_w": P("pp", "mp", None), "proj_b": P("pp"),
                "ln2_g": P("pp"), "ln2_b": P("pp"),
                "gate_w": P("pp"),
                "w1": P("pp", "ep", None, "mp"),
                "b1": P("pp", "ep", "mp"),
                "w2": P("pp", "ep", "mp", None),
                "b2": P("pp", "ep"),
            },
        }
    else:
        blocks = dense
    return {
        "wte": P("mp", None),
        "wpe": P(),
        "blocks": blocks,
        "lnf_g": P(), "lnf_b": P(),
        "head_w": P(None, "mp"),
    }


def _ln(x, g, b, eps=1e-5):
    # deliberately the COMPOSED form, not the Pallas fused LayerNorm the
    # registry dispatches for nn-level users: inside this model's
    # scan-over-layers + remat structure the kernel's call overhead and
    # saved-stats traffic cost more than the fusion saves (measured
    # 597.7 vs 582.2 ms/step on the 1.3B flagship, one v5e, round 4 —
    # the kernel wins on the unscanned BERT graph instead)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), -1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * g + b


def _attention(q, k, v):
    """Causal attention on local heads. [B, S, h_local, D]."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    S = logits.shape[-1]
    mask = jnp.tril(jnp.ones((S, S), bool))
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _attn_sublayer(p, x, cfg: GPTConfig, mp_axis: str = "mp", fp8=None,
                   sp=None, flash=None, sep_axis=None):
    """ln1 + Megatron-TP causal attention + residual — the shared first
    half of the dense and MoE hybrid blocks (reads the ln1_*/qkv_*/proj_*
    keys; sp callers must have pre-wrapped the replicated-but-SP params,
    see _block_fn).

    flash: None (the registry scaled_dot_product_attention — composed
    einsum off-TPU, bitwise-unchanged legacy path) or a
    kernels.pallas.flash_training.FlashAttentionConfig: the fused flash
    fwd + custom_vjp bwd kernel wired DIRECTLY into the block (no
    registry hop), optionally with sep ring/Ulysses context parallelism
    over `sep_axis` (x then carries this rank's sequence shard)."""
    mp = lax.axis_size(mp_axis)
    heads_local = cfg.num_heads // mp
    B = x.shape[0]
    H = cfg.hidden_size
    from ..distributed.fleet.layers.mpu import mp_ops

    if sp is None:
        S = x.shape[1]
        h = _ln(x, p["ln1_g"], p["ln1_b"])
        hi = mp_ops.c_identity(h, mp_axis)
        qkv = (_fp8_mm(fp8, "qkv")(hi.astype(cfg.dtype),
                                   p["qkv_w"].astype(cfg.dtype))
               + p["qkv_b"].astype(cfg.dtype))  # [B, S, 3H/mp]
    else:
        S = x.shape[1] * mp  # x is this rank's sequence shard
        h = _ln(x, p["ln1_g"], p["ln1_b"])
        qkv = (mp_ops.ag_matmul(
            h.astype(cfg.dtype), p["qkv_w"].astype(cfg.dtype), mp_axis,
            ring=sp.ring,
            mm=None if fp8 is None else _fp8_mm(fp8, "qkv"))
            + p["qkv_b"].astype(cfg.dtype))  # [B, S, 3H/mp]
    qkv = qkv.reshape(B, S, heads_local, 3, cfg.head_dim)
    # heads are fully local under TP, so per-shard attention is the whole
    # computation (over the FULL sequence under sp — only the
    # between-block residual stream is seq-sharded there; over this
    # rank's sequence SHARD under a sep-mode flash plan)
    if flash is not None:
        # training-grade path: the fused kernel (interpreter mode on CPU
        # tier-1) wired directly, bypassing the registry hop — with
        # flash.sep, ring/Ulysses context parallelism over sep_axis
        from ..kernels.pallas import flash_training as _ft
        attn = _ft.attention(qkv[:, :, :, 0], qkv[:, :, :, 1],
                             qkv[:, :, :, 2], flash, sep_axis=sep_axis)
    else:
        # registry op: Pallas flash on TPU (the engine's shard_map runs
        # with check_vma=False, so the kernel traces inside it); composed
        # O(S^2) fallback elsewhere
        attn = F.scaled_dot_product_attention(
            qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2],
            is_causal=True)
    attn = attn.reshape(B, S, H // mp)
    if sp is None:
        out = _fp8_mm(fp8, "proj")(attn, p["proj_w"].astype(cfg.dtype))
        out = (mp_ops.mp_allreduce(out, mp_axis)
               + p["proj_b"].astype(cfg.dtype))
    else:
        out = (mp_ops.matmul_rs(
            attn, p["proj_w"].astype(cfg.dtype), mp_axis, ring=sp.ring,
            mm=None if fp8 is None else _fp8_mm(fp8, "proj"))
            + p["proj_b"].astype(cfg.dtype))
    return x + out


def _block_fn(p, x, cfg: GPTConfig, mp_axis: str = "mp", fp8=None, sp=None,
              flash=None, sep_axis=None):
    """One transformer block, explicit Megatron TP (runs inside shard_map;
    degenerates correctly at mp degree 1).

    QKV channel layout is HEAD-MAJOR: [H, heads * 3 * head_dim], so a
    contiguous column shard over 'mp' holds COMPLETE heads (each with its
    q, k and v) — a [H, 3H] q|k|v-major packing would split heads across
    ranks and silently corrupt attention under TP.

    fp8: this layer's {site: {x, w, g}} delayed scales (replicated over
    dp/mp) routing the four GEMMs through quantization.fp8.fp8_dot; each
    rank quantizes its LOCAL weight shard with the shared per-tensor
    scale, and the engine pmaxes the observed amaxes over dp/mp before
    the meta update.

    sp: None (plain TP: replicated activations, c_identity/mp_allreduce
    pairs — bitwise-unchanged legacy path) or a
    comm_overlap.MpOverlapConfig. With sp on, x arrives SEQUENCE-SHARDED
    [B, S/mp, H]: each pair becomes ag_matmul / matmul_rs (all_gather on
    the way into the column GEMM, reduce-scatter on the way out of the
    row GEMM — same wire bytes, 1/mp the LayerNorm/residual math and
    saved between-block activations), and sp.ring additionally decomposes
    those collectives into ppermute rings interleaved with the GEMM
    partial products (collective matmul; fp8 must be off — per-chunk
    fp8_dot calls would sum partial amax observations).

    flash/sep_axis: see _attn_sublayer — the attention implementation is
    the ONLY thing they change; every TP/sp collective stays as-is."""
    mp = lax.axis_size(mp_axis)
    from ..distributed.fleet.layers.mpu import mp_ops

    if sp is not None:
        # replicated-but-sequence-parallel params (the reference's
        # mark_as_sequence_parallel_parameter allreduce hook,
        # sequence_parallel_utils.py:192): LayerNorm weights and the
        # row-GEMM biases see only this rank's seq shard, so their local
        # grads are PARTIAL — identity-fwd/psum-bwd (c_identity) restores
        # the full-sequence gradient. mp-sharded leaves (qkv/fc1 weights
        # and biases, proj/fc2 weights) never need this: their grads come
        # from the gathered full-sequence activations.
        p = dict(p)
        for k in ("ln1_g", "ln1_b", "ln2_g", "ln2_b", "proj_b", "fc2_b"):
            p[k] = mp_ops.c_identity(p[k], mp_axis)
    x = _attn_sublayer(p, x, cfg, mp_axis, fp8=fp8, sp=sp, flash=flash,
                       sep_axis=sep_axis)

    h = _ln(x, p["ln2_g"], p["ln2_b"])
    if sp is None:
        hi = mp_ops.c_identity(h, mp_axis)
        m = (_fp8_mm(fp8, "fc1")(hi.astype(cfg.dtype),
                                 p["fc1_w"].astype(cfg.dtype))
             + p["fc1_b"].astype(cfg.dtype))
    else:
        m = (mp_ops.ag_matmul(
            h.astype(cfg.dtype), p["fc1_w"].astype(cfg.dtype), mp_axis,
            ring=sp.ring,
            mm=None if fp8 is None else _fp8_mm(fp8, "fc1"))
            + p["fc1_b"].astype(cfg.dtype))
    m = jax.nn.gelu(m.astype(jnp.float32), approximate=True).astype(cfg.dtype)
    if sp is None:
        m = _fp8_mm(fp8, "fc2")(m, p["fc2_w"].astype(cfg.dtype))
        m = mp_ops.mp_allreduce(m, mp_axis) + p["fc2_b"].astype(cfg.dtype)
    else:
        m = (mp_ops.matmul_rs(
            m, p["fc2_w"].astype(cfg.dtype), mp_axis, ring=sp.ring,
            mm=None if fp8 is None else _fp8_mm(fp8, "fc2"))
            + p["fc2_b"].astype(cfg.dtype))
    return x + m


def _moe_block_fn(p, x, cfg: GPTConfig, mp_axis: str = "mp",
                  ep_axis: str = "ep", mcfg=None, ef=None, flash=None):
    """One MoE transformer block of the hybrid path: the shared TP
    attention sublayer, then a switch-routed (top-1, capacity-bounded)
    expert FFN dispatched over the 'ep' mesh axis.

    Routing runs in fp32 on the LOCAL token shard (the gate is replicated
    over ep/mp, so every rank derives identical slot math for its own
    tokens); the routed [E, C, D] buffer crosses the ep axis through
    comm_overlap.a2a.expert_exchange — plain all-to-alls by default,
    index dispatch / int8-EF wire / chunked overlap per `mcfg`
    (MoeDispatchConfig). `ef` is this layer's {"disp", "comb"} residual
    slice when the exchange is quantized.

    Returns (x_out, stats, new_ef): stats = {"aux": switch load-balance
    loss E*sum(me*ce), "tokens": routed tokens per expert [E] (pre-drop),
    "kept": tokens that won a capacity slot} — per (layer, microbatch)
    execution, summed by the callers."""
    from ..incubate.distributed.models.moe.gate import (
        _capacity_dispatch, _capacity_dispatch_idx, _one_hot,
        compute_capacity)
    from ..incubate.distributed.models.moe.moe_layer import (
        _index_combine, _index_scatter)
    from ..distributed.comm_overlap import a2a as _a2a

    x = _attn_sublayer(p, x, cfg, mp_axis, flash=flash)
    h = _ln(x, p["ln2_g"], p["ln2_b"])
    B, S, H = h.shape
    T = B * S
    E = cfg.moe_num_experts
    xt = h.reshape(T, H).astype(cfg.dtype)
    # route in fp32 (the BaseGate.logits discipline: softmax/argmax
    # numerics matter more than MXU speed on a [T, E] matmul)
    logits = xt.astype(jnp.float32) @ p["gate_w"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_val = probs.max(axis=-1)
    expert = probs.argmax(axis=-1)
    me = probs.mean(axis=0)
    ce = _one_hot(expert, E).mean(axis=0)
    aux = jnp.sum(me * ce) * E  # Switch-Transformer load-balance loss
    C = compute_capacity(T, E, 1, cfg.moe_capacity_factor)
    index = mcfg is not None and mcfg.index
    if index:
        # zero-flop slot-id dispatch (the reference's CUDA global_scatter
        # analogue) — saves the 2*T*E*C*D one-hot einsum each way
        slot, gates, counts = _capacity_dispatch_idx(expert, gate_val, C, E)
        kept = jnp.sum((slot >= 0).astype(jnp.float32))
        dispatched, slot_safe = _index_scatter(xt, slot[:, None], E, C)
    else:
        combine, keep_tok, counts = _capacity_dispatch(expert, gate_val,
                                                       C, E)
        kept = jnp.sum(keep_tok.astype(jnp.float32))
        dispatched = jnp.einsum("tec,td->ecd",
                                (combine > 0).astype(xt.dtype), xt)

    def act(m):
        return jax.nn.gelu(m.astype(jnp.float32),
                           approximate=True).astype(cfg.dtype)

    returned, new_ef = _a2a.expert_exchange(
        dispatched, p["w1"].astype(cfg.dtype), p["b1"].astype(cfg.dtype),
        p["w2"].astype(cfg.dtype), p["b2"].astype(cfg.dtype),
        ep_axis=ep_axis, mp_axis=mp_axis, activation=act, cfg=mcfg,
        residuals=ef)
    if index:
        y = _index_combine(returned, gates[:, None], slot_safe)
    else:
        y = jnp.einsum("tec,ecd->td", combine.astype(returned.dtype),
                       returned)
    stats = {"aux": aux, "tokens": counts.astype(jnp.float32),
             "kept": kept}
    return x + y.reshape(B, S, H).astype(x.dtype), stats, new_ef


def _vocab_parallel_embed(wte_local, tokens, mp_axis: str = "mp"):
    from ..distributed.fleet.layers.mpu import mp_ops
    idx = lax.axis_index(mp_axis)
    per = wte_local.shape[0]
    local = tokens - idx * per
    ok = (local >= 0) & (local < per)
    safe = jnp.where(ok, local, 0)
    out = jnp.take(wte_local, safe, axis=0)
    out = jnp.where(ok[..., None], out, 0.0)
    # psum fwd / identity bwd: downstream is replicated across mp, so a raw
    # psum would deliver mp-times the cotangent to the local shard
    return mp_ops.mp_allreduce(out, mp_axis)


def _vocab_parallel_ce(logits_local, labels, mp_axis: str = "mp",
                       ignore_index: int = -100):
    """Stable vocab-sharded softmax CE; returns per-token loss."""
    mp_idx = lax.axis_index(mp_axis)
    per = logits_local.shape[-1]
    lf = logits_local.astype(jnp.float32)
    # max-shift is for stability only; its gradient cancels, and pmax has no
    # differentiation rule — stop_gradient is exact here
    from ..distributed.fleet.layers.mpu import mp_ops
    lmax = lax.pmax(lax.stop_gradient(jnp.max(lf, -1, keepdims=True)),
                    mp_axis)
    shifted = lf - lmax
    # mp_allreduce (identity bwd) — see _vocab_parallel_embed
    lse = jnp.log(mp_ops.mp_allreduce(
        jnp.sum(jnp.exp(shifted), -1, keepdims=True), mp_axis)) + lmax
    local_label = labels - mp_idx * per
    ok = (local_label >= 0) & (local_label < per)
    safe = jnp.where(ok, local_label, 0)
    picked = jnp.take_along_axis(lf, safe[..., None], axis=-1)
    picked = mp_ops.mp_allreduce(jnp.where(ok[..., None], picked, 0.0), mp_axis)
    loss = (lse - picked)[..., 0]
    valid = labels != ignore_index
    return jnp.where(valid, loss, 0.0), valid


def dense_embed(params, tokens, cfg: GPTConfig):
    """Token+position embedding over the embed sub-tree {wte, wpe}."""
    x = jnp.take(params["wte"], tokens, axis=0) + params["wpe"][None, :tokens.shape[1]]
    return x.astype(cfg.dtype)


def dense_block(p, x, cfg: GPTConfig, fp8=None, flash=None):
    """One transformer block on an UNstacked per-layer param tree — shared
    by the scan in dense_forward and the param-streaming trainer. fp8:
    this layer's {site: {x, w, g}} delayed scales — the qkv/proj/fc1/fc2
    GEMMs route through quantization.fp8.fp8_dot (None = plain bf16/f32
    path, bitwise-unchanged). flash: None or a FlashAttentionConfig —
    the fused kernel instead of the registry attention (sep does not
    apply to the single-device dense path)."""
    from jax.ad_checkpoint import checkpoint_name
    B, S, H = x.shape
    h = _ln(x, p["ln1_g"], p["ln1_b"])
    qkv = (_fp8_mm(fp8, "qkv")(h.astype(cfg.dtype),
                               p["qkv_w"].astype(cfg.dtype))
           + p["qkv_b"].astype(cfg.dtype))
    # checkpoint_name tags are inert under plain jax.checkpoint; the
    # selective remat policy (dense_forward remat_save=) keys on them
    qkv = checkpoint_name(qkv, "qkv")
    qkv = qkv.reshape(B, S, cfg.num_heads, 3, cfg.head_dim)
    if flash is not None:
        # direct fused path; its (out, lse) residuals carry the
        # FLASH_REMAT_NAMES tags, so selective remat reuses the flash
        # forward instead of re-running the kernel
        from ..kernels.pallas import flash_training as _ft
        attn = _ft.attention(qkv[:, :, :, 0], qkv[:, :, :, 1],
                             qkv[:, :, :, 2], flash)
    else:
        # registry op: Pallas flash kernel on TPU (O(S) VMEM), XLA
        # composition elsewhere — same math as the hybrid engine's
        attn = F.scaled_dot_product_attention(
            qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2],
            is_causal=True)
    attn = checkpoint_name(attn, "attn_out")
    out = _fp8_mm(fp8, "proj")(attn.reshape(B, S, H),
                               p["proj_w"].astype(cfg.dtype))
    x = x + out + p["proj_b"].astype(cfg.dtype)
    h = _ln(x, p["ln2_g"], p["ln2_b"])
    m = (_fp8_mm(fp8, "fc1")(h.astype(cfg.dtype),
                             p["fc1_w"].astype(cfg.dtype))
         + p["fc1_b"].astype(cfg.dtype))
    m = checkpoint_name(m, "fc1")
    m = jax.nn.gelu(m.astype(jnp.float32), approximate=True).astype(cfg.dtype)
    return (x + _fp8_mm(fp8, "fc2")(m, p["fc2_w"].astype(cfg.dtype))
            + p["fc2_b"].astype(cfg.dtype))


def lm_logsumexp_ce(logits, labels):
    """Mean next-token CE in logsumexp+gather form, shared by the GPT and
    Llama dense losses. Logits stay in their compute dtype (bf16 on TPU)
    in HBM — the f32 convert fuses into the reduction, so the V-length
    accumulation is fp32 without a whole-tensor [B, S, V] fp32 copy (the
    largest write of the step) ever materializing; no [B, S, V]
    log_softmax either."""
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None],
                                 axis=-1)[..., 0].astype(jnp.float32)
    return jnp.mean(lse - picked)


def dense_head_loss(params, x, labels, cfg: GPTConfig):
    """Final LN + LM head + logsumexp CE over the head sub-tree
    {lnf_g, lnf_b, head_w}. Identical math to dense_loss's tail."""
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    logits = x.astype(cfg.dtype) @ params["head_w"].astype(cfg.dtype)
    return lm_logsumexp_ce(logits, labels)


def dense_forward(params, tokens, cfg: GPTConfig, remat: bool = True,
                  remat_save=("attn_out", "qkv"), fp8=None, flash=None):
    """Single-device forward over the stacked-parameter pytree (no
    collectives). Same math/layout as the hybrid engine — head-major QKV.
    remat=True checkpoints each block (recompute in backward) — the memory/
    FLOPs trade that keeps long-sequence training inside HBM.
    remat_save: checkpoint_name'd intermediates kept instead of recomputed
    (see dense_block tags). Saving attn_out+qkv measured fastest on the
    1.3B flagship (578.6 vs 600.7 ms/step full-remat, one v5e, round 4:
    skips recomputing the qkv projection and the flash forward at
    ~128 MB/layer of saved activations); pass remat_save=() for the
    minimum-memory full-remat form (bigger-than-HBM configs).

    fp8: per-layer delayed scales, stacked [L] like the block params (see
    quantization.fp8.init_fp8_meta) — they ride the same scan, so each
    layer's amax observation comes back separately instead of summed. The
    selective-remat policy additionally saves the quantized operands
    (FP8_REMAT_NAMES) so backward reuses them instead of re-quantizing.

    flash: None or a FlashAttentionConfig — the fused attention kernel in
    every block; selective remat then also saves the kernel's (out, lse)
    residuals (FLASH_REMAT_NAMES) so the backward reuses the flash
    forward, while full remat (remat_save=()) replays the KERNEL."""
    x = dense_embed(params, tokens, cfg)

    def block(p, x, f=None):
        return dense_block(p, x, cfg, fp8=f, flash=flash)

    if remat and remat_save:
        if fp8 is not None:
            from ..quantization.fp8 import FP8_REMAT_NAMES
            remat_save = tuple(remat_save) + tuple(FP8_REMAT_NAMES)
        if flash is not None:
            from ..kernels.pallas.flash_attention import FLASH_REMAT_NAMES
            # "attn_out" is a pure reshape of the kernel's "flash_out"
            # residual — saving both would store the attention output
            # twice per block and erode the O(S) win
            remat_save = tuple(n for n in remat_save
                               if n != "attn_out") + tuple(FLASH_REMAT_NAMES)
        blk = jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.save_only_these_names(
                *remat_save))
    elif remat:
        blk = jax.checkpoint(block)
    else:
        blk = block

    if fp8 is not None:
        def body(carry, pf):
            p, f = pf
            return blk(p, carry, f), None
        x, _ = lax.scan(body, x, (params["blocks"], fp8))
    else:
        def body(carry, p):
            return blk(p, carry), None
        x, _ = lax.scan(body, x, params["blocks"])
    x = _ln(x, params["lnf_g"], params["lnf_b"])
    return x.astype(cfg.dtype) @ params["head_w"].astype(cfg.dtype)


def dense_loss(params, tokens, labels, cfg: GPTConfig, remat: bool = True,
               remat_save=("attn_out", "qkv"), fp8=None, flash=None):
    """remat_save threads through to dense_forward — bigger-than-HBM
    callers (benchmarks/offload_bench.py moments tier) pass () for the
    minimum-memory full-remat form. fp8: per-layer delayed scales; flash:
    fused-attention plan (see dense_forward)."""
    logits = dense_forward(params, tokens, cfg, remat=remat,
                           remat_save=remat_save, fp8=fp8, flash=flash)
    return lm_logsumexp_ce(logits, labels)


# ---------------------------------------------------------------------------
# Param-streaming (bigger-than-HBM) form: segmented params
# ---------------------------------------------------------------------------
def split_streamed_params(params, cfg: GPTConfig):
    """Stacked hybrid tree → segmented {embed, blocks: [per-layer], head}
    layout for the param-streaming trainer (small models / tests — a
    bigger-than-HBM model must use init_streamed_params instead, which
    never materializes the whole tree on device)."""
    blocks = [jax.tree.map(lambda a: a[i], params["blocks"])
              for i in range(cfg.num_layers)]
    return {
        "embed": {"wte": params["wte"], "wpe": params["wpe"]},
        "blocks": blocks,
        "head": {"lnf_g": params["lnf_g"], "lnf_b": params["lnf_b"],
                 "head_w": params["head_w"]},
    }


def init_streamed_params(cfg: GPTConfig, key, park=lambda t: t):
    """Segmented init that materializes ONE segment on device at a time,
    parking each through `park` (pinned_host placement) before the next is
    generated — a whole-tree init of a 6.7B model would OOM HBM before the
    first step ran. Same distributions as init_hybrid_params."""
    H, L, FF, V = (cfg.hidden_size, cfg.num_layers, cfg.ffn_hidden,
                   cfg.vocab_size)
    std, pd = 0.02, cfg.param_dtype
    k_embed, k_head, *k_blocks = jax.random.split(key, 2 + L)

    def nrm(key, shape, scale=std):
        return (scale * jax.random.normal(key, shape)).astype(pd)

    @jax.jit
    def one_block(key):
        ks = jax.random.split(key, 4)
        return {
            "ln1_g": jnp.ones((H,), pd), "ln1_b": jnp.zeros((H,), pd),
            "qkv_w": nrm(ks[0], (H, 3 * H)), "qkv_b": jnp.zeros((3 * H,), pd),
            "proj_w": nrm(ks[1], (H, H), std / math.sqrt(2 * L)),
            "proj_b": jnp.zeros((H,), pd),
            "ln2_g": jnp.ones((H,), pd), "ln2_b": jnp.zeros((H,), pd),
            "fc1_w": nrm(ks[2], (H, FF)), "fc1_b": jnp.zeros((FF,), pd),
            "fc2_w": nrm(ks[3], (FF, H), std / math.sqrt(2 * L)),
            "fc2_b": jnp.zeros((H,), pd),
        }

    @jax.jit
    def embed_init(key):
        k1, k2 = jax.random.split(key)
        return {"wte": nrm(k1, (V, H)), "wpe": nrm(k2, (cfg.max_seq_len, H))}

    @jax.jit
    def head_init(key):
        return {"lnf_g": jnp.ones((H,), pd), "lnf_b": jnp.zeros((H,), pd),
                "head_w": nrm(key, (H, V))}

    return {
        "embed": park(embed_init(k_embed)),
        "blocks": [park(one_block(k)) for k in k_blocks],
        "head": park(head_init(k_head)),
    }


def streamed_fns(cfg: GPTConfig):
    """(embed_fn, block_fn, head_loss_fn) for
    build_param_streamed_train_step — the same math as dense_loss."""
    return (lambda p, tokens: dense_embed(p, tokens, cfg),
            lambda p, x: dense_block(p, x, cfg),
            lambda p, x, labels: dense_head_loss(p, x, labels, cfg))


def _note_mp_wire(cfg, tokens, sp, mp_axis, pp_axis, num_microbatches,
                  n_block_layers, virtual_pp=1):
    """Deposit the analytic per-step mp wire bytes (trace-time constant)
    for the telemetry comms_bytes series — one shared accounting for the
    gpt and llama hybrid losses (both have 2 column/row GEMM pairs per
    block: attention + MLP). See observability.metrics.mp_wire_bytes for
    the per-term cost model.

    Executed-block count per schedule (every pipeline tick executes the
    stage body on every rank, bubbles included — those collectives move
    real bytes): 1F1B runs M+P-1 ticks of all L/P local layers; the
    interleaved schedule runs V*M+P-1 ticks of ONE L/(P*V)-layer chunk.
    ZBH1's forward matches 1F1B and its split backward is approximated
    by the same fwd+bwd pair model."""
    from ..observability import metrics as _metrics
    mp = lax.axis_size(mp_axis)
    P_ = lax.axis_size(pp_axis)
    b_local, S = tokens.shape
    dt = jnp.dtype(cfg.dtype).itemsize
    a_blk = (b_local // num_microbatches) * S * cfg.hidden_size * dt
    a_full = b_local * S * cfg.hidden_size * dt
    V = max(int(virtual_pp), 1)
    executed = (V * num_microbatches + P_ - 1) * (n_block_layers / V)
    mode = "allreduce" if sp is None else sp.mode
    _metrics.note_mp_comm(mode, _metrics.mp_wire_bytes(
        mode, mp,
        gemm_pair_bytes=2.0 * executed * a_blk,
        # embed psum + head boundary + the 4 CE reductions ([b, S, 1] f32)
        allreduce_bytes=2.0 * a_full + 4.0 * b_local * S * 4,
        scatter_bytes=a_full))


def _note_zero3_wire(z3, params, pp_axis, num_microbatches: int,
                     virtual_pp: int = 1):
    """Deposit the analytic per-step ZeRO-3 param-gather wire bytes
    (trace-time constant) for the telemetry comms_bytes series — one
    shared accounting for the gpt and llama hybrid losses. Must run on
    the ORIGINAL (dp-sharded) param leaves: local size x dp is each
    leaf's full-over-dp byte count. See
    observability.metrics.zero3_ag_wire_bytes for the cost model."""
    from ..observability import metrics as _metrics
    zax = z3["axis"]
    dp = lax.axis_size(zax)
    P_ = lax.axis_size(pp_axis)
    V = max(int(virtual_pp), 1)
    zd_blk = jax.tree.leaves(z3["zdims"]["blocks"])
    blk = sum(float(p.size) * dp * jnp.dtype(p.dtype).itemsize
              for p, zd in zip(jax.tree.leaves(params["blocks"]), zd_blk)
              if zd >= 0) / V  # one V-chunk's layers gather per tick
    other = sum(float(params[k].size) * dp
                * jnp.dtype(params[k].dtype).itemsize
                for k in z3["other_leaves"] if z3["zdims"][k] >= 0)
    p0 = jax.tree.leaves(params["blocks"])[0]
    _metrics.note_zero3_comm(_metrics.zero3_ag_wire_bytes(
        dp, block_param_bytes=blk,
        n_stage_executions=float(V * num_microbatches + P_ - 1),
        other_param_bytes=other, quantize=z3["cfg"].quantize,
        param_itemsize=jnp.dtype(p0.dtype).itemsize))


def _act_stats(x):
    """Per-layer activation health of one block output (trace-time, fp32):
    mean-square (rms after the host sqrt) and absmax — the numerics
    deposit each scan body makes when the plan's `act` is on.
    stop_gradient at the source: the stats are diagnostics riding the
    aux channel, and the downstream pmax has no differentiation rule."""
    xf = lax.stop_gradient(x).astype(jnp.float32)
    return {"sq": jnp.mean(xf * xf), "am": jnp.max(jnp.abs(xf))}


def _scatter_layer_stats(ys, pp_axis):
    """This pp rank's stacked per-layer stats [L_local] scattered into
    the GLOBAL layer vector [L_local x pp] at the rank's slice (zeros
    elsewhere) — the pipeline aux channel's psum over pp then assembles
    the full vector with no overlap. Shared by the gpt and llama hybrid
    losses."""
    L_loc = int(ys["sq"].shape[0])
    Lg = L_loc * lax.axis_size(pp_axis)
    pos = lax.axis_index(pp_axis) * L_loc
    return jax.tree.map(
        lambda v: lax.dynamic_update_slice(
            jnp.zeros((Lg,), jnp.float32), v.astype(jnp.float32), (pos,)),
        ys)


def _pack_num_aux(out, ys, num_act, pp_axis, extra=None):
    """ONE copy of the stage-aux packaging every scan branch shares
    (gpt + llama): the per-layer activation stats when the numerics
    plan asks, merged next to any existing side-channel entries (the
    z3ef residuals). Plain `out` when there is no aux — the pipeline
    is then called without with_aux and the program is
    bitwise-unchanged."""
    if extra is None and not num_act:
        return out
    aux = dict(extra or {})
    if num_act:
        aux["num"] = _scatter_layer_stats(ys, pp_axis)
    return out, aux


def _deposit_act_stats(aux, M: int, axes):
    """Observe the per-layer activation series from the pipeline aux
    (summed over the M valid ticks — /M is the mean over microbatches;
    rms additionally pmeans and absmax pmaxes over the data axes so the
    replicated telemetry row is rank-identical). Shared gpt/llama."""
    from ..observability import metrics as _metrics
    sq = aux["sq"] / float(M)
    am = aux["am"] / float(M)
    if axes:
        sq = lax.pmean(sq, axes)
        am = lax.pmax(am, axes)
    for i in range(int(sq.shape[0])):
        _metrics.observe(f"num_act_rms_l{i}", jnp.sqrt(sq[i]))
        _metrics.observe(f"num_act_absmax_l{i}", am[i])


def _moe_pipeline(params, x_mb, cfg: GPTConfig, M: int, pp_axis, mp_axis,
                  ep_axis, mcfg, moe_ef, flash=None, z3=None):
    """1F1B pipeline over (dense, MoE) layer pairs with the aux side
    channel (spmd_pipeline with_aux): returns (out [M, mb, s, H], stats
    summed over every (layer, microbatch) execution and psum'd over pp,
    new flat moe_ef residuals or None). z3: ZeRO-3 plan — the pair scan
    gathers each (dense, MoE) layer pair's dp-sharded leaves on use
    (comm_overlap.zero3.scan_gather; the expert bank included — its ep/mp
    shardings keep their axes, dp is gathered away just like any other
    leaf)."""
    dense_p = params["blocks"]["dense"]
    moe_p = params["blocks"]["moe"]
    l2_local = jax.tree.leaves(dense_p)[0].shape[0]
    ef_p = None
    if moe_ef is not None:
        from ..distributed.comm_overlap import a2a as _a2a
        from ..incubate.distributed.models.moe.gate import compute_capacity
        T = x_mb.shape[1] * x_mb.shape[2]
        ep = lax.axis_size(ep_axis)
        C = compute_capacity(T, cfg.moe_num_experts, 1,
                             cfg.moe_capacity_factor)
        chunks = mcfg.chunks if (mcfg is not None and mcfg.overlap) else 1
        shapes = _a2a.moe_ef_local_shapes(cfg.moe_num_experts, C,
                                          cfg.hidden_size, ep, chunks)
        ef_p = {}
        for key, shp in shapes.items():
            want = l2_local * math.prod(shp)
            enforce(moe_ef[key].size == want,
                    "moe_ef residual size mismatch: the quantized-a2a "
                    "residuals were sized at build time from "
                    "moe_ef_tokens — pass the ACTUAL per-rank "
                    "(batch, seq) of the training data",
                    op="gpt.hybrid_loss_fn", leaf=key,
                    have=int(moe_ef[key].size), want=int(want))
            ef_p[key] = moe_ef[key].reshape((l2_local,) + shp)

    def stage_fn(bp, h):
        if moe_ef is not None:
            pd, pm, efl = bp

            if z3 is not None:
                from ..distributed.comm_overlap import zero3 as _z3g

                def pair_fn(p_full, carry, efll):
                    pdl, pml = p_full
                    hh = _block_fn(pdl, carry, cfg, mp_axis, flash=flash)
                    hh, st, nef = _moe_block_fn(pml, hh, cfg, mp_axis,
                                                ep_axis, mcfg, efll,
                                                flash=flash)
                    return hh, (st, nef)
                out, (st, nef), _ = _z3g.scan_gather(
                    pair_fn, h, (pd, pm),
                    (z3["zdims"]["blocks"]["dense"],
                     z3["zdims"]["blocks"]["moe"]),
                    z3["axis"], extras=(efl,), cfg=z3["cfg"])
            else:
                def body(carry, xs):
                    pdl, pml, efll = xs
                    hh = _block_fn(pdl, carry, cfg, mp_axis, flash=flash)
                    hh, st, nef = _moe_block_fn(pml, hh, cfg, mp_axis,
                                                ep_axis, mcfg, efll,
                                                flash=flash)
                    return hh, (st, nef)
                out, (st, nef) = lax.scan(body, h, (pd, pm, efl))
        else:
            pd, pm = bp

            if z3 is not None:
                from ..distributed.comm_overlap import zero3 as _z3g

                def pair_fn(p_full, carry):
                    pdl, pml = p_full
                    hh = _block_fn(pdl, carry, cfg, mp_axis, flash=flash)
                    hh, st, _ = _moe_block_fn(pml, hh, cfg, mp_axis,
                                              ep_axis, mcfg, None,
                                              flash=flash)
                    return hh, st
                out, st, _ = _z3g.scan_gather(
                    pair_fn, h, (pd, pm),
                    (z3["zdims"]["blocks"]["dense"],
                     z3["zdims"]["blocks"]["moe"]),
                    z3["axis"], cfg=z3["cfg"])
            else:
                def body(carry, xs):
                    pdl, pml = xs
                    hh = _block_fn(pdl, carry, cfg, mp_axis, flash=flash)
                    hh, st, _ = _moe_block_fn(pml, hh, cfg, mp_axis,
                                              ep_axis, mcfg, None,
                                              flash=flash)
                    return hh, st
                out, st = lax.scan(body, h, (pd, pm))
            nef = ()
        return out, {"stats": jax.tree.map(lambda a: a.sum(axis=0), st),
                     "ef": nef}

    stage_args = ((dense_p, moe_p) if moe_ef is None
                  else (dense_p, moe_p, ef_p))
    out, aux = spmd_pipeline(stage_fn, stage_args, x_mb, axis=pp_axis,
                             with_aux=True)
    new_ef = None
    if moe_ef is not None:
        new_ef = {k: v.reshape(-1) for k, v in aux["ef"].items()}
    return out, aux["stats"], new_ef


def _note_moe_wire(cfg: GPTConfig, tokens, mp_axis, pp_axis, ep_axis,
                   num_microbatches: int, n_pairs_local: int, mcfg):
    """Analytic per-step wire deposits for the GPT-MoE hybrid loss
    (trace-time constants): the mp term — a (dense, MoE) pair costs the
    dense layer's 2 column/row GEMM pairs plus the MoE attention's 1,
    and the expert FFN's forward-only mp all-reduce of the arrived
    [E, C, D] buffer — via note_mp_comm, and the ep dispatch/combine
    all-to-all term via note_ep_comm. The telemetry tests re-derive both
    independently (the PR 5 pattern)."""
    from ..incubate.distributed.models.moe.gate import compute_capacity
    from ..observability import metrics as _metrics
    mp = lax.axis_size(mp_axis)
    P_ = lax.axis_size(pp_axis)
    ep = lax.axis_size(ep_axis)
    b_local, S = tokens.shape
    M = num_microbatches
    dt = jnp.dtype(cfg.dtype).itemsize
    H, E = cfg.hidden_size, cfg.moe_num_experts
    C = compute_capacity((b_local // M) * S, E, 1, cfg.moe_capacity_factor)
    a_blk = (b_local // M) * S * H * dt
    a_full = b_local * S * H * dt
    executed = (M + P_ - 1) * n_pairs_local
    _metrics.note_mp_comm("allreduce", _metrics.mp_wire_bytes(
        "allreduce", mp,
        gemm_pair_bytes=3.0 * executed * a_blk,
        allreduce_bytes=(2.0 * a_full + 4.0 * b_local * S * 4
                         + executed * float(E * C * H * dt))))
    _metrics.note_ep_comm(_metrics.ep_a2a_wire_bytes(
        ep, payload_elems=float(E * C * H),
        n_layer_executions=float(executed), itemsize=dt,
        quantize=bool(mcfg is not None and mcfg.quantize)))


def hybrid_loss_fn(params, tokens, labels, cfg: GPTConfig,
                   num_microbatches: int, dp_axis="dp", pp_axis="pp",
                   mp_axis="mp", virtual_pp: int = 1,
                   schedule: str = "1F1B", fp8=None, sp=None,
                   ep_axis="ep", moe=None, moe_ef=None, flash=None,
                   sep_axis="sep", z3=None, z3_ef=None, num=None):
    """Per-device loss of the full hybrid GPT (runs inside shard_map).

    tokens/labels: this dp shard's batch [b_local, S]. virtual_pp > 1 runs
    the interleaved schedule (blocks must be stacked in
    vpp_block_permutation order — build_hybrid_train_step does this).
    schedule="ZBH1" selects the zero-bubble pipeline
    (PipelineZeroBubblePass / spmd_pipeline_zero_bubble).
    fp8: this pp rank's stacked [L/pp] delayed scales (sharded over pp
    like the block params); 1F1B schedule only — the interleaved/ZB
    permutations would need the same block reorder applied to the scales.
    sp: None (plain TP, bitwise-unchanged) or comm_overlap.MpOverlapConfig
    — sequence-parallel TP: activations between blocks (and through the
    pp ppermutes, whose transfers shrink mp-fold too) are seq-sharded
    over mp; the LM head becomes an ag_matmul and the embedding output is
    seq-scattered. Requires S % mp == 0.

    GPT-MoE (cfg.moe_num_experts > 0): the batch is sharded over dp AND
    ep (b_local is the per-(dp, ep)-rank shard), every second layer runs
    the expert FFN over the ep axis, the switch load-balance loss rides
    the pipeline's aux channel (spmd_pipeline with_aux) weighted by
    cfg.moe_aux_weight, and the final loss pmean spans (dp, ep). moe: a
    comm_overlap.MoeDispatchConfig (or None for the dense-dispatch
    baseline); moe_ef: this rank's flat {"disp", "comb"} int8
    error-feedback residuals when the exchange is quantized — the return
    value then becomes (loss, new_moe_ef). 1F1B only; not composed with
    fp8 or sequence parallelism (the MoE block runs the
    replicated-activation TP path).

    flash: None (composed-einsum attention, bitwise-unchanged) or a
    kernels.pallas.flash_training.FlashAttentionConfig — the fused flash
    kernel in every block. With flash.sep set, tokens/labels arrive
    SEQUENCE-SHARDED over `sep_axis` ([b_local, S/sep] per rank): the
    position embedding reads this rank's global slice, attention runs
    ring/Ulysses context parallelism per shard, and the loss mean spans
    (dp, sep). Not composed with sp (both shard the sequence dim) or
    MoE (enforced at build).

    z3: None (params arrive full per mp/pp shard — bitwise-unchanged) or
    the ZeRO-3 plan from build_hybrid_train_step ({"zdims": per-leaf dp
    shard dims, "axis": dp axis, "cfg": comm_overlap.zero3.Zero3Config,
    "other_leaves": the once-per-step leaf names}): every dp-shardable
    param leaf then arrives as this rank's 1/dp SHARD and is
    all-gathered ON USE — embeddings/head/final-LN once at their sites,
    the stacked block leaves per layer inside the stage scan
    (scan_gather: block i+1's gather issues beside block i's compute;
    the checkpointed stage bodies re-gather in the backward). z3_ef:
    this rank's stacked int8-EF residual tree when the block gathers are
    quantized — the return value then becomes (loss, new_z3_ef)
    (pp degree 1, one pipeline microbatch, enforced at build).

    num: None or an observability.numerics.NumericsConfig — with
    num.act the dense block scan additionally emits each layer's
    activation mean-square/absmax as scan ys, the pipeline aux channel
    assembles the global per-layer vectors (each pp rank scatters its
    slice; valid-tick masked, psum'd over pp), and the loss observes
    the ``num_act_rms_l<i>`` / ``num_act_absmax_l<i>`` telemetry series
    (mean over microbatches, pmean/pmax over the data axes so the
    replicated row is rank-identical). Plain-1F1B dense path only (the
    aux channel); per-layer GRAD norms are engine-side and cover every
    schedule. None is bitwise-unchanged.
    """
    b_local, S = tokens.shape
    M = num_microbatches
    enforce(b_local % M == 0,
            "per-dp-rank batch must be divisible by num_microbatches",
            op="gpt.hybrid_loss_fn", batch_local=b_local, microbatches=M)
    enforce(fp8 is None or (virtual_pp == 1 and schedule == "1F1B"),
            "fp8 delayed scaling supports the 1F1B schedule only",
            op="gpt.hybrid_loss_fn", virtual_pp=virtual_pp,
            schedule=schedule)
    moe_on = cfg.moe_on
    if moe_on:
        enforce(fp8 is None and sp is None,
                "the GPT-MoE hybrid path is not composed with fp8 delayed "
                "scaling or sequence parallelism (the MoE block runs the "
                "replicated-activation TP path)", op="gpt.hybrid_loss_fn")
        enforce(virtual_pp == 1 and schedule == "1F1B",
                "GPT-MoE supports the 1F1B schedule only (the aux channel "
                "and expert stacking follow the plain pipeline layout)",
                op="gpt.hybrid_loss_fn", virtual_pp=virtual_pp,
                schedule=schedule)
    sep_on = flash is not None and flash.sep is not None
    if sep_on:
        enforce(sp is None and not moe_on,
                "sep context parallelism shards the sequence dim — not "
                "composed with mp sequence parallelism (which also "
                "shards it) or the MoE batch layout",
                op="gpt.hybrid_loss_fn")
    from ..distributed.comm_overlap import collective_matmul as _cm
    if z3 is not None:
        from ..distributed.comm_overlap import zero3 as _z3g
        # analytic AG/RS wire deposit from the ORIGINAL (sharded) leaves
        _note_zero3_wire(z3, params, pp_axis, M, virtual_pp=virtual_pp)
        # once-per-step leaves gather at their (single) use sites: a
        # shallow copy swaps the shards for the gathered leaves so the
        # downstream code is byte-identical to the replicated path
        params = dict(params)
        for name in z3["other_leaves"]:
            zd_ = z3["zdims"][name]
            if zd_ >= 0:
                params[name] = _z3g.all_gather_param(params[name], zd_,
                                                     z3["axis"])
    x = _vocab_parallel_embed(params["wte"], tokens, mp_axis)
    if sep_on:
        # tokens are this rank's sequence shard: position embedding reads
        # the rank's GLOBAL slice (causal masking inside ring/Ulysses
        # likewise uses global positions). The GLOBAL length must fit the
        # table — dynamic_slice CLAMPS an out-of-range start, so an
        # oversized sequence would silently hand later ranks the first
        # ranks' position rows instead of erroring
        n_sep = lax.axis_size(sep_axis)
        enforce(S * n_sep <= cfg.max_seq_len,
                "sep context parallelism: the global sequence "
                "(per-rank S x sep degree) must fit max_seq_len — the "
                "position table is sliced per rank",
                op="gpt.hybrid_loss_fn", seq_local=S, sep=n_sep,
                max_seq_len=cfg.max_seq_len)
        off = lax.axis_index(sep_axis) * S
        x = x + lax.dynamic_slice_in_dim(params["wpe"], off, S,
                                         axis=0)[None]
    else:
        x = x + params["wpe"][None, :S]
    x = x.astype(cfg.dtype)
    if sp is not None:
        enforce(S % lax.axis_size(mp_axis) == 0,
                "sequence parallelism needs S divisible by the mp degree",
                op="gpt.hybrid_loss_fn", seq=S,
                mp=lax.axis_size(mp_axis))
        x = _cm.scatter_seq(x, mp_axis, dim=1)  # [b_local, S/mp, H]
    x_mb = x.reshape(M, b_local // M, x.shape[1], cfg.hidden_size)

    moe_stats = None
    new_z3_ef = None
    num_aux = None
    num_act = num is not None and num.act
    if num_act:
        enforce(not moe_on and virtual_pp == 1 and schedule == "1F1B",
                "per-layer activation telemetry rides the plain 1F1B "
                "pipeline's aux channel (the builders disable num.act "
                "for MoE/ZBH1/VPP — per-layer grad norms stay on)",
                op="gpt.hybrid_loss_fn")
    if moe_on:
        out, moe_stats, new_moe_ef = _moe_pipeline(
            params, x_mb, cfg, M, pp_axis, mp_axis, ep_axis, moe, moe_ef,
            flash=flash, z3=z3)
    else:
        def _y(out):
            # per-layer scan output: activation health when the numerics
            # plan asks for it (None keeps the scan ys empty — bitwise)
            return _act_stats(out) if num_act else None

        def stage_fn(block_params, h):
            if fp8 is not None:
                blocks, scales = block_params
                if z3 is not None:
                    def blk_fn(p, c, f):
                        o = _block_fn(p, c, cfg, mp_axis, fp8=f,
                                      sp=sp, flash=flash,
                                      sep_axis=sep_axis)
                        return o, _y(o)
                    out, ys, _ = _z3g.scan_gather(
                        blk_fn, h, blocks, z3["zdims"]["blocks"],
                        z3["axis"], extras=(scales,), cfg=z3["cfg"])
                else:
                    def body(carry, pf):
                        p, f = pf
                        o = _block_fn(p, carry, cfg, mp_axis, fp8=f,
                                      sp=sp, flash=flash,
                                      sep_axis=sep_axis)
                        return o, _y(o)
                    out, ys = lax.scan(body, h, (blocks, scales))
                return _pack_num_aux(out, ys, num_act, pp_axis)

            if z3 is not None and z3_ef is not None:
                blocks, resid = block_params

                def blk_fn(p, c):
                    o = _block_fn(p, c, cfg, mp_axis, sp=sp,
                                  flash=flash, sep_axis=sep_axis)
                    return o, _y(o)
                out, ys, nres = _z3g.scan_gather(
                    blk_fn, h, blocks, z3["zdims"]["blocks"], z3["axis"],
                    cfg=z3["cfg"], residuals=resid)
                return _pack_num_aux(out, ys, num_act, pp_axis,
                                     extra={"z3ef": nres})

            if z3 is not None:
                def blk_fn(p, c):
                    o = _block_fn(p, c, cfg, mp_axis, sp=sp,
                                  flash=flash, sep_axis=sep_axis)
                    return o, _y(o)
                out, ys, _ = _z3g.scan_gather(
                    blk_fn, h, block_params, z3["zdims"]["blocks"],
                    z3["axis"], cfg=z3["cfg"])
                return _pack_num_aux(out, ys, num_act, pp_axis)

            def body(carry, p):
                o = _block_fn(p, carry, cfg, mp_axis, sp=sp,
                              flash=flash, sep_axis=sep_axis)
                return o, _y(o)
            out, ys = lax.scan(body, h, block_params)
            return _pack_num_aux(out, ys, num_act, pp_axis)

        stage_params = (params["blocks"] if fp8 is None
                        else (params["blocks"], fp8))
        if z3 is not None and z3_ef is not None:
            # quantized gathers: the refreshed EF residuals ride the
            # pipeline's aux side channel (pp degree 1 / one microbatch,
            # enforced at build — the single valid tick IS the step)
            out, aux = spmd_pipeline(stage_fn, (params["blocks"], z3_ef),
                                     x_mb, axis=pp_axis, with_aux=True)
            new_z3_ef = aux["z3ef"]
            num_aux = aux.get("num")
        elif virtual_pp > 1:
            out = spmd_pipeline_interleaved(
                stage_fn, vpp_chunk_blocks(params["blocks"], virtual_pp),
                x_mb, axis=pp_axis)
        elif schedule == "ZBH1":
            out = spmd_pipeline_zero_bubble(stage_fn, params["blocks"],
                                            x_mb, axis=pp_axis)
        elif num_act:
            # the activation stats ride the same valid-tick-masked aux
            # side channel the MoE routing stats use
            out, aux = spmd_pipeline(stage_fn, stage_params, x_mb,
                                     axis=pp_axis, with_aux=True)
            num_aux = aux["num"]
        else:
            out = spmd_pipeline(stage_fn, stage_params, x_mb, axis=pp_axis)
    out = out.reshape(b_local, x.shape[1], cfg.hidden_size)
    from ..distributed.fleet.layers.mpu import mp_ops
    lnf_g, lnf_b = params["lnf_g"], params["lnf_b"]
    if sp is not None:
        # final LN runs on the seq shard — its param grads are partial
        # (see the _block_fn sp note)
        lnf_g = mp_ops.c_identity(lnf_g, mp_axis)
        lnf_b = mp_ops.c_identity(lnf_b, mp_axis)
    out = _ln(out, lnf_g, lnf_b)
    if sp is None:
        # column-parallel head: identity fwd / allreduce bwd on its input
        out = mp_ops.c_identity(out, mp_axis)
        logits_local = (out.astype(cfg.dtype)
                        @ params["head_w"].astype(cfg.dtype))
    else:
        # seq-sharded final LN, then AG -> column GEMM (bwd RS) — same
        # wire as the allreduce-mode head boundary
        logits_local = mp_ops.ag_matmul(
            out.astype(cfg.dtype), params["head_w"].astype(cfg.dtype),
            mp_axis, ring=sp.ring)
    if moe_on:
        _note_moe_wire(cfg, tokens, mp_axis, pp_axis, ep_axis, M,
                       jax.tree.leaves(params["blocks"]["dense"])[0]
                       .shape[0], moe)
    else:
        _note_mp_wire(cfg, tokens, sp, mp_axis, pp_axis, M,
                      jax.tree.leaves(params["blocks"])[0].shape[0],
                      virtual_pp=virtual_pp)
    if num_aux is not None:
        # sp shards the sequence over mp (per-rank shards differ); plain
        # TP replicates the activations, so mp needs no reduction there
        _deposit_act_stats(num_aux, M,
                           (dp_axis,)
                           + ((mp_axis,) if sp is not None else ())
                           + ((sep_axis,) if sep_on else ()))
    loss, valid = _vocab_parallel_ce(logits_local, labels, mp_axis)
    total = jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
    if moe_on:
        from ..observability import metrics as _metrics
        L2 = cfg.num_layers // 2
        # aux summed over every (layer, microbatch) execution -> mean
        aux_mean = moe_stats["aux"] / float(L2 * M)
        total = total + jnp.float32(cfg.moe_aux_weight) * aux_mean
        routed = float(L2 * M) * float((b_local // M) * S)
        _metrics.observe("moe_drop_frac",
                         1.0 - moe_stats["kept"] / routed)
        if cfg.moe_num_experts <= 32:
            for i in range(cfg.moe_num_experts):
                _metrics.observe(f"moe_tokens_e{i}",
                                 moe_stats["tokens"][i])
        # the batch is sharded over dp AND ep — the loss mean spans both
        total = lax.pmean(total, (dp_axis, ep_axis))
        if moe_ef is not None:
            return total, new_moe_ef
        return total
    if sep_on:
        # sequence shards are equal-size (and every position valid), so
        # the mean of per-shard means IS the global mean; sep grads are
        # genuinely partial and combine through the engine's
        # extra_grad_axes pmean — the same convention as dp
        total = lax.pmean(total, (dp_axis, sep_axis))
    else:
        total = lax.pmean(total, dp_axis)
    if z3_ef is not None:
        return total, new_z3_ef
    return total


def moe_telemetry_series(cfg: GPTConfig):
    """Telemetry series the GPT-MoE hybrid loss observes: the
    capacity-drop fraction plus (for expert counts small enough to chart)
    one routed-tokens series per expert. build_hybrid_train_step
    registers these onto an explicitly-passed TelemetryConfig; flag-driven
    telemetry registers them via FLAGS_telemetry_extra."""
    if not cfg.moe_on:
        return ()
    series = ("moe_drop_frac",)
    if cfg.moe_num_experts <= 32:
        series += tuple(f"moe_tokens_e{i}"
                        for i in range(cfg.moe_num_experts))
    return series


def build_hybrid_train_step(cfg: GPTConfig, mesh: Mesh, optimizer,
                            num_microbatches: int = 1, dp_axis="dp",
                            pp_axis="pp", mp_axis="mp", extra_grad_axes=(),
                            virtual_pp: int = 1, schedule: str = "1F1B",
                            grad_reduce_dtype="auto",
                            zero1_dp: bool = False, zero_stage="auto",
                            zero3="auto", comm_overlap="auto",
                            fp8="auto", telemetry="auto",
                            mp_overlap="auto", ep_axis="ep",
                            moe_dispatch="auto", moe_ef_tokens=None,
                            flash_attention="auto", sep_axis="sep",
                            numerics="auto"):
    """Compile the full hybrid train step: one program containing embedding,
    pipelined blocks, vocab-parallel loss, backward, dp grad sync and the
    optimizer update. Returns (step_fn, shard_params_fn, init_state_fn).

    virtual_pp > 1 selects the interleaved schedule; shard_params then
    reorders the stacked blocks into the chunk-major layout (checkpoints
    saved from these sharded params are in that layout — reload through
    the same shard_params). schedule="ZBH1" selects the zero-bubble
    pipeline (what PipelineZeroBubblePass sets on a TrainSpec).

    comm_overlap: "auto" (flag-driven, default off) / None /
    CommOverlapConfig — replaces the monolithic end-of-backward dp pmean
    with bucketed, schedule-overlapped (optionally int8 error-feedback)
    collectives; see hybrid_engine.build_train_step. When the overlap
    scan accumulates over its own microbatches, the per-dp-rank batch
    must divide comm microbatches x pipeline num_microbatches.

    fp8: "auto" (FLAGS_fp8 / amp O3, default off) / bool — route the
    block GEMMs (GPT_FP8_SITES) through delayed-scaling fp8_dot; the
    (scale, amax_history) state rides opt_state["fp8_meta"], sharded
    over pp with the stacked blocks, and amaxes pmax over dp/mp (+extra
    axes) so scales stay replicated. 1F1B schedule only.

    mp_overlap: "auto" (FLAGS_mp_seq_parallel / FLAGS_mp_collective_
    matmul, default off) / None / mode string / MpOverlapConfig —
    sequence-parallel TP over the mp axis, optionally with the AG/RS
    boundaries decomposed into ppermute ring collective matmuls
    (distributed.comm_overlap.collective_matmul). Off: the allreduce
    path compiles BITWISE-identically to a build without the argument.
    collective_matmul composes with everything but fp8 (the ring's
    per-chunk GEMMs would sum partial amax observations — seq_parallel
    itself composes with fp8 fine: the site GEMMs see the gathered
    full-sequence input exactly as the allreduce path does).

    GPT-MoE (cfg.moe_num_experts > 0): the mesh must carry an `ep_axis`
    (degree >= 1, dividing the expert count); the batch shards over
    dp x ep and every second layer's FFN becomes the switch-routed
    expert bank exchanged over ep. moe_dispatch: "auto" reads
    FLAGS_moe_index_dispatch / FLAGS_moe_quantize_a2a / FLAGS_moe_overlap
    (all off = the dense-dispatch plain-exchange baseline, which
    compiles BITWISE-identically to an explicit moe_dispatch=None
    build); a comm_overlap.MoeDispatchConfig forces. The quantized
    exchange threads int8 error-feedback residuals through
    opt_state["moe_ef"] and needs moe_ef_tokens=(per-rank batch, seq)
    to size them at build time (pp degree 1, one pipeline microbatch).
    Not composed with fp8, sequence parallelism, VPP or ZBH1.

    zero_stage: "auto" (FLAGS_zero_stage, default 0) / None / 0/1/2/3 —
    ZeRO sharding over the dp axis (hybrid_engine.build_train_step).
    Stage 1 == the legacy zero1_dp=True (dp-sharded optimizer state);
    stage 2 additionally accounts the grad buffer dp-sharded (same
    compiled collectives — the reduce-scatter already owns the dp sync);
    stage 3 shards the PARAMS over dp at rest and gathers each block's
    leaves on use inside the layer scan (prefetched per
    FLAGS_zero3_overlap_ag; the checkpointed stage bodies re-gather in
    the backward, so live full params stay O(1 block)). Stage 3
    composes with mp/pp (all schedules), sp/ring, fp8, flash, sep and
    MoE exactly as stage 1 does. zero3: "auto" (flags) / None /
    comm_overlap.zero3.Zero3Config — the stage-3 gather knobs; with
    .quantize the BLOCK all-gathers travel as int8 + error-feedback
    residuals riding opt_state["zero3_ef"] (pp degree 1, one pipeline
    microbatch, not composed with fp8 / comm_overlap /
    moe_quantize_a2a). Unset (stage 0) compiles BITWISE-identically to
    a build without the argument.

    flash_attention: "auto" (FLAGS_flash_attention / FLAGS_flash_sep,
    default off) / None / bool / "ring" / "ulysses" /
    FlashAttentionConfig — the fused Pallas flash fwd + custom_vjp bwd
    kernel wired directly into every block (no registry hop). Off: the
    composed einsum path compiles BITWISE-identically. Composes with
    mp_overlap (attention consumes the gathered full sequence; heads
    stay local under TP), fp8 (the surrounding qkv/proj GEMMs keep their
    site_mm routing — attention itself stays bf16/f32), zero1,
    comm_overlap and every pipeline schedule. A sep mode additionally
    mounts `sep_axis` as a context-parallel mesh axis: the batch shards
    over dp AND the sequence over sep (data_spec P(dp, sep)), sep joins
    extra_grad_axes, and attention runs ring/Ulysses per shard with
    flash as the inner kernel — requires the axis on the mesh, S
    divisible by its degree (trace-time), no mp sequence parallelism
    and no MoE; "ulysses" further needs heads/mp divisible by sep.

    numerics: "auto" (FLAGS_numerics, default off) / None / bool /
    observability.numerics.NumericsConfig — in-program tensor-health
    telemetry riding the telemetry ring (ISSUE 15): per-stacked-layer
    grad norms (every schedule; MoE sums the dense+moe pair per index),
    per-layer activation rms/absmax deposited from the block scan
    (plain-1F1B dense path — the pipeline aux channel), EF-residual
    norms for whichever of comm_ef/moe_ef/zero3_ef the build threads,
    and fp8 per-site scale saturation/headroom. Implies a (non-strict)
    telemetry config when FLAGS_telemetry is off. Off compiles
    BITWISE-identically (tier-1 asserted).
    """
    from .hybrid_engine import build_train_step
    from ..quantization import fp8 as _f8
    from ..distributed.comm_overlap.collective_matmul import \
        resolve_mp_overlap
    from ..distributed.comm_overlap.a2a import resolve_moe_dispatch
    from ..kernels.pallas.flash_training import resolve_flash_attention

    sp = resolve_mp_overlap(mp_overlap)
    flash = resolve_flash_attention(flash_attention)
    sep_on = flash is not None and flash.sep is not None
    if sep_on:
        enforce(sep_axis in mesh.axis_names,
                "a sep-mode flash plan mounts context parallelism on a "
                f"mesh axis: add '{sep_axis}' (degree >= 1) to the mesh",
                op="gpt.build_hybrid_train_step",
                axes=tuple(mesh.axis_names))
        enforce(sp is None,
                "sep context parallelism and mp sequence parallelism "
                "both shard the sequence dim — disable "
                "FLAGS_mp_seq_parallel / mp_overlap or the flash sep "
                "mode", op="gpt.build_hybrid_train_step")
        enforce(not cfg.moe_on,
                "sep context parallelism is not composed with the "
                "GPT-MoE batch layout (batch shards over dp x ep)",
                op="gpt.build_hybrid_train_step")
        sep_n = int(mesh.shape[sep_axis])
        if flash.sep == "ulysses" and sep_n > 1:
            heads_local = cfg.num_heads // int(mesh.shape[mp_axis])
            enforce(heads_local % sep_n == 0,
                    "ulysses trades the sequence shard for a head shard: "
                    "local heads (num_heads / mp) must divide by the sep "
                    "degree — use ring attention otherwise",
                    op="gpt.build_hybrid_train_step",
                    heads_local=heads_local, sep=sep_n)
        # sep grads are genuinely partial (each rank saw a sequence
        # shard) — combine them exactly as the engine combines any
        # context-parallel axis
        extra_grad_axes = tuple(extra_grad_axes) + (sep_axis,)
    fp8_plan = _f8.resolve_fp8_plan(
        fp8, GPT_FP8_SITES, cfg.num_layers, stacked_axis=pp_axis,
        amax_axes=(dp_axis, mp_axis) + tuple(extra_grad_axes))
    # fp8 x ring-collective-matmul is refused by the engine (the ONE copy
    # of that compose rule — hybrid_engine.build_train_step); S % mp
    # divisibility is checked at trace time in hybrid_loss_fn (the
    # runtime sequence length may be shorter than max_seq_len)
    if fp8_plan is not None:
        enforce(virtual_pp == 1 and schedule == "1F1B",
                "fp8 delayed scaling supports the 1F1B schedule only "
                "(scales are stacked per layer and must follow any block "
                "permutation)", op="gpt.build_hybrid_train_step",
                virtual_pp=virtual_pp, schedule=schedule)

    moe_on = cfg.moe_on
    mcfg = resolve_moe_dispatch(moe_dispatch) if moe_on else None
    moe_plan = None
    if moe_on:
        from ..distributed.comm_overlap import a2a as _a2a
        from ..incubate.distributed.models.moe.gate import compute_capacity
        from .. import observability as _obs
        enforce(ep_axis in mesh.axis_names,
                "GPT-MoE shards the expert bank over an expert-parallel "
                f"mesh axis: add '{ep_axis}' (degree >= 1) to the mesh",
                op="gpt.build_hybrid_train_step",
                axes=tuple(mesh.axis_names))
        ep = int(mesh.shape[ep_axis])
        E = cfg.moe_num_experts
        enforce(E % ep == 0, "the ep degree must divide the expert count",
                op="gpt.build_hybrid_train_step", experts=E, ep=ep)
        enforce(cfg.ffn_hidden % int(mesh.shape[mp_axis]) == 0,
                "the expert hidden dim shards over mp",
                op="gpt.build_hybrid_train_step",
                ffn_hidden=cfg.ffn_hidden, mp=int(mesh.shape[mp_axis]))
        enforce(fp8_plan is None and sp is None,
                "the GPT-MoE hybrid path is not composed with fp8 "
                "delayed scaling or sequence parallelism — disable "
                "FLAGS_fp8 / FLAGS_mp_seq_parallel",
                op="gpt.build_hybrid_train_step")
        enforce(virtual_pp == 1 and schedule == "1F1B",
                "GPT-MoE supports the 1F1B schedule only",
                op="gpt.build_hybrid_train_step", virtual_pp=virtual_pp,
                schedule=schedule)
        ef = None
        if mcfg is not None and mcfg.quantize:
            enforce(int(mesh.shape[pp_axis]) == 1
                    and num_microbatches == 1,
                    "moe_quantize_a2a threads ONE error-feedback "
                    "residual slot per MoE layer per step; pipeline "
                    "microbatching would sum residuals across "
                    "microbatches — use pp degree 1 and "
                    "num_microbatches 1",
                    op="gpt.build_hybrid_train_step",
                    pp=int(mesh.shape[pp_axis]),
                    num_microbatches=num_microbatches)
            enforce(moe_ef_tokens is not None,
                    "moe_quantize_a2a sizes the residual state at build "
                    "time: pass moe_ef_tokens=(per-rank batch, seq)",
                    op="gpt.build_hybrid_train_step")
            b_loc, s_ef = moe_ef_tokens
            C = compute_capacity(int(b_loc) * int(s_ef), E, 1,
                                 cfg.moe_capacity_factor)
            chunks = mcfg.chunks if mcfg.overlap else 1
            shapes = _a2a.moe_ef_local_shapes(E, C, cfg.hidden_size, ep,
                                              chunks)
            L2 = cfg.num_layers // 2
            n_dev = int(mesh.devices.size)
            sizes = {k: L2 * math.prod(s) for k, s in shapes.items()}
            ef = {
                "init": (lambda: {k: jnp.zeros((n_dev * sz,), jnp.float32)
                                  for k, sz in sizes.items()}),
                "specs": {k: P(tuple(mesh.axis_names)) for k in sizes},
            }
        moe_plan = {
            "ep_axis": ep_axis, "ef": ef,
            "meta": {"ep": ep, "experts": E,
                     "dispatch": ("index" if (mcfg is not None
                                              and mcfg.index)
                                  else "dense"),
                     "quantize": bool(mcfg is not None and mcfg.quantize),
                     "overlap": bool(mcfg is not None and mcfg.overlap)},
        }
        if isinstance(telemetry, _obs.TelemetryConfig):
            # register the MoE series on the caller's config (in place —
            # their TelemetryHost decodes from the same object; build the
            # engine before constructing the host)
            telemetry.extra = telemetry.extra + tuple(
                s for s in moe_telemetry_series(cfg)
                if s not in telemetry.extra)

    # -- ZeRO stage resolution (stage 3 builds the gather-on-use plan) ----
    from .hybrid_engine import zero_dims, zero_extend_spec
    from ..distributed.comm_overlap.zero3 import (resolve_zero3,
                                                  resolve_zero_stage)
    specs = hybrid_param_specs(cfg)
    example = jax.eval_shape(
        lambda: init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    stage = resolve_zero_stage(zero_stage, zero1_dp,
                               op="gpt.build_hybrid_train_step")
    z3plan = None
    z3_engine = None
    if stage >= 3:
        z3cfg = resolve_zero3(zero3)
        zdims = zero_dims(specs, example, mesh, dp_axis)
        z3plan = {"zdims": zdims, "axis": dp_axis, "cfg": z3cfg,
                  "other_leaves": ("wte", "wpe", "lnf_g", "lnf_b",
                                   "head_w")}
        z3_engine = {"ef": None, "meta": z3cfg.meta()}
        if z3cfg.quantize:
            enforce(int(mesh.shape[pp_axis]) == 1
                    and num_microbatches == 1 and virtual_pp == 1,
                    "zero3_quantize_ag threads ONE error-feedback "
                    "residual slot per layer per step; pipeline "
                    "microbatching would sum residuals across ticks — "
                    "use pp degree 1, num_microbatches 1",
                    op="gpt.build_hybrid_train_step",
                    pp=int(mesh.shape[pp_axis]),
                    num_microbatches=num_microbatches)
            enforce(fp8_plan is None,
                    "zero3_quantize_ag and fp8 delayed scaling both own "
                    "the loss's 4th argument — disable one of the two",
                    op="gpt.build_hybrid_train_step")
            enforce(not moe_on,
                    "zero3_quantize_ag is not composed with the GPT-MoE "
                    "hybrid path (the pair scan does not thread the AG "
                    "residuals) — disable FLAGS_zero3_quantize_ag or "
                    "FLAGS_moe_*", op="gpt.build_hybrid_train_step")
            enforce(not (mcfg is not None and mcfg.quantize),
                    "zero3_quantize_ag and moe_quantize_a2a both thread "
                    "their residuals as the loss's 4th argument — "
                    "disable one of the two",
                    op="gpt.build_hybrid_train_step")
            blocks_ex, blocks_sp, zd_blk = (example["blocks"],
                                            specs["blocks"],
                                            zdims["blocks"])
            # residuals mirror the SHARDED block leaves: stacked global
            # shapes with the dp-extended specs, fp32; not-quantized
            # (replicated) leaves get a 0-column placeholder so the scan
            # structure stays homogeneous
            ef_specs = jax.tree.map(
                lambda sp_, zd, ex: (zero_extend_spec(sp_, zd, dp_axis,
                                                      ex.ndim)
                                     if zd >= 0 else P(sp_[0])),
                blocks_sp, zd_blk, blocks_ex,
                is_leaf=lambda x: isinstance(x, P))

            def ef_init(_ex=blocks_ex, _zd=zd_blk):
                return jax.tree.map(
                    lambda ex, zd: jnp.zeros(
                        tuple(ex.shape) if zd >= 0 else (ex.shape[0], 0),
                        jnp.float32),
                    _ex, _zd)
            z3_engine = {"ef": {"init": ef_init, "specs": ef_specs},
                         "meta": z3cfg.meta()}

    # -- numerics plan (tensor-health telemetry; ISSUE 15) ----------------
    from ..observability.numerics import resolve_numerics
    ncfg = resolve_numerics(
        numerics,
        # the stacked block subtree's layer-index count: GPT-MoE stacks
        # (dense, moe) PAIRS, so its per-layer series span L/2 indices
        num_layers=(cfg.num_layers // 2 if moe_on else cfg.num_layers),
        # activation stats need the plain-1F1B aux channel; per-layer
        # grad norms (engine-side) stay on for every schedule
        act=(not moe_on and virtual_pp == 1 and schedule == "1F1B"),
        pp_axis=pp_axis)

    if moe_plan is not None and moe_plan["ef"] is not None:
        def loss_fn(p, tokens, labels, moe_ef):
            return hybrid_loss_fn(p, tokens, labels, cfg, num_microbatches,
                                  dp_axis, pp_axis, mp_axis,
                                  virtual_pp=virtual_pp, schedule=schedule,
                                  sp=sp, ep_axis=ep_axis, moe=mcfg,
                                  moe_ef=moe_ef, flash=flash,
                                  sep_axis=sep_axis, z3=z3plan, num=ncfg)
    elif fp8_plan is not None:
        def loss_fn(p, tokens, labels, scales):
            return hybrid_loss_fn(p, tokens, labels, cfg, num_microbatches,
                                  dp_axis, pp_axis, mp_axis,
                                  virtual_pp=virtual_pp, schedule=schedule,
                                  fp8=scales, sp=sp, flash=flash,
                                  sep_axis=sep_axis, z3=z3plan, num=ncfg)
    elif z3_engine is not None and z3_engine["ef"] is not None:
        def loss_fn(p, tokens, labels, z3_ef):
            return hybrid_loss_fn(p, tokens, labels, cfg, num_microbatches,
                                  dp_axis, pp_axis, mp_axis,
                                  virtual_pp=virtual_pp, schedule=schedule,
                                  sp=sp, ep_axis=ep_axis, moe=mcfg,
                                  flash=flash, sep_axis=sep_axis,
                                  z3=z3plan, z3_ef=z3_ef, num=ncfg)
    else:
        def loss_fn(p, tokens, labels):
            return hybrid_loss_fn(p, tokens, labels, cfg, num_microbatches,
                                  dp_axis, pp_axis, mp_axis,
                                  virtual_pp=virtual_pp, schedule=schedule,
                                  sp=sp, ep_axis=ep_axis, moe=mcfg,
                                  flash=flash, sep_axis=sep_axis,
                                  z3=z3plan, num=ncfg)

    if moe_on:
        data_spec = P((dp_axis, ep_axis))
    elif sep_on:
        # batch over dp, sequence over the context-parallel axis
        data_spec = P(dp_axis, sep_axis)
    else:
        data_spec = None
    step, shard_params, init_state = build_train_step(
        loss_fn, specs, mesh, optimizer, dp_axis=dp_axis,
        data_spec=data_spec,
        extra_grad_axes=extra_grad_axes, example_params=example,
        grad_reduce_dtype=grad_reduce_dtype, zero_stage=stage,
        zero3=z3_engine,
        comm_overlap=comm_overlap, fp8=fp8_plan, telemetry=telemetry,
        mp_overlap=sp, moe=moe_plan, flash=flash, numerics=ncfg)
    # elastic-checkpoint hint (checkpoint.reshard): the stacked-[L] block
    # leaves' STORAGE order is (pp, vpp)-dependent under the interleaved
    # schedule; resume onto a different layout permutes them (fp8_meta's
    # per-layer scale stacks follow the same assignment)
    init_state.layout_extra["pp"] = {
        "num_layers": int(cfg.num_layers), "pp": int(mesh.shape[pp_axis]),
        "vpp": int(virtual_pp),
        "stacked_components": ["blocks", "fp8_meta"],
    }
    if fp8_plan is not None:
        # pipelined amax observations sum over T = M + P - 1 time steps
        # (test_fp8 asserts exact T x dense); a resume onto a different pp
        # degree rescales the carried histories by T_new/T_old so the
        # delayed scales keep their magnitude (checkpoint.reshard)
        init_state.layout_extra["fp8_amax_ticks"] = (
            num_microbatches + int(mesh.shape[pp_axis]) - 1)

    if virtual_pp > 1:
        shard_params = vpp_wrap_shard_params(
            shard_params, cfg.num_layers, mesh.shape[pp_axis], virtual_pp)
    return step, shard_params, init_state
