"""Placement types (reference:
python/paddle/distributed/auto_parallel/placement_type.py; C++
paddle/phi/core/distributed/auto_parallel/placement_types.h).

Mapping to XLA/GSPMD:
  Shard(d)    -> tensor dim d partitioned over a mesh axis (PartitionSpec entry)
  Replicate() -> no annotation on that mesh axis
  Partial(op) -> pending cross-axis reduction; GSPMD tracks this internally,
                 here it's explicit metadata resolved by `reshard` (psum /
                 pmax / ... over the axis).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["Placement", "Shard", "Replicate", "Partial",
           "placements_to_spec", "to_placements"]


class Placement:
    def is_shard(self, dim: Optional[int] = None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim: Optional[int] = None) -> bool:
        return dim is None or dim == self.dim

    def get_dim(self) -> int:
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"


def placements_to_spec(placements: Sequence[Placement], ndim: int,
                       axis_names: Sequence[str]) -> PartitionSpec:
    """Convert a per-mesh-axis placement list into a per-tensor-dim
    PartitionSpec. placements[i] describes what mesh axis i does."""
    entries: List = [None] * ndim
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            d = p.dim if p.dim >= 0 else p.dim + ndim
            axis = axis_names[axis_idx]
            if entries[d] is None:
                entries[d] = axis
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (axis,)
            else:
                entries[d] = (entries[d], axis)
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def to_placements(spec: PartitionSpec, ndim: int,
                  axis_names: Sequence[str]) -> List[Placement]:
    """Inverse of placements_to_spec (lossy: Partial is not representable)."""
    out: List[Placement] = [Replicate() for _ in axis_names]
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for a in axes:
            out[list(axis_names).index(a)] = Shard(dim)
    return out
