"""Audio feature layers (reference: python/paddle/audio/features/layers.py —
Spectrogram:45, MelSpectrogram:130, LogMelSpectrogram:237, MFCC:344).

TPU pipeline per layer: STFT (XLA fft) → |·|^p → mel filterbank matmul
(MXU) → log / DCT matmul. Filterbank and DCT matrices are constants folded
into the compiled program.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax.numpy as jnp
from ..enforce import enforce, enforce_gt

from ..nn.layer.layers import Layer
from .. import signal as _signal
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """(layers.py:45) |STFT|^power, [N, n_fft//2+1, num_frames]."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = 512,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 1.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        enforce_gt(power, 0, "Power of spectrogram must be > 0.",
                   op="Spectrogram")
        self.power = power
        win_length = win_length or n_fft
        fft_window = get_window(window, win_length, fftbins=True, dtype=dtype)
        self.register_buffer("fft_window", fft_window)
        self._stft = partial(_signal.stft, n_fft=n_fft, hop_length=hop_length,
                             win_length=win_length, window=fft_window,
                             center=center, pad_mode=pad_mode)

    def forward(self, x):
        return jnp.abs(self._stft(x)) ** self.power


class MelSpectrogram(Layer):
    """(layers.py:130) Spectrogram → mel filterbank, [N, n_mels, frames]."""

    def __init__(self, sr: int = 22050, n_fft: int = 2048,
                 hop_length: Optional[int] = 512,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode, dtype)
        fbank = compute_fbank_matrix(sr=sr, n_fft=n_fft, n_mels=n_mels,
                                     f_min=f_min, f_max=f_max, htk=htk,
                                     norm=norm, dtype=dtype)
        self.register_buffer("fbank_matrix", fbank)  # [n_mels, nfreq]

    def forward(self, x):
        spect = self._spectrogram(x)                 # [..., nfreq, F]
        return jnp.matmul(self.fbank_matrix, spect)  # [..., n_mels, F]


class LogMelSpectrogram(Layer):
    """(layers.py:237) power_to_db(MelSpectrogram)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return power_to_db(self._melspectrogram(x), ref_value=self.ref_value,
                           amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    """(layers.py:344) DCT of log-mel, [N, n_mfcc, num_frames]."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        enforce(n_mfcc <= n_mels, "n_mfcc cannot be larger than n_mels",
                op="MFCC", n_mfcc=n_mfcc, n_mels=n_mels)
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix", create_dct(n_mfcc, n_mels,
                                                      dtype=dtype))

    def forward(self, x):
        mel = self._log_melspectrogram(x)            # [..., n_mels, F]
        return jnp.einsum("mk,...mf->...kf", self.dct_matrix, mel)
