"""Hang flight recorder: a silent pod hang becomes a diagnosable
artifact.

When a step wedges (watchdog timeout), the platform preempts (SIGTERM)
or the loop aborts (non-finite escalation), whatever this process knew
at that moment is dumped as ONE bounded bundle directory under
``FLAGS_flight_recorder_dir``:

* ``manifest.json``     — reason, wall clock, pid/host/role, watchdog
  counters;
* ``events_tail.jsonl`` — the last ``FLAGS_flight_recorder_events``
  records of the JSONL event log (bounded read from the end — the log
  may be huge, the crash path must stay cheap);
* ``telemetry_tail.json`` — the last decoded interval of every live
  :class:`~paddle_tpu.observability.metrics.TelemetryHost` ring buffer
  (loss / grad-norm / comms-bytes right up to the hang);
* ``open_spans.json``   — host RecordEvent spans still open plus the
  watchdog's pending spans with their ages: *what* was running;
* ``heartbeats.json``   — fleet aggregator snapshots (per-host
  last-heartbeat ages, the last straggler report): *who else* was alive;
* ``profile_window.json`` — the active profile-capture window, if one
  was open when the hang hit;
* ``numerics.json``     — every live numerics monitor's last-K
  per-layer/EF/fp8 stats + detector/episode state (ISSUE 15; the
  monitor also dumps a bundle itself on a ``numerics_anomaly``);
* ``report.txt``        — the watchdog's thread-stack report.

The recorder is pull-based: sources register weakly (TelemetryHost,
TelemetryAggregator) or are read at dump time (event log file, span
registry), so an idle recorder costs nothing and a dump never blocks on
a wedged device — everything read is host state. Dumps are rate-limited
and the crash dir keeps only ``FLAGS_flight_recorder_keep`` bundles.
With ``FLAGS_flight_recorder_dir`` empty the whole module is inert, and
none of it touches compiled programs either way (host-only — the
telemetry-off bitwise no-op contract is untouched).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import weakref
from typing import Any, Dict, Optional

__all__ = ["FlightRecorder", "get_flight_recorder", "set_flight_recorder",
           "maybe_dump", "register_telemetry_host", "register_aggregator",
           "register_serving_engine", "register_numerics_monitor",
           "register_router"]

_SRC_LOCK = threading.Lock()
_TELEMETRY_HOSTS: "weakref.WeakSet" = weakref.WeakSet()
_AGGREGATORS: "weakref.WeakSet" = weakref.WeakSet()
_SERVING_ENGINES: "weakref.WeakSet" = weakref.WeakSet()
_NUMERICS_MONITORS: "weakref.WeakSet" = weakref.WeakSet()
_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()


def register_telemetry_host(host) -> None:
    """Weakly track a TelemetryHost so crash bundles can include its ring
    tail (called by TelemetryHost.__init__; weak — the recorder never
    keeps a dead run's host alive)."""
    with _SRC_LOCK:
        _TELEMETRY_HOSTS.add(host)


def register_aggregator(agg) -> None:
    """Weakly track a fleet TelemetryAggregator for heartbeat/straggler
    state in crash bundles."""
    with _SRC_LOCK:
        _AGGREGATORS.add(agg)


def register_serving_engine(engine) -> None:
    """Weakly track a ServingEngine so crash bundles include its host
    snapshot — slots, queue, pool utilization, per-request status
    (called by ServingEngine.__init__; ISSUE 13)."""
    with _SRC_LOCK:
        _SERVING_ENGINES.add(engine)


def register_router(router) -> None:
    """Weakly track a fleet Router so crash bundles gain ``router.json``
    — per-replica lifecycle state + failure counters, the fleet queue
    and every request's status/watermark (called by Router.__init__;
    ISSUE 16): a fleet incident leaves forensics, not just one
    replica's view."""
    with _SRC_LOCK:
        _ROUTERS.add(router)


def register_numerics_monitor(monitor) -> None:
    """Weakly track a numerics NumericsMonitor so EVERY crash bundle —
    hang, SIGTERM, nonfinite abort or the monitor's own anomaly dump —
    gains ``numerics.json`` (last-K per-layer stats + detector state;
    called by NumericsMonitor.__init__, ISSUE 15)."""
    with _SRC_LOCK:
        _NUMERICS_MONITORS.add(monitor)


from .events import _jsonable  # one coercion for bundles AND the log


class FlightRecorder:
    def __init__(self, crash_dir: str, *, max_events: Optional[int] = None,
                 keep: Optional[int] = None,
                 min_interval_s: float = 2.0):
        from ..flags import flag
        self.crash_dir = crash_dir
        self.max_events = int(max_events if max_events is not None
                              else flag("flight_recorder_events"))
        self.keep = max(int(keep if keep is not None
                            else flag("flight_recorder_keep")), 1)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._last_dump = 0.0
        self.dump_count = 0
        self.last_bundle: Optional[str] = None

    # -- the dump ------------------------------------------------------------
    def dump(self, reason: str, *, watchdog=None, report: Optional[str]
             = None, extra: Optional[Dict[str, Any]] = None
             ) -> Optional[str]:
        """Write one bundle; returns its path, or None when rate-limited
        or the dump failed (the crash path NEVER raises from here — a
        broken recorder must not mask the original failure)."""
        try:
            return self._dump(reason, watchdog=watchdog, report=report,
                              extra=extra)
        except Exception as e:  # pragma: no cover - defensive
            import sys
            sys.stderr.write(f"[flight-recorder] dump failed: {e!r}\n")
            return None

    def _dump(self, reason, *, watchdog, report, extra):
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump < self.min_interval_s:
                return None
            self._last_dump = now
            self.dump_count += 1
        safe = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                       for ch in str(reason))[:60]
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(self.crash_dir,
                            f"flight_{stamp}_{safe}_pid{os.getpid()}")
        suffix = 0
        while os.path.exists(path + (f".{suffix}" if suffix else "")):
            suffix += 1
        if suffix:
            path += f".{suffix}"
        os.makedirs(path, exist_ok=True)

        from .events import get_event_log
        from ..flags import flag
        log = get_event_log()
        manifest = {
            "reason": str(reason), "ts": time.time(),
            "time": time.strftime("%Y-%m-%d %H:%M:%S"),
            "pid": os.getpid(),
            "host": log.host if log is not None else None,
            "role": log.role if log is not None else None,
            "event_log": log.path if log is not None else None,
            "telemetry_flag": bool(flag("telemetry")),
            "dump_count": self.dump_count,
        }
        if watchdog is not None:
            try:
                manifest["watchdog"] = watchdog.stats()
            except Exception:
                pass
        if extra:
            manifest["extra"] = extra
        self._write_json(path, "manifest.json", manifest)

        # recent JSONL events (bounded tail read off disk)
        if log is not None:
            tail = log.tail(self.max_events)
            with open(os.path.join(path, "events_tail.jsonl"), "w",
                      encoding="utf-8") as f:
                for rec in tail:
                    f.write(json.dumps(rec, default=_jsonable) + "\n")

        # telemetry ring tails of every live host
        with _SRC_LOCK:
            hosts = list(_TELEMETRY_HOSTS)
            aggs = list(_AGGREGATORS)
            engines = list(_SERVING_ENGINES)
            monitors = list(_NUMERICS_MONITORS)
            routers = list(_ROUTERS)
        tele = {}
        for i, h in enumerate(hosts):
            try:
                tele[f"telemetry_host_{i}"] = h.tail()
            except Exception:
                continue
        if tele:
            self._write_json(path, "telemetry_tail.json", tele)

        # what was running: open RecordEvent spans + watchdog pending
        from ..profiler.utils import active_spans
        spans: Dict[str, Any] = {"record_events": active_spans()}
        if watchdog is not None:
            try:
                spans["watchdog_pending"] = [
                    {"tag": tag, "age_s": round(age, 3)}
                    for tag, age in watchdog.pending()]
            except Exception:
                pass
        self._write_json(path, "open_spans.json", spans)

        # who else was alive: fleet heartbeat/straggler state
        beats = {}
        for i, a in enumerate(aggs):
            try:
                beats[f"aggregator_{i}"] = a.snapshot()
            except Exception:
                continue
        if beats:
            self._write_json(path, "heartbeats.json", beats)

        # serving state: what every live engine was doing — slots, queue,
        # pool utilization, per-request status (host dicts only)
        serving = {}
        for i, e in enumerate(engines):
            try:
                serving[f"serving_engine_{i}"] = e.snapshot()
            except Exception:
                continue
        if serving:
            self._write_json(path, "serving.json", serving)

        # fleet state: every live router's replica lifecycle + failure
        # counters + per-request watermarks (ISSUE 16; host dicts only)
        rts = {}
        for i, r in enumerate(routers):
            try:
                rts[f"router_{i}"] = r.snapshot()
            except Exception:
                continue
        if rts:
            self._write_json(path, "router.json", rts)

        # numerics forensics: last-K per-layer/EF/fp8 stats + detector
        # state of every live NumericsMonitor (host deques only)
        num = {}
        for i, m in enumerate(monitors):
            try:
                num[f"numerics_monitor_{i}"] = m.snapshot()
            except Exception:
                continue
        if num:
            self._write_json(path, "numerics.json", num)

        from .profile_reader import active_profile_window
        win = active_profile_window()
        if win is not None:
            self._write_json(path, "profile_window.json", win)

        if report:
            with open(os.path.join(path, "report.txt"), "w",
                      encoding="utf-8") as f:
                f.write(str(report))

        self._prune()
        self.last_bundle = path
        if log is not None:
            try:
                log.emit("flight_recorder_dump", reason=str(reason),
                         bundle=path)
            except Exception:
                pass
        return path

    def _write_json(self, path: str, name: str, obj) -> None:
        with open(os.path.join(path, name), "w", encoding="utf-8") as f:
            json.dump(obj, f, indent=2, default=_jsonable)

    def _prune(self) -> None:
        """Keep the newest `keep` bundles; the crash dir stays bounded
        even under a watchdog storm."""
        try:
            entries = sorted(
                e for e in os.listdir(self.crash_dir)
                if e.startswith("flight_")
                and os.path.isdir(os.path.join(self.crash_dir, e)))
        except OSError:
            return
        for e in entries[:-self.keep]:
            shutil.rmtree(os.path.join(self.crash_dir, e),
                          ignore_errors=True)


_GLOBAL: Optional[FlightRecorder] = None
_GLOBAL_DIR: Optional[str] = None
_EXPLICIT = False
_G_LOCK = threading.Lock()


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The process flight recorder: an explicitly installed one wins;
    otherwise one bound to FLAGS_flight_recorder_dir (None when empty),
    re-bound if the flag changed."""
    global _GLOBAL, _GLOBAL_DIR
    with _G_LOCK:
        if _EXPLICIT:
            return _GLOBAL
    from ..flags import flag
    d = str(flag("flight_recorder_dir") or "")
    with _G_LOCK:
        if _EXPLICIT:
            return _GLOBAL
        if _GLOBAL is not None and _GLOBAL_DIR == d:
            return _GLOBAL
        _GLOBAL = FlightRecorder(d) if d else None
        _GLOBAL_DIR = d or None
    return _GLOBAL


def set_flight_recorder(rec: Optional[FlightRecorder]
                        ) -> Optional[FlightRecorder]:
    """Install an explicit recorder (tests/drivers), shadowing the flag
    binding; None restores flag-driven behavior. Returns the previous."""
    global _GLOBAL, _GLOBAL_DIR, _EXPLICIT
    with _G_LOCK:
        prev, _GLOBAL = _GLOBAL, rec
        _GLOBAL_DIR = rec.crash_dir if rec is not None else None
        _EXPLICIT = rec is not None
    return prev


def maybe_dump(reason: str, *, watchdog=None, report: Optional[str] = None,
               extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump through the process recorder iff one is configured. The one
    call sprinkled on crash paths (watchdog timeout, SIGTERM drain,
    nonfinite abort) — inert and allocation-free when the flag is off."""
    rec = get_flight_recorder()
    if rec is None:
        return None
    return rec.dump(reason, watchdog=watchdog, report=report, extra=extra)
