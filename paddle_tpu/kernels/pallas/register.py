"""Wire Pallas kernels into the op registry as TPU fast paths.

The reference selects fused CUDA kernels through KernelFactory dispatch
(paddle/phi/core/kernel_factory.h:316); here the same decision is the
``register_pallas_impl`` override, gated by the ``enable_pallas_kernels``
flag and the per-kernel ``supported`` predicate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops import register_pallas_impl
import paddle_tpu.kernels.pallas.flash_attention as fa
import paddle_tpu.kernels.pallas.layer_norm as ln
import paddle_tpu.kernels.pallas.rms_norm as rn


@register_pallas_impl("scaled_dot_product_attention", supported=fa.supported)
def _sdpa_pallas(query, key, value, attn_mask=None, dropout_p=0.0,
                 is_causal=False, training=True, name=None, segment_ids=None):
    del name
    bias = None
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            # bool masks become additive in the compute dtype; -1e30 is
            # representable in bf16 and matches the composed path's fill
            bias = jnp.where(attn_mask, 0.0, -1e30).astype(query.dtype)
        else:
            bias = attn_mask
    seg = None
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids).astype(jnp.int32)
    p, seed = _dropout_seed(dropout_p, training)
    from ...flags import flag
    bq = flag("flash_attn_block_q") or None  # 0 = auto-pick
    bk = flag("flash_attn_block_k") or None
    return fa.flash_attention(query, key, value, is_causal, None, bq, bk,
                              bias=bias, q_segment_ids=seg,
                              kv_segment_ids=seg, dropout_p=p,
                              dropout_seed=seed)


def _dropout_seed(p, training):
    """Draw the kernel dropout seed from the framework RNG. Inside a jitted
    step the caller must be under ``paddle_tpu.random.rng_guard(step_key)``
    so the seed is a *traced* value that changes per step; a concrete key
    during tracing would bake ONE mask into the compiled program."""
    if not (p and training):
        return 0.0, None
    import warnings

    from ...random import next_key
    from ...utils.compat import trace_state_clean
    key = next_key()
    if not isinstance(key, jax.core.Tracer) and not trace_state_clean():
        warnings.warn(
            "attention dropout seed drawn while tracing without an active "
            "rng_guard: the dropout mask will be IDENTICAL every step of "
            "the compiled program. Wrap the jitted step body in "
            "paddle_tpu.random.rng_guard(step_key).")
    return float(p), jax.random.randint(key, (1,), 0, 2 ** 31 - 1,
                                        dtype=jnp.int32)


def _unpadded_supported(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    if return_softmax or getattr(query, "ndim", 0) != 3:
        return False
    if query.shape[2] > 256 or key.shape[1] == 0:
        return False
    if query.shape[1] % key.shape[1] != 0:
        return False
    # packed-global causal == per-sequence causal only when q and k share
    # the exact same packing
    if causal and cu_seqlens_q is not cu_seqlens_k:
        return False
    return True


@register_pallas_impl("flash_attn_unpadded", supported=_unpadded_supported)
def _flash_attn_unpadded_pallas(query, key, value, cu_seqlens_q,
                                cu_seqlens_k, max_seqlen_q, max_seqlen_k,
                                scale=None, dropout=0.0, causal=False,
                                return_softmax=False, training=True):
    """Varlen via in-kernel segment ids: pad totals to the 128-lane
    boundary (pad rows get non-matching segment ids, so they contribute
    nothing and their output rows are sliced off)."""
    from ...nn.functional.flash_attention import _segments_from_cu
    tq, h, d = query.shape
    tk = key.shape[0]
    seg_q = _segments_from_cu(cu_seqlens_q, tq)
    seg_k = _segments_from_cu(cu_seqlens_k, tk)
    pq, pk = (-tq) % 128, (-tk) % 128
    if pq:
        query = jnp.pad(query, ((0, pq), (0, 0), (0, 0)))
        seg_q = jnp.pad(seg_q, (0, pq), constant_values=-1)
    if pk:
        key = jnp.pad(key, ((0, pk), (0, 0), (0, 0)))
        value = jnp.pad(value, ((0, pk), (0, 0), (0, 0)))
        seg_k = jnp.pad(seg_k, (0, pk), constant_values=-2)
    p, seed = _dropout_seed(dropout, training)
    out = fa.flash_attention(
        query[None], key[None], value[None], causal, scale,
        q_segment_ids=seg_q[None], kv_segment_ids=seg_k[None],
        dropout_p=p, dropout_seed=seed)
    return out[0, :tq], None


def _flashmask_supported(query, key, value, startend_row_indices=None,
                         dropout=0.0, causal=False, window_size=None):
    if not fa.supported(query, key, value, dropout_p=dropout):
        return False
    if startend_row_indices is not None:
        idx = startend_row_indices
        if getattr(idx, "ndim", 0) != 4 or idx.shape[-1] not in (1, 2):
            return False
        if not causal:
            # bidirectional forms mask TWO bands per column (triangle-
            # scoped) — not expressible as the kernel's single start/end
            # band; they ride the composed path's dense mask instead
            return False
        b, sq, h = query.shape[0], query.shape[1], query.shape[2]
        if idx.shape[0] != b or idx.shape[1] not in (1, h):
            return False
        if idx.shape[2] != key.shape[1]:
            return False
    return True


@register_pallas_impl("flashmask_attention", supported=_flashmask_supported)
def _flashmask_pallas(query, key, value, startend_row_indices=None,
                      dropout=0.0, causal=False, window_size=None):
    fm = None
    if startend_row_indices is not None:
        idx = startend_row_indices
        start = idx[..., 0]
        end = (idx[..., 1] if idx.shape[-1] == 2
               else jnp.full_like(start, query.shape[1]))
        fm = (start, end)
    window = None
    if window_size is not None:
        w = window_size if isinstance(window_size, int) else window_size[0]
        window = (int(w), None)
    p, seed = _dropout_seed(dropout, True)
    out = fa.flash_attention(query, key, value, causal, None,
                             startend_row_indices=fm, window=window,
                             dropout_p=p, dropout_seed=seed)
    return out, None


def _ln_supported(x, normalized_shape, weight=None, bias=None,
                  epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    return (weight is not None and len(tuple(normalized_shape)) == 1
            and tuple(normalized_shape)[0] == x.shape[-1]
            and ln.supported(x, weight, epsilon))


@register_pallas_impl("layer_norm", supported=_ln_supported)
def _layer_norm_pallas(x, normalized_shape, weight=None, bias=None,
                       epsilon=1e-5, name=None):
    del normalized_shape, name
    return ln.layer_norm(x, weight, bias, epsilon)


def _rms_supported(x, weight=None, bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    return (weight is not None and bias is None
            and begin_norm_axis in (-1, x.ndim - 1)
            and rn.supported(x, weight, epsilon))


@register_pallas_impl("rms_norm", supported=_rms_supported)
def _rms_norm_pallas(x, weight=None, bias=None, epsilon=1e-6,
                     begin_norm_axis=-1):
    return rn.rms_norm(x, weight, epsilon)
