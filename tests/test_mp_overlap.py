"""Sequence-parallel TP + ring collective-matmul tests (ISSUE 5).

Covers: primitive-level fwd/bwd parity of the collective_matmul ops,
bitwise-identical lowered HLO with the feature off, multi-step loss
parity of seq-parallel and collective-matmul vs the allreduce baseline
on the dp2·pp2·mp2 virtual mesh (50-step acceptance run in the slow
tier), the mp=1 degenerate case, the fp8+zero1 compose, the
ring-vs-fp8 refusal, the per-mode HLO collective-mix assertion (guards
against silent fallback to the replicated path), telemetry comms_bytes
vs the analytic wire model, and the mp_ops axis/shape validation.

Parity tolerance note: seq-parallel REDUCES the LayerNorm/bias grads
over mp (per-shard partial sums + psum) where the baseline computes one
full-sequence reduction per rank — the same sums reassociated, so fp32
losses agree to ulp-level absolute differences (measured ≤2e-8 abs /
≤2e-6 rel over 50 steps as the toy overfits toward 0.02 loss) but not
always bit-for-bit. The bitwise guarantee of this PR is the OFF path:
with the flags off the compiled step is byte-identical HLO
(test_mp_overlap_off_is_bitwise_noop).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.enforce import EnforceNotMet, InvalidArgumentError
from paddle_tpu.distributed.comm_overlap import collective_matmul as cm
from paddle_tpu.distributed.fleet.layers.mpu import mp_ops
from paddle_tpu.models import gpt as G
from paddle_tpu.utils import shard_map

from hlo_utils import collective_counts

CFG = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
                  max_seq_len=16, dtype=jnp.float32)
LR = jnp.float32(1e-2)


def _data(batch=8, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randint(0, CFG.vocab_size, (batch, seq))),
            jnp.asarray(rng.randint(0, CFG.vocab_size, (batch, seq))))


def _run_gpt(mesh, mode, steps, cfg=CFG, **kw):
    opt = paddle.optimizer.AdamW(1e-2)
    step, shard, init = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=2, mp_overlap=mode, **kw)
    p = shard(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    s = init(p)
    tokens, labels = _data()
    losses = []
    for _ in range(steps):
        p, s, loss = step(p, s, tokens, labels, LR)
        losses.append(float(loss))
    return losses


def _max_rel(a, b):
    return max(abs(x - y) / max(abs(x), 1e-12) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# Primitive level
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("ring", [False, True], ids=["fused", "ring"])
def test_ag_matmul_and_matmul_rs_match_dense(ring):
    """Forward and backward of both entry points vs the dense reference,
    on an mp=4 sub-mesh (ring partial-sum order differs from the fused
    collectives — parity within fp32 reassociation noise)."""
    mesh = dist.build_mesh({"mp": 4, "x": 2})
    rng = np.random.RandomState(0)
    B, S, H, F = 2, 8, 6, 12
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    w = jnp.asarray(rng.randn(H, F).astype(np.float32))

    def ag_grads(xl, wl):
        def loss(xl, wl):
            # per-rank loss over this rank's F shard; the total is the
            # mp-sum (x axis replicates the identical computation)
            return jnp.sum(cm.ag_matmul(xl, wl, "mp", ring=ring) ** 2)
        return (lax.psum(loss(xl, wl), "mp"),) + jax.grad(
            loss, argnums=(0, 1))(xl, wl)

    l, gx, gw = jax.jit(shard_map(
        ag_grads, mesh=mesh,
        in_specs=(P(None, "mp", None), P(None, "mp")),
        out_specs=(P(), P(None, "mp", None), P(None, "mp"))))(x, w)
    l_ref, (gx_ref, gw_ref) = (
        jnp.sum((x @ w) ** 2),
        jax.grad(lambda x, w: jnp.sum((x @ w) ** 2), argnums=(0, 1))(x, w))
    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               atol=1e-4)

    z = jnp.asarray(rng.randn(B, S, F).astype(np.float32))
    w2 = jnp.asarray(rng.randn(F, H).astype(np.float32))

    def rs_grads(zl, wl):
        def loss(zl, wl):
            return jnp.sum(cm.matmul_rs(zl, wl, "mp", ring=ring) ** 2)
        # the per-rank seq-shard losses sum to the dense loss
        return (lax.psum(loss(zl, wl), "mp"),) + jax.grad(
            loss, argnums=(0, 1))(zl, wl)

    l2, gz, gw2 = jax.jit(shard_map(
        rs_grads, mesh=mesh,
        in_specs=(P(None, None, "mp"), P("mp", None)),
        out_specs=(P(), P(None, None, "mp"), P("mp", None))))(z, w2)
    l2_ref = jnp.sum((z @ w2) ** 2)
    gz_ref, gw2_ref = jax.grad(
        lambda z, w: jnp.sum((z @ w) ** 2), argnums=(0, 1))(z, w2)
    np.testing.assert_allclose(float(l2), float(l2_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gz), np.asarray(gz_ref),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw2), np.asarray(gw2_ref),
                               atol=1e-4)


def test_scatter_ag_rs_seq_roundtrip_and_grads():
    mesh = dist.build_mesh({"mp": 4, "x": 2})
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 8, 4).astype(np.float32))

    def fn(xr):
        # replicated -> scatter -> gather is the identity (values)
        y = cm.ag_seq(cm.scatter_seq(xr, "mp"), "mp")

        # per-rank loss on the shard: grad = scatter-bwd(2*chunk) =
        # all_gather of the per-chunk grads = exactly 2x on every rank
        def loss(xr):
            return jnp.sum(cm.scatter_seq(xr, "mp") ** 2)

        return y, jax.grad(loss)(xr)

    y, g = jax.jit(shard_map(fn, mesh=mesh, in_specs=(P(),),
                             out_specs=(P(), P())))(x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(x), atol=1e-6)


# ---------------------------------------------------------------------------
# Engine level: off = bitwise no-op, on = loss parity
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def mesh8():
    return dist.build_mesh({"dp": 2, "pp": 2, "mp": 2})


def test_mp_overlap_off_is_bitwise_noop(mesh8):
    """FLAGS off + mp_overlap='auto' must lower to byte-identical HLO as
    an explicit mp_overlap=None build (the telemetry no-op pattern)."""
    paddle.set_flags({"FLAGS_mp_seq_parallel": False,
                      "FLAGS_mp_collective_matmul": False})
    tokens, labels = _data()

    def build(mode):
        step, shard, init = G.build_hybrid_train_step(
            CFG, mesh8, paddle.optimizer.AdamW(1e-2), num_microbatches=2,
            mp_overlap=mode)
        p = shard(G.init_hybrid_params(CFG, jax.random.PRNGKey(0)))
        return step, p, init(p)

    step_none, p, s = build(None)
    base = step_none.lower(p, s, tokens, labels, LR).as_text()
    step_auto, _, _ = build("auto")
    assert step_auto.lower(p, s, tokens, labels, LR).as_text() == base

    # and ON genuinely changes the program
    step_sp, _, _ = build("seq_parallel")
    assert step_sp.lower(p, s, tokens, labels, LR).as_text() != base


def test_seq_parallel_and_ring_loss_parity(mesh8):
    """8-step fp32 loss parity of both sp modes vs the allreduce baseline
    on dp2·pp2·mp2 (50-step acceptance run: test_parity_50_steps, slow
    tier). Tolerance: see module docstring."""
    base = _run_gpt(mesh8, None, 8)
    sp = _run_gpt(mesh8, "seq_parallel", 8)
    ring = _run_gpt(mesh8, "collective_matmul", 8)
    assert base[0] == sp[0] == ring[0], "forward must match exactly"
    assert _max_rel(base, sp) < 1e-6, (base, sp)
    assert _max_rel(base, ring) < 1e-6, (base, ring)


@pytest.mark.slow
def test_parity_50_steps(mesh8):
    """ISSUE 5 acceptance: 50-step loss parity on the virtual 8-device
    mesh for both modes, fp32. The toy overfits to ~0.02 loss by step
    50, so the ulp-level grad reassociation (module docstring) shows up
    as ~1e-6 relative there — rtol 1e-5 with a small atol floor."""
    base = _run_gpt(mesh8, None, 50)
    sp = _run_gpt(mesh8, "seq_parallel", 50)
    ring = _run_gpt(mesh8, "collective_matmul", 50)
    np.testing.assert_allclose(sp, base, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ring, base, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_parity_bf16(mesh8):
    """bf16 compute dtype: step 0 (identical params) must match exactly
    and step 1 to ~1e-4 — a WRONG gradient (e.g. the unsummed SP-param
    grads this suite exists to catch) shows up at step 1 at 3e-3.
    Beyond that, bf16 QUANTIZES the fp32 ulp noise (a 1e-8 param
    difference crosses bf16 rounding boundaries, measured ~3e-3 by step
    2 on this overfitting toy), so longer bf16 horizons only get a
    sanity band — the 50-step acceptance run is the fp32 one."""
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                      num_heads=4, max_seq_len=16, dtype=jnp.bfloat16)
    base = _run_gpt(mesh8, None, 3, cfg=cfg)
    sp = _run_gpt(mesh8, "seq_parallel", 3, cfg=cfg)
    assert base[0] == sp[0], (base, sp)
    assert abs(base[1] - sp[1]) / abs(base[1]) < 5e-4, (base, sp)
    assert _max_rel(base, sp) < 2e-2, (base, sp)


def test_mp1_degenerate():
    """mp=1 mesh: every sp collective degenerates to identity/local
    matmul — losses must equal the baseline exactly."""
    mesh = dist.build_mesh({"dp": 4, "pp": 2, "mp": 1})
    base = _run_gpt(mesh, None, 3)
    sp = _run_gpt(mesh, "seq_parallel", 3)
    ring = _run_gpt(mesh, "collective_matmul", 3)
    assert base == sp == ring, (base, sp, ring)


@pytest.mark.slow
def test_fp8_zero1_compose(mesh8):
    """seq-parallel composes with fp8 delayed scaling + ZeRO-1: the site
    GEMMs see the gathered full-sequence input (same values as the
    allreduce path's replicated input), so the fp8 trajectories track —
    step 0 exactly, then to quantization-amplified reassociation noise
    (a grad ulp shifts an amax, which shifts next step's scales;
    measured ≤2e-4 over 4 steps)."""
    base = _run_gpt(mesh8, None, 4, fp8=True, zero1_dp=True)
    sp = _run_gpt(mesh8, "seq_parallel", 4, fp8=True, zero1_dp=True)
    assert base[0] == sp[0], (base, sp)
    assert _max_rel(base, sp) < 5e-4, (base, sp)


def test_ring_refuses_fp8(mesh8):
    with pytest.raises(EnforceNotMet, match="amax"):
        G.build_hybrid_train_step(
            CFG, mesh8, paddle.optimizer.AdamW(1e-2), num_microbatches=2,
            mp_overlap="collective_matmul", fp8=True)
    from paddle_tpu.models import llama as L
    lcfg = L.llama_tiny(dtype=jnp.float32)
    with pytest.raises(EnforceNotMet, match="amax"):
        L.build_hybrid_train_step(
            lcfg, mesh8, paddle.optimizer.AdamW(1e-2), num_microbatches=2,
            mp_overlap="collective_matmul", fp8=True)


# ---------------------------------------------------------------------------
# HLO collective mix (guards against silent fallback to the replicated
# path — loss parity alone cannot distinguish the modes)
# ---------------------------------------------------------------------------
def test_hlo_collective_mix(mesh8):
    tokens, labels = _data()

    def counts(mode):
        step, shard, init = G.build_hybrid_train_step(
            CFG, mesh8, paddle.optimizer.AdamW(1e-2), num_microbatches=2,
            mp_overlap=mode)
        p = shard(G.init_hybrid_params(CFG, jax.random.PRNGKey(0)))
        s = init(p)
        return collective_counts(
            step.lower(p, s, tokens, labels, LR).as_text())

    base, sp, ring = counts(None), counts("seq_parallel"), \
        counts("collective_matmul")
    # baseline: pure all-reduce TP — no AG/RS anywhere, only the pp
    # pipeline's two ppermutes
    assert base["all_gather"] == 0 and base["reduce_scatter"] == 0, base
    # seq-parallel: the per-layer ACTIVATION all-reduce pairs become
    # AG+RS. (Raw all-reduce op COUNTS are not a clean discriminator
    # here: sp adds [H]-sized grad psums for the replicated-but-SP
    # ln/bias params — more ops, vastly fewer bytes — so the mode
    # signature is the AG/RS/permute mix.)
    assert sp["all_gather"] > 0 and sp["reduce_scatter"] > 0, sp
    # collective matmul: the AG/RS pairs become ppermute rings (the
    # baseline's permutes are the pp pipeline's — the ring adds more)
    assert ring["collective_permute"] > sp["collective_permute"], (sp, ring)
    assert ring["all_gather"] < sp["all_gather"], (sp, ring)
    assert ring["reduce_scatter"] < sp["reduce_scatter"], (sp, ring)


def test_llama_hlo_collective_mix(mesh8):
    from paddle_tpu.models import llama as L
    lcfg = L.LlamaConfig(vocab_size=64, hidden_size=32, num_layers=4,
                         num_heads=4, num_kv_heads=2, intermediate_size=64,
                         max_seq_len=16, dtype=jnp.float32)
    tokens, labels = _data()

    def counts(mode):
        step, shard, init = L.build_hybrid_train_step(
            lcfg, mesh8, paddle.optimizer.AdamW(1e-2), num_microbatches=2,
            mp_overlap=mode)
        p = shard(L.init_hybrid_params(lcfg, jax.random.PRNGKey(0)))
        s = init(p)
        return collective_counts(
            step.lower(p, s, tokens, labels, LR).as_text())

    base, sp, ring = counts(None), counts("seq_parallel"), \
        counts("collective_matmul")
    assert sp["all_reduce"] < base["all_reduce"], (base, sp)
    assert sp["all_gather"] > base["all_gather"], (base, sp)
    assert ring["collective_permute"] > sp["collective_permute"], (sp, ring)


@pytest.mark.slow
def test_llama_seq_parallel_parity(mesh8):
    from paddle_tpu.models import llama as L
    lcfg = L.LlamaConfig(vocab_size=64, hidden_size=32, num_layers=4,
                         num_heads=4, num_kv_heads=2, intermediate_size=64,
                         max_seq_len=16, dtype=jnp.float32)
    tokens, labels = _data()

    def run(mode, steps=8):
        opt = paddle.optimizer.AdamW(1e-2)
        step, shard, init = L.build_hybrid_train_step(
            lcfg, mesh8, opt, num_microbatches=2, mp_overlap=mode)
        p = shard(L.init_hybrid_params(lcfg, jax.random.PRNGKey(0)))
        s = init(p)
        out = []
        for _ in range(steps):
            p, s, loss = step(p, s, tokens, labels, LR)
            out.append(float(loss))
        return out

    base, sp, ring = run(None), run("seq_parallel"), \
        run("collective_matmul")
    assert _max_rel(base, sp) < 1e-6, (base, sp)
    assert _max_rel(base, ring) < 1e-6, (base, ring)


# ---------------------------------------------------------------------------
# Telemetry: comms_bytes matches the analytic wire model
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", [None, "seq_parallel",
                                  "collective_matmul"])
def test_telemetry_comms_matches_analytic(mode):
    """dp=1 mesh (zero dp-sync bytes) so comms_bytes isolates the mp
    path; expected value re-derived here from the documented wire model
    — the engine must deposit exactly this constant every step."""
    import paddle_tpu.observability as obs
    mesh = dist.build_mesh({"dp": 1, "pp": 2, "mp": 4})
    tcfg = obs.TelemetryConfig(interval=2)
    step, shard, init = G.build_hybrid_train_step(
        CFG, mesh, paddle.optimizer.AdamW(1e-3), num_microbatches=2,
        mp_overlap=mode, telemetry=tcfg)
    p = shard(G.init_hybrid_params(CFG, jax.random.PRNGKey(0)))
    s = init(p)
    tokens, labels = _data(batch=4)
    host = obs.TelemetryHost(tcfg)
    for i in range(2):
        p, s, loss = step(p, s, tokens, labels, jnp.float32(1e-3))
        host.poll(s, i)

    mp, pp, M = 4, 2, 2
    b, S, H = 4, 16, CFG.hidden_size
    dt = 4  # fp32 activations
    a_blk = (b // M) * S * H * dt
    a_full = b * S * H * dt
    executed = (M + pp - 1) * (CFG.num_layers // pp)
    expected = obs.mp_wire_bytes(
        "allreduce" if mode is None else mode, mp,
        gemm_pair_bytes=2.0 * executed * a_blk,
        allreduce_bytes=2.0 * a_full + 4.0 * b * S * 4,
        scatter_bytes=a_full)
    got = host.series["comms_bytes"][-1]
    assert got == pytest.approx(expected, rel=1e-6), (got, expected)
    if mode is not None:
        assert tcfg.static["mp_mode"] == mode
        # sp modes pay the embed scatter's backward all-gather on top of
        # the (byte-identical) GEMM-pair and boundary terms
        f = (mp - 1) / mp
        base_expected = obs.mp_wire_bytes(
            "allreduce", mp, gemm_pair_bytes=2.0 * executed * a_blk,
            allreduce_bytes=2.0 * a_full + 4.0 * b * S * 4,
            scatter_bytes=a_full)
        assert expected == pytest.approx(base_expected + f * a_full)


def test_telemetry_comms_analytic_vpp():
    """The executed-block count is schedule-aware: the interleaved
    pipeline runs V*M+P-1 ticks of ONE L/(P*V)-layer chunk (not M+P-1
    ticks of the whole stage — the 1F1B formula overstates vpp wire
    bytes by the bubble difference)."""
    import paddle_tpu.observability as obs
    mesh = dist.build_mesh({"dp": 1, "pp": 2, "mp": 4})
    tcfg = obs.TelemetryConfig(interval=1)
    step, shard, init = G.build_hybrid_train_step(
        CFG, mesh, paddle.optimizer.AdamW(1e-3), num_microbatches=2,
        virtual_pp=2, mp_overlap="seq_parallel", telemetry=tcfg)
    p = shard(G.init_hybrid_params(CFG, jax.random.PRNGKey(0)))
    s = init(p)
    tokens, labels = _data(batch=4)
    host = obs.TelemetryHost(tcfg)
    p, s, _ = step(p, s, tokens, labels, jnp.float32(1e-3))
    host.poll(s, 0)

    mp, pp, M, V = 4, 2, 2, 2
    b, S, H, dt = 4, 16, CFG.hidden_size, 4
    a_blk = (b // M) * S * H * dt
    a_full = b * S * H * dt
    l_local = CFG.num_layers // pp
    executed = (V * M + pp - 1) * (l_local / V)
    expected = obs.mp_wire_bytes(
        "seq_parallel", mp, gemm_pair_bytes=2.0 * executed * a_blk,
        allreduce_bytes=2.0 * a_full + 4.0 * b * S * 4,
        scatter_bytes=a_full)
    assert host.series["comms_bytes"][-1] == pytest.approx(expected,
                                                           rel=1e-6)


# ---------------------------------------------------------------------------
# Validation (ISSUE 5 small fix)
# ---------------------------------------------------------------------------
def test_mp_ops_axis_validation():
    """c_identity / mp_allreduce / the sp entry points raise a typed
    InvalidArgumentError (not an opaque jax trace error) when the named
    axis is not in scope."""
    x = jnp.ones((2, 4, 8))
    for fn in (lambda: mp_ops.c_identity(x, "mp"),
               lambda: mp_ops.mp_allreduce(x, "mp"),
               lambda: mp_ops.c_split(x, "mp"),
               lambda: mp_ops.c_concat(x, "mp"),
               lambda: mp_ops.ag_matmul(x, jnp.ones((8, 4)), "mp"),
               lambda: mp_ops.matmul_rs(x, jnp.ones((8, 4)), "mp")):
        with pytest.raises(InvalidArgumentError, match="not in scope"):
            fn()
    # ...and a wrong NAME inside shard_map is equally typed
    mesh = dist.build_mesh({"mp": 8})

    def local(x):
        return mp_ops.mp_allreduce(x, "model")  # no such axis

    with pytest.raises(InvalidArgumentError, match="not in scope"):
        jax.jit(shard_map(local, mesh=mesh, in_specs=(P(),),
                          out_specs=P()))(x)

    # ...including on DIFFERENTIATED paths, where custom_vjp fwd rules
    # replace the primal (c_concat routes its fwd through the validated
    # entry)
    def local_grad(x):
        return jax.grad(
            lambda x: jnp.sum(mp_ops.c_concat(x, "model") ** 2))(x)

    with pytest.raises(InvalidArgumentError, match="not in scope"):
        jax.jit(shard_map(local_grad, mesh=mesh, in_specs=(P(),),
                          out_specs=P()))(x)


def test_mp_ops_shape_validation():
    mesh = dist.build_mesh({"mp": 8})
    x = jnp.ones((2, 4, 6))  # 6 not divisible by 8

    def split_bad(x):
        return mp_ops.c_split(x, "mp", dim=-1)

    with pytest.raises(EnforceNotMet, match="divisible"):
        jax.jit(shard_map(split_bad, mesh=mesh, in_specs=(P(),),
                          out_specs=P("mp")))(x)

    def rs_bad(x):
        return mp_ops.matmul_rs(x, jnp.ones((6, 6)), "mp")  # S=4 % 8 != 0

    with pytest.raises(EnforceNotMet, match="divisible"):
        jax.jit(shard_map(rs_bad, mesh=mesh, in_specs=(P(),),
                          out_specs=P(None, "mp", None)))(x)
