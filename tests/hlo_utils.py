"""HLO/StableHLO inspection helpers for collective-mix assertions.

The mp-overlap modes are distinguished by WHICH collectives the lowered
program contains (all-reduce pairs vs AG+RS vs ppermute rings), so tests
assert the expected mix per mode instead of trusting the flag plumbing —
a silent fallback to the replicated path would keep loss parity while
quietly re-exposing the blocking all-reduces. Counting happens on lowered
text (``jit(...).lower(...).as_text()``, StableHLO) and also understands
compiled-HLO spellings (``all-reduce`` / ``all-reduce-start``) so callers
can pass either form.
"""

import re

# op -> regexes across the dialects jax emits (StableHLO dots, HLO dashes;
# the \b/lookahead guards keep all_reduce from matching all_reduce_scatter
# and the -start/-done async forms from double-counting)
_COLLECTIVE_PATTERNS = {
    "all_reduce": (r"stablehlo\.all_reduce\b", r"mhlo\.all_reduce\b",
                   r"\ball-reduce(?:-start)?\("),
    "all_gather": (r"stablehlo\.all_gather\b", r"mhlo\.all_gather\b",
                   r"\ball-gather(?:-start)?\("),
    "reduce_scatter": (r"stablehlo\.reduce_scatter\b",
                       r"mhlo\.reduce_scatter\b",
                       r"\breduce-scatter(?:-start)?\("),
    "collective_permute": (r"stablehlo\.collective_permute\b",
                           r"mhlo\.collective_permute\b",
                           r"\bcollective-permute(?:-start)?\("),
}


def collective_counts(hlo_text: str) -> dict:
    """Count collective ops in lowered (StableHLO) or compiled (HLO) module
    text: {op_name: count} for all-reduce / all-gather / reduce-scatter /
    collective-permute. Ops inside scan/while bodies appear once (static
    program text), which is what mode assertions want."""
    return {name: sum(len(re.findall(p, hlo_text)) for p in pats)
            for name, pats in _COLLECTIVE_PATTERNS.items()}


def lowered_collective_counts(jitted, *args, **kwargs) -> dict:
    """collective_counts of ``jitted.lower(*args, **kwargs).as_text()``."""
    return collective_counts(jitted.lower(*args, **kwargs).as_text())
