"""FleetExecutor actor-runtime tests (reference pattern:
test/cpp/fleet_executor/ interceptor tests — ping-pong message loops,
compute pipelines with buffered credits, multi-carrier runs)."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet_executor import (
    SINK_ID, SOURCE_ID, AmplifierInterceptor, Carrier, ComputeInterceptor,
    FleetExecutor, Message, MessageType, TaskNode)
from paddle_tpu import _native

NATIVE = _native.load() is not None


def test_carrier_local_send_recv():
    c = Carrier(rank=0)
    c.register(1)
    c.register(2)
    assert c.send(Message(1, 2, MessageType.DATA_IS_READY, 7, b"hi"))
    m = c.recv(2, timeout_ms=2000)
    assert m is not None and m.src == 1 and m.scope == 7 and m.payload == b"hi"
    assert c.recv(2, timeout_ms=50) is None  # empty mailbox times out
    assert not c.send(Message(1, 99, MessageType.DATA_IS_READY))  # no route
    c.stop()


def test_pipeline_three_stage_runs_all_microbatches():
    n_mb = 8
    log = {1: [], 2: [], 3: []}

    def mk(run_order):
        def fn(scope):
            log[run_order].append(scope)
            return scope * run_order
        return fn

    t1 = TaskNode(rank=0, task_id=1, max_run_times=n_mb, run_fn=mk(1))
    t2 = TaskNode(rank=0, task_id=2, max_run_times=n_mb, run_fn=mk(2))
    t3 = TaskNode(rank=0, task_id=3, max_run_times=n_mb, run_fn=mk(3))
    t1.add_downstream_task(2, 2); t2.add_upstream_task(1, 2)
    t2.add_downstream_task(3, 2); t3.add_upstream_task(2, 2)

    ex = FleetExecutor([t1, t2, t3], rank=0)
    try:
        assert ex.run(timeout=30)
        for k in (1, 2, 3):
            assert log[k] == list(range(n_mb)), (k, log[k])
        assert ex.results(3) == [3 * s for s in range(n_mb)]
    finally:
        ex.shutdown()


def test_buffer_credit_limits_in_flight():
    # stage 2 sleeps; stage 1 must never run more than buffer_size ahead
    n_mb, buf = 6, 1
    lead = []
    s1_runs = []
    s2_runs = []

    def f1(scope):
        s1_runs.append(scope)
        lead.append(len(s1_runs) - len(s2_runs))

    def f2(scope):
        time.sleep(0.02)
        s2_runs.append(scope)

    t1 = TaskNode(rank=0, task_id=1, max_run_times=n_mb, run_fn=f1)
    t2 = TaskNode(rank=0, task_id=2, max_run_times=n_mb, run_fn=f2)
    t1.add_downstream_task(2, buf); t2.add_upstream_task(1, buf)
    ex = FleetExecutor([t1, t2], rank=0)
    try:
        assert ex.run(timeout=30)
        assert max(lead) <= buf + 1  # credit window respected
    finally:
        ex.shutdown()


def test_run_is_repeatable():
    # second run must re-execute every stage (review regression: compute
    # steps and sink counts RESET between runs)
    n_mb = 4
    ran = []
    t1 = TaskNode(rank=0, task_id=1, max_run_times=n_mb,
                  run_fn=lambda s: ran.append(s))
    ex = FleetExecutor([t1], rank=0)
    try:
        assert ex.run(timeout=30)
        assert ex.run(timeout=30)
        assert ran == list(range(n_mb)) * 2
    finally:
        ex.shutdown()


def test_shutdown_with_slow_run_fn_does_not_crash():
    # review regression: a run_fn still executing during shutdown must not
    # race a freed native carrier
    started = threading.Event()

    def slow(scope):
        started.set()
        time.sleep(1.0)

    t1 = TaskNode(rank=0, task_id=1, max_run_times=4, run_fn=slow)
    ex = FleetExecutor([t1], rank=0)
    ex.carrier.send(Message(SOURCE_ID, SOURCE_ID, MessageType.START))
    assert started.wait(10)
    ex.shutdown()  # must join the thread, then free — no segfault
    assert ex.carrier.recv(1, timeout_ms=10) is None  # safe after destroy


def test_amplifier_runs_every_k():
    n_mb, k = 8, 4
    ran = []
    t1 = TaskNode(rank=0, task_id=1, max_run_times=n_mb)
    t2 = TaskNode(rank=0, task_id=2, max_run_times=n_mb,
                  run_fn=lambda s: ran.append(s), node_type="Amplifier")
    t1.add_downstream_task(2, 2); t2.add_upstream_task(1, 2)
    ex = FleetExecutor([t1, t2], rank=0)
    # re-wire the amplifier period (constructor default is every step)
    amp = ex.interceptors[2]
    amp.run_per_steps = k
    amp.run_at_offset = k - 1
    try:
        assert ex.run(timeout=30)
        assert ran == [3, 7]  # gradient-merge style: every k-th micro-batch
    finally:
        ex.shutdown()


@pytest.mark.skipif(not NATIVE, reason="native runtime unavailable")
def test_cross_carrier_tcp_bus():
    # two carriers in one process connected over loopback = the reference's
    # multi-node MessageBus topology (test/cpp/fleet_executor pattern)
    c0 = Carrier(rank=0, use_native=True)
    c1 = Carrier(rank=1, use_native=True)
    p0, p1 = c0.listen(), c1.listen()
    assert p0 > 0 and p1 > 0
    assert c0.connect(1, "127.0.0.1", p1)
    assert c1.connect(0, "127.0.0.1", p0)
    c0.register(10)
    c1.register(20)
    c0.set_route(20, 1)
    c1.set_route(10, 0)
    try:
        assert c0.send(Message(10, 20, MessageType.DATA_IS_READY, 3,
                               b"x" * 1000))
        m = c1.recv(20, timeout_ms=5000)
        assert m is not None and m.src == 10 and m.payload == b"x" * 1000
        # reply path
        assert c1.send(Message(20, 10, MessageType.DATA_IS_USELESS, 3))
        r = c0.recv(10, timeout_ms=5000)
        assert r is not None and r.type == MessageType.DATA_IS_USELESS
    finally:
        c0.stop()
        c1.stop()


@pytest.mark.skipif(not NATIVE, reason="native runtime unavailable")
def test_two_rank_pipeline_over_bus():
    # rank 0 holds stage 1, rank 1 holds stage 2; each rank runs its own
    # FleetExecutor; stage edges cross the bus
    n_mb = 4
    got = []
    t1 = TaskNode(rank=0, task_id=1, max_run_times=n_mb,
                  run_fn=lambda s: s)
    t2 = TaskNode(rank=1, task_id=2, max_run_times=n_mb,
                  run_fn=lambda s: got.append(s))
    t1.add_downstream_task(2, 2)
    t2.add_upstream_task(1, 2)

    ex0 = FleetExecutor([t1, TaskNode(rank=1, task_id=2)], rank=0,
                        num_micro_batches=n_mb, cluster={})
    ex1 = FleetExecutor([TaskNode(rank=0, task_id=1), t2], rank=1,
                        num_micro_batches=n_mb, cluster={})
    try:
        assert ex0.carrier.connect(1, "127.0.0.1", ex1.port)
        assert ex1.carrier.connect(0, "127.0.0.1", ex0.port)
        done1 = threading.Event()
        r1 = threading.Thread(target=lambda: (ex1.run(timeout=30),
                                              done1.set()))
        r1.start()
        assert ex0.run(timeout=30)
        assert done1.wait(30)
        assert sorted(got) == list(range(n_mb))
    finally:
        ex0.shutdown()
        ex1.shutdown()
