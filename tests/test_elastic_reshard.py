"""Elastic-scale resilience tests (ISSUE 7): topology-agnostic checkpoints
(schema-v2 layout metadata, v1 byte-compat), reshard-on-load across mesh
changes (pp remap, zero1 on<->off, dp/mp regroup), carry remap policies
(comm_ef reset + JSONL event, telemetry reinit, fp8 amax-tick rescale),
mid-save torn-chunk faults, checkpoint-root hardening, the inspect CLI and
the resilient driver's reshard-on-resume path.

Fast tier: pure-checkpoint and driver-level tests (no hybrid-engine
compiles). The hybrid-engine elastic legs (pp-shrink bitwise parity, the
ISSUE dp2pp2mp2<->dp4pp2mp1 pair, zero1 toggle, fp8 carries) and the
2-process elastic spawn ride the slow tier.
"""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.checkpoint.metadata import Metadata
from paddle_tpu.distributed.resilience import (
    FaultInjected, commit_checkpoint, faults, latest_checkpoint)
from paddle_tpu.distributed.resilience.commit import checkpoint_step
from paddle_tpu.distributed.resilience.driver import run_resilient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mesh_of(dims, n=None):
    devs = jax.devices()
    return dist.build_mesh(dims, devices=None if n is None else devs[:n])


def shard(x, mesh, spec):
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))


def _arm(spec):
    paddle.set_flags({"FLAGS_fault_inject": spec})


@pytest.fixture
def reshard_on():
    paddle.set_flags({"FLAGS_ckpt_reshard": True})
    yield
    paddle.set_flags({"FLAGS_ckpt_reshard": False})


@pytest.fixture
def jsonl_events(tmp_path):
    """Route JSONL events to a temp file and hand back a reader."""
    p = tmp_path / "events.jsonl"
    paddle.set_flags({"FLAGS_telemetry_jsonl": str(p)})
    yield lambda: ([json.loads(l) for l in p.read_text().splitlines()]
                   if p.exists() else [])
    paddle.set_flags({"FLAGS_telemetry_jsonl": ""})


# -- schema / byte-compat ----------------------------------------------------
def test_flags_off_metadata_is_v1_byte_compatible(tmp_path):
    """Default (FLAGS_ckpt_reshard off) metadata must carry EXACTLY the v1
    instance fields — the pickle is byte-identical to the pre-elastic
    format (same class, same __dict__)."""
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"w": shard(jnp.ones((8, 2)), mesh, P("dp"))},
                         str(tmp_path))
    md = ckpt.load_metadata(str(tmp_path))
    assert sorted(md.__dict__) == ["flat_mapping", "misc",
                                   "state_dict_metadata", "storage_metadata"]
    # v2 accessors fall back to the class defaults
    assert md.schema_version == 1 and md.layout is None


def test_v1_pickle_loads_with_compat_defaults(tmp_path):
    """A checkpoint written before the schema existed (simulated by
    stripping the v2 fields) still loads, reports v1, and never triggers
    the reshard path."""
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"w": shard(jnp.full((4,), 7.0), mesh, P())},
                         str(tmp_path))
    raw = pickle.loads((tmp_path / "0.metadata").read_bytes())
    raw.__dict__.pop("schema_version", None)
    raw.__dict__.pop("layout", None)
    (tmp_path / "0.metadata").write_bytes(pickle.dumps(raw))
    md = ckpt.load_metadata(str(tmp_path))
    assert md.schema_version == 1 and md.layout is None
    tgt = {"w": shard(jnp.zeros((4,)), mesh, P())}
    assert ckpt.layout_mismatch(md, tgt) is None  # nothing to compare
    out = ckpt.load_state_dict(tgt, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["w"]), np.full((4,), 7.0))


def test_layout_recorded_with_flag_on(tmp_path, reshard_on):
    mesh = mesh_of({"dp": 2, "mp": 4})
    state = {"m": {"w": shard(jnp.ones((8, 4)), mesh, P("mp", None)),
                   "b": shard(jnp.ones((4,)), mesh, P())},
             "step": 3}
    ckpt.save_state_dict(state, str(tmp_path),
                         layout_extra={"zero1": False})
    md = ckpt.load_metadata(str(tmp_path))
    assert md.schema_version == 2
    lay = md.layout
    assert lay.mesh == {"dp": 2, "mp": 4}
    assert lay.specs["m.w"] == ("mp", None)
    assert lay.global_shapes["m.w"] == (8, 4)
    assert lay.replication["m.w"] == 2   # replicated over dp only
    assert lay.replication["m.b"] == 8   # fully replicated
    assert lay.extra["zero1"] is False


# -- reshard-on-load: pure checkpoint level ----------------------------------
def test_reshard_load_onto_disjoint_mesh(tmp_path, reshard_on):
    mesh_a = mesh_of({"dp": 2, "mp": 4})
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    ckpt.save_state_dict({"w": shard(w, mesh_a, P("mp", "dp"))},
                         str(tmp_path))
    mesh_b = mesh_of({"x": 4}, n=4)
    tgt = {"w": shard(jnp.zeros((8, 8)), mesh_b, P(None, "x"))}
    md = ckpt.load_metadata(str(tmp_path))
    mm = ckpt.layout_mismatch(md, tgt)
    assert mm is not None and "mesh" in mm
    out = ckpt.load_resharded(tgt, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["w"]), w)
    assert out["w"].sharding.spec == P(None, "x")


def test_zero1_toggle_roundtrip(tmp_path, reshard_on):
    """dp-sharded optimizer moments (zero1 on) load bitwise into replicated
    targets (zero1 off) and back — global offsets make the two forms
    interchangeable."""
    mesh = mesh_of({"dp": 8})
    m = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    ckpt.save_state_dict({"opt": {"m1": shard(m, mesh, P("dp"))}}, d1,
                         layout_extra={"zero1": True})
    tgt = {"opt": {"m1": shard(jnp.zeros((16, 4)), mesh, P())}}
    out = ckpt.load_resharded(tgt, d1, layout_extra={"zero1": False})
    np.testing.assert_array_equal(np.asarray(out["opt"]["m1"]), m)
    # and back: replicated save -> dp-sharded load
    ckpt.save_state_dict(out, d2, layout_extra={"zero1": False})
    tgt2 = {"opt": {"m1": shard(jnp.zeros((16, 4)), mesh, P("dp"))}}
    out2 = ckpt.load_resharded(tgt2, d2, layout_extra={"zero1": True})
    np.testing.assert_array_equal(np.asarray(out2["opt"]["m1"]), m)


def test_pp_vpp_relayout_on_load(tmp_path, reshard_on):
    """Stacked-[L] block leaves stored in (pp=2, vpp=2) chunk-major order
    load into a (pp=2, vpp=1) template in canonical order — the in-stream
    half of pp_adaptor, driven purely by the recorded layout."""
    from paddle_tpu.distributed.fleet.meta_parallel.pp_utils.spmd_pipeline \
        import vpp_block_permutation
    mesh = mesh_of({"pp": 2}, n=2)
    L = 8
    canon = np.arange(L * 3, dtype=np.float32).reshape(L, 3)
    order = vpp_block_permutation(L, 2, 2)
    stored = canon[np.asarray(order)]
    src_pp = {"num_layers": L, "pp": 2, "vpp": 2,
              "stacked_components": ["blocks"]}
    dst_pp = {"num_layers": L, "pp": 2, "vpp": 1,
              "stacked_components": ["blocks"]}
    ckpt.save_state_dict(
        {"blocks": {"w": shard(stored, mesh, P("pp"))},
         "lnf": shard(jnp.ones((3,)), mesh, P())},
        str(tmp_path), layout_extra={"pp": src_pp})
    tgt = {"blocks": {"w": shard(jnp.zeros((L, 3)), mesh, P("pp"))},
           "lnf": shard(jnp.zeros((3,)), mesh, P())}
    md = ckpt.load_metadata(str(tmp_path))
    assert ckpt.layout_mismatch(md, tgt, layout_extra={"pp": dst_pp})[
        "pp_relayout"] is True
    out = ckpt.load_resharded(tgt, str(tmp_path),
                              layout_extra={"pp": dst_pp})
    np.testing.assert_array_equal(np.asarray(out["blocks"]["w"]), canon)
    # non-stacked leaves pass through untouched
    np.testing.assert_array_equal(np.asarray(out["lnf"]), np.ones((3,)))
    # identity relayout (same layout both sides) short-circuits
    same = ckpt.load_resharded(
        {"blocks": {"w": shard(jnp.zeros((L, 3)), mesh, P("pp"))},
         "lnf": shard(jnp.zeros((3,)), mesh, P())},
        str(tmp_path), layout_extra={"pp": src_pp})
    np.testing.assert_array_equal(np.asarray(same["blocks"]["w"]), stored)


def test_comm_ef_reset_on_plan_change_with_event(tmp_path, reshard_on,
                                                 jsonl_events):
    """comm_ef residuals reset to the template's zeros (with an explicit
    JSONL event) when the bucket plan changed; identical plans load."""
    mesh = mesh_of({"dp": 8})
    res = np.random.RandomState(1).randn(64).astype(np.float32)
    plan_a = {"n_dev": 8, "buckets": [64]}
    ckpt.save_state_dict(
        {"state": {"comm_ef": {"0": shard(res, mesh, P("dp"))}}},
        str(tmp_path), layout_extra={"comm_plan": plan_a})
    # same plan -> residuals transfer
    tgt = {"state": {"comm_ef": {"0": shard(jnp.zeros((64,)), mesh,
                                            P("dp"))}}}
    out = ckpt.load_resharded(tgt, str(tmp_path),
                              layout_extra={"comm_plan": plan_a})
    np.testing.assert_array_equal(np.asarray(out["state"]["comm_ef"]["0"]),
                                  res)
    # changed plan -> reset to the template zeros + event
    tgt2 = {"state": {"comm_ef": {"0": shard(jnp.zeros((64,)), mesh,
                                             P("dp"))}}}
    out2 = ckpt.load_resharded(tgt2, str(tmp_path),
                               layout_extra={"comm_plan": {"n_dev": 4,
                                                           "buckets": [64]}})
    np.testing.assert_array_equal(np.asarray(out2["state"]["comm_ef"]["0"]),
                                  np.zeros((64,)))
    events = [e for e in jsonl_events() if e["event"] == "ckpt_carry_reset"]
    assert events and events[-1]["reason"] == "plan_changed"
    assert events[-1]["key"] == "state.comm_ef.0"


def test_comm_ef_missing_from_checkpoint_resets(tmp_path, reshard_on):
    """A template whose comm_ef carry does not exist in the checkpoint at
    all (saved without comm overlap) keeps its fresh zeros instead of
    raising KeyError."""
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"state": {"w": shard(jnp.ones((8,)), mesh,
                                               P("dp"))}}, str(tmp_path))
    tgt = {"state": {"w": shard(jnp.zeros((8,)), mesh, P("dp")),
                     "comm_ef": {"0": shard(jnp.zeros((16,)), mesh,
                                            P("dp"))}}}
    out = ckpt.load_resharded(tgt, str(tmp_path),
                              layout_extra={"comm_plan": {"n_dev": 8}})
    np.testing.assert_array_equal(np.asarray(out["state"]["w"]),
                                  np.ones((8,)))
    np.testing.assert_array_equal(np.asarray(out["state"]["comm_ef"]["0"]),
                                  np.zeros((16,)))


def test_comm_ef_resets_on_mesh_regroup(tmp_path, reshard_on, jsonl_events):
    """Residuals are LOCAL rounding errors: a mesh regroup at the same
    device count (dp8 -> dp4·mp2) reassigns shards to ranks even though
    the plan fingerprint and every global shape are unchanged — they must
    reset, not transfer verbatim."""
    res = np.random.RandomState(2).randn(64).astype(np.float32)
    plan = {"n_dev": 8, "buckets": [64]}
    ckpt.save_state_dict(
        {"state": {"comm_ef": {"0": shard(res, mesh_of({"dp": 8}),
                                          P("dp"))}}},
        str(tmp_path), layout_extra={"comm_plan": plan})
    mesh_b = mesh_of({"dp": 4, "mp": 2})
    tgt = {"state": {"comm_ef": {"0": shard(jnp.zeros((64,)), mesh_b,
                                            P("dp"))}}}
    out = ckpt.load_resharded(tgt, str(tmp_path),
                              layout_extra={"comm_plan": plan})
    np.testing.assert_array_equal(np.asarray(out["state"]["comm_ef"]["0"]),
                                  np.zeros((64,)))
    ev = [e for e in jsonl_events() if e["event"] == "ckpt_carry_reset"]
    assert ev and ev[-1]["reason"] == "mesh_changed"


def test_comm_ef_resets_on_pp_relayout(tmp_path, reshard_on, jsonl_events):
    """A (pp, vpp) relayout at a fixed mesh and plan reorders which layers
    back each rank's buckets — the residuals' owners changed, so they
    reset (same-mesh same-plan loads still transfer, asserted above)."""
    mesh = mesh_of({"dp": 8})
    res = np.random.RandomState(3).randn(64).astype(np.float32)
    plan = {"n_dev": 8, "buckets": [64]}
    pp_a = {"num_layers": 4, "pp": 2, "vpp": 1}
    ckpt.save_state_dict(
        {"state": {"comm_ef": {"0": shard(res, mesh, P("dp"))}}},
        str(tmp_path), layout_extra={"comm_plan": plan, "pp": pp_a})
    tgt = {"state": {"comm_ef": {"0": shard(jnp.zeros((64,)), mesh,
                                            P("dp"))}}}
    out = ckpt.load_resharded(
        tgt, str(tmp_path),
        layout_extra={"comm_plan": plan,
                      "pp": {"num_layers": 4, "pp": 2, "vpp": 2}})
    np.testing.assert_array_equal(np.asarray(out["state"]["comm_ef"]["0"]),
                                  np.zeros((64,)))
    ev = [e for e in jsonl_events() if e["event"] == "ckpt_carry_reset"]
    assert ev and ev[-1]["reason"] == "pp_relayout"


def test_telemetry_reinit_policy(tmp_path, reshard_on, jsonl_events):
    """Telemetry ring buffers reinitialize on the reshard path — stale
    per-topology series (comms bytes) must not leak onto the new mesh."""
    mesh = mesh_of({"dp": 8})
    buf = {"data": shard(jnp.full((4, 3), 9.0), mesh, P()),
           "count": shard(jnp.asarray(7, jnp.int32), mesh, P())}
    ckpt.save_state_dict({"state": {"telemetry": buf,
                                    "w": shard(jnp.ones((8,)), mesh,
                                               P("dp"))}}, str(tmp_path))
    tgt = {"state": {"telemetry": {
        "data": shard(jnp.zeros((4, 3)), mesh, P()),
        "count": shard(jnp.asarray(0, jnp.int32), mesh, P())},
        "w": shard(jnp.zeros((8,)), mesh, P("dp"))}}
    out = ckpt.load_resharded(tgt, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["state"]["telemetry"]
                                             ["data"]), np.zeros((4, 3)))
    assert int(out["state"]["telemetry"]["count"]) == 0
    np.testing.assert_array_equal(np.asarray(out["state"]["w"]),
                                  np.ones((8,)))
    assert any(e["event"] == "ckpt_carry_reinit" for e in jsonl_events())


def test_fp8_amax_ticks_rescale(tmp_path, reshard_on):
    """fp8 histories/scales rescale by T_new/T_old across a pp-degree
    change (pipelined amax observations sum over T = M + P - 1 ticks)."""
    mesh = mesh_of({"dp": 8})
    hist = np.arange(12, dtype=np.float32).reshape(3, 4)
    scale = np.asarray([3.0, 6.0, 9.0], np.float32)
    st = {"fp8_meta": {"scale": {"qkv": {"x": shard(scale, mesh, P())}},
                       "amax_history": {"qkv": {"x": shard(hist, mesh,
                                                           P())}}}}
    ckpt.save_state_dict(st, str(tmp_path),
                         layout_extra={"fp8_amax_ticks": 3})
    tgt = {"fp8_meta": {
        "scale": {"qkv": {"x": shard(jnp.zeros((3,)), mesh, P())}},
        "amax_history": {"qkv": {"x": shard(jnp.zeros((3, 4)), mesh,
                                            P())}}}}
    out = ckpt.load_resharded(tgt, str(tmp_path),
                              layout_extra={"fp8_amax_ticks": 2})
    np.testing.assert_allclose(
        np.asarray(out["fp8_meta"]["amax_history"]["qkv"]["x"]),
        hist * (2.0 / 3.0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(out["fp8_meta"]["scale"]["qkv"]["x"]),
        scale * (2.0 / 3.0), rtol=1e-6)


def test_fp8_ticks_only_change_is_a_mismatch(tmp_path, reshard_on):
    """A ticks-only change (e.g. num_microbatches at a fixed mesh) must
    route resume through the reshard path — the plain loader would skip
    the amax rescale and leave the quantization grids off by T_a/T_b."""
    mesh = mesh_of({"dp": 8})
    st = {"w": shard(jnp.ones((8,)), mesh, P("dp"))}
    ckpt.save_state_dict(st, str(tmp_path),
                         layout_extra={"fp8_amax_ticks": 3})
    md = ckpt.load_metadata(str(tmp_path))
    tgt = {"w": shard(jnp.zeros((8,)), mesh, P("dp"))}
    assert ckpt.layout_mismatch(md, tgt) is None  # no target ticks: plain
    mm = ckpt.layout_mismatch(md, tgt, layout_extra={"fp8_amax_ticks": 5})
    assert mm == {"fp8_amax_ticks": {"saved": 3, "target": 5}}
    assert ckpt.layout_mismatch(
        md, tgt, layout_extra={"fp8_amax_ticks": 3}) is None


def test_follow_carry_missing_keeps_template(tmp_path, reshard_on,
                                             jsonl_events):
    """Enabling fp8 at RESUME time (checkpoint saved without it): the
    'follow'-policy carry is absent from the checkpoint, so the template's
    fresh fp8_meta is kept (with an event) instead of raising KeyError."""
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"opt": {"w": shard(jnp.ones((8,)), mesh,
                                             P("dp"))}}, str(tmp_path))
    fresh = shard(jnp.full((3,), 0.125), mesh, P())
    tgt = {"opt": {"w": shard(jnp.zeros((8,)), mesh, P("dp")),
                   "fp8_meta": {"scale": {"qkv": {"x": fresh}}}}}
    out = ckpt.load_resharded(tgt, str(tmp_path))
    np.testing.assert_array_equal(
        np.asarray(out["opt"]["fp8_meta"]["scale"]["qkv"]["x"]),
        np.full((3,), 0.125))
    np.testing.assert_array_equal(np.asarray(out["opt"]["w"]), np.ones(8))
    ev = [e for e in jsonl_events() if e["event"] == "ckpt_carry_reset"]
    assert ev and ev[-1]["reason"] == "missing"


def test_unknown_carry_policy_rejected(tmp_path, reshard_on):
    """A typo'd policy name must fail loudly, not silently transfer the
    carry verbatim (the corruption the policies exist to prevent)."""
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"w": shard(jnp.ones((8,)), mesh, P("dp"))},
                         str(tmp_path))
    tgt = {"w": shard(jnp.zeros((8,)), mesh, P("dp"))}
    with pytest.raises(ValueError, match="unknown carry policy"):
        ckpt.load_resharded(tgt, str(tmp_path),
                            layout_extra={"carries": {"comm_ef": "reset"}})


def test_missing_key_without_policy_raises(tmp_path, reshard_on):
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"a": shard(jnp.ones((8,)), mesh, P("dp"))},
                         str(tmp_path))
    with pytest.raises(KeyError):
        ckpt.load_resharded({"b": shard(jnp.ones((8,)), mesh, P("dp"))},
                            str(tmp_path))


# -- mid-save faults ---------------------------------------------------------
def test_torn_chunk_crash_falls_back_to_previous_commit(tmp_path):
    """A chunk file torn mid-save (storage layer lost the tail after the
    rename) crashes the save; recovery must fall back to the previous
    committed step and never surface the torn directory."""
    d = str(tmp_path)
    good = commit_checkpoint({"w": jnp.ones((4,))}, d, 1)
    _arm("ckpt/torn_chunk:1")
    with pytest.raises(FaultInjected):
        commit_checkpoint({"w": jnp.full((4,), 2.0)}, d, 2)
    _arm("")
    # the staging straggler holds a REALLY torn file
    torn = [f for f in os.listdir(d) if f.endswith(".tmp")]
    assert torn, os.listdir(d)
    assert latest_checkpoint(d) == good          # straggler GC'd, good wins
    assert sorted(os.listdir(d)) == ["step_00000001"]
    out = ckpt.load_state_dict({"w": jnp.zeros((4,))}, good)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(4))


def test_torn_chunk_kill_sequence_never_discoverable(tmp_path):
    """Spawned hard-kill variant: no sequence of torn-chunk crashes leaves
    latest_checkpoint pointing at an unloadable directory."""
    worker = os.path.join(REPO, "tests", "resilience_worker.py")
    d = str(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               FLAGS_fault_inject="ckpt/torn_chunk:2:kill")
    p = subprocess.run([sys.executable, worker, "train", d],
                       env=env, capture_output=True, text=True, timeout=180)
    assert p.returncode == faults.FAULT_EXIT_CODE, (p.stdout, p.stderr)
    latest = latest_checkpoint(d)
    assert latest is not None and checkpoint_step(latest) == 3
    out = ckpt.load_state_dict(
        {"step": 0, "state": {"w": jnp.zeros((4,), jnp.float32)}}, latest)
    assert out["step"] == 3


# -- checkpoint-root hardening -----------------------------------------------
def test_latest_checkpoint_skips_foreign_entries(tmp_path, jsonl_events):
    d = str(tmp_path)
    good = commit_checkpoint({"w": jnp.ones((2,))}, d, 1)
    (tmp_path / "notes.txt").write_text("operator scratch")
    (tmp_path / "step_abc").mkdir()          # foreign dir, step-ish name
    (tmp_path / "experiment").mkdir()
    assert latest_checkpoint(d) == good
    # foreign entries are never garbage-collected
    assert (tmp_path / "notes.txt").exists()
    assert (tmp_path / "step_abc").is_dir()
    assert (tmp_path / "experiment").is_dir()
    ev = [e for e in jsonl_events()
          if e["event"] == "ckpt_root_foreign_entries"]
    assert ev and ev[-1]["count"] == 3


def test_latest_checkpoint_falls_back_past_corrupt_metadata(tmp_path,
                                                            jsonl_events):
    """A COMMITTED dir whose metadata was corrupted after the fact is
    skipped with a warning event; discovery falls back to the previous
    committed step instead of handing back an unloadable dir."""
    d = str(tmp_path)
    good = commit_checkpoint({"w": jnp.ones((2,))}, d, 1)
    bad = commit_checkpoint({"w": jnp.full((2,), 2.0)}, d, 2)
    (tmp_path / "step_00000002" / "0.metadata").write_bytes(b"garbage")
    assert latest_checkpoint(d) == good
    ev = [e for e in jsonl_events()
          if e["event"] == "ckpt_unloadable_skipped"]
    assert ev and "step_00000002" in ev[-1]["path"]
    # validate=False restores the pure marker check
    assert latest_checkpoint(d, validate=False) == bad


def test_latest_checkpoint_falls_back_past_missing_data_file(tmp_path):
    d = str(tmp_path)
    good = commit_checkpoint({"w": jnp.ones((2,))}, d, 1)
    commit_checkpoint({"w": jnp.full((2,), 2.0)}, d, 2)
    os.unlink(tmp_path / "step_00000002" / "0_0.distcp")
    assert latest_checkpoint(d) == good


def test_run_resilient_resumes_past_corrupted_newest(tmp_path):
    """End to end: corrupt the newest commit, run_resilient resumes from
    the previous one and reaches the same final state as an uninterrupted
    run."""
    def sgd(state, i):
        x = jax.random.normal(jax.random.PRNGKey(i), (4,), jnp.float32)
        return {"w": state["w"] - 0.2 * (state["w"] - x)}, float(i)

    w0 = {"w": jnp.zeros((4,), jnp.float32)}
    golden, _ = run_resilient(sgd, w0, steps=6,
                              ckpt_dir=str(tmp_path / "a"), ckpt_every=2)
    d = str(tmp_path / "b")
    run_resilient(sgd, w0, steps=4, ckpt_dir=d, ckpt_every=2)
    (tmp_path / "b" / "step_00000004" / "0.metadata").write_bytes(b"junk")
    state, info = run_resilient(sgd, w0, steps=6, ckpt_dir=d, ckpt_every=2)
    assert info["resumed_from"].endswith("step_00000002")
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(golden["w"]))


# -- resilient driver: elastic resume ----------------------------------------
def test_run_resilient_reshards_on_mesh_change(tmp_path, reshard_on,
                                               jsonl_events):
    """Kill on mesh A (8 devices), resume on mesh B (4 devices): the driver
    detects the recorded mismatch, reshards, and the trajectory is bitwise
    the B-only golden's (elementwise update — no cross-replica math)."""
    def make_step(mesh):
        spec = NamedSharding(mesh, P(mesh.axis_names[0]))

        @jax.jit
        def upd(w, x):
            return w - 0.5 * (w - x)

        def step_fn(state, i):
            x = jax.device_put(
                jax.random.normal(jax.random.PRNGKey(i), (8, 2),
                                  jnp.float32), spec)
            w = upd(state["w"], x)
            return {"w": w}, float(jnp.sum(w))
        return step_fn

    mesh_a, mesh_b = mesh_of({"dp": 8}), mesh_of({"x": 4}, n=4)
    w0 = np.zeros((8, 2), np.float32)

    golden_losses = {}
    run_resilient(make_step(mesh_b),
                  {"w": shard(w0, mesh_b, P("x"))}, steps=6,
                  ckpt_dir=str(tmp_path / "gold"), ckpt_every=0,
                  resume=False,
                  on_step=lambda i, l: golden_losses.__setitem__(i, l))

    d = str(tmp_path / "run")
    _arm("loop/before_step:4")
    with pytest.raises(FaultInjected):
        run_resilient(make_step(mesh_a),
                      {"w": shard(w0, mesh_a, P("dp"))}, steps=6,
                      ckpt_dir=d, ckpt_every=3)
    _arm("")
    losses = {}
    state, info = run_resilient(
        make_step(mesh_b), {"w": shard(w0, mesh_b, P("x"))}, steps=6,
        ckpt_dir=d, ckpt_every=3,
        on_step=lambda i, l: losses.__setitem__(i, l))
    assert info["resharded"] is True
    assert info["resumed_from"].endswith("step_00000003")
    assert state["w"].sharding.spec == P("x")
    for i in (3, 4, 5):
        assert losses[i] == golden_losses[i]  # bitwise
    assert any(e["event"] == "resilience_reshard_resume"
               for e in jsonl_events())


def test_run_resilient_flag_off_keeps_plain_load(tmp_path):
    """FLAGS_ckpt_reshard off: resume goes through the plain loader even
    when the checkpoint carries no layout — today's behavior, untouched."""
    def sgd(state, i):
        return {"w": state["w"] + 1.0}, 1.0
    d = str(tmp_path)
    run_resilient(sgd, {"w": jnp.zeros((4,))}, steps=2, ckpt_dir=d,
                  ckpt_every=2)
    state, info = run_resilient(sgd, {"w": jnp.zeros((4,))}, steps=4,
                                ckpt_dir=d, ckpt_every=2)
    assert info["resumed_from"].endswith("step_00000002")
    assert info["resharded"] is False
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full((4,), 4.0))
    md = ckpt.load_metadata(os.path.join(d, "step_00000004"))
    assert md.schema_version == 1 and md.layout is None


# -- inspect CLI -------------------------------------------------------------
def test_inspect_cli_human_and_json(tmp_path, reshard_on, capsys):
    from paddle_tpu.distributed.checkpoint.__main__ import main
    mesh = mesh_of({"dp": 2, "mp": 4})
    d = str(tmp_path)
    commit_checkpoint(
        {"step": 5, "state": {"w": shard(jnp.ones((8, 4)), mesh,
                                         P("mp", None))}},
        d, 5, layout_extra={"zero1": True, "pp": {"num_layers": 2, "pp": 1,
                                                  "vpp": 1}})
    # a commit ROOT resolves to its newest committed step
    assert main(["inspect", d]) == 0
    out = capsys.readouterr().out
    assert "schema version: 2" in out
    assert "dp2 x mp4" in out
    assert "state.w: (8, 4) float32" in out
    assert "extra.zero1: True" in out
    # --json on the step dir, with the per-file chunk map
    assert main(["inspect", os.path.join(d, "step_00000005"), "--json"]) == 0
    desc = json.loads(capsys.readouterr().out)
    assert desc["schema_version"] == 2
    assert desc["layout"]["mesh"] == {"dp": 2, "mp": 4}
    assert desc["tensors"]["state.w"]["global_shape"] == [8, 4]
    assert desc["tensors"]["state.w"]["spec"] == ["mp", None]
    assert "misc_keys" in desc and "step" in desc["misc_keys"]
    assert sum(len(v) for v in desc["files"].values()) == desc["n_chunks"]


def test_inspect_cli_v1_checkpoint(tmp_path, capsys):
    from paddle_tpu.distributed.checkpoint.__main__ import main
    mesh = mesh_of({"dp": 8})
    ckpt.save_state_dict({"w": shard(jnp.ones((8,)), mesh, P("dp"))},
                         str(tmp_path))
    assert main(["inspect", str(tmp_path), "--chunks"]) == 0
    out = capsys.readouterr().out
    assert "schema version: 1" in out and "no layout metadata" in out
    assert "0_0.distcp" in out


# -- hybrid-engine elastic legs (slow tier) ----------------------------------
def _hybrid_setup(mesh, zero1=False, fp8=False, num_microbatches=2):
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                      num_heads=4, max_seq_len=16, dtype=jnp.float32)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2)
    step, shard_params, init_state = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=num_microbatches, zero1_dp=zero1,
        fp8=fp8)
    params = shard_params(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    state = {"params": params, "opt": init_state(params)}
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))

    def step_fn(st, i):
        del i
        p, s, loss = step(st["params"], st["opt"], tokens, labels,
                          jnp.float32(1e-2))
        return {"params": p, "opt": s}, loss
    return step_fn, state, init_state.layout_extra


def _elastic_leg(tmp_path, dims_a, n_a, dims_b, n_b, z_a=False, z_b=False,
                 fp8=False):
    """Shared harness: golden on B uninterrupted; A killed by injection
    after the step-4 commit; resume on B. Returns (post-kill losses,
    golden losses, resume info)."""
    golden = {}
    step_fn, state, _ = _hybrid_setup(mesh_of(dims_b, n=n_b), zero1=z_b,
                                      fp8=fp8)
    run_resilient(step_fn, state, steps=6, ckpt_dir=str(tmp_path / "gold"),
                  ckpt_every=0, resume=False,
                  on_step=lambda i, l: golden.__setitem__(i, l))
    d = str(tmp_path / "run")
    step_fn, state, extra = _hybrid_setup(mesh_of(dims_a, n=n_a), zero1=z_a,
                                          fp8=fp8)
    _arm("loop/before_step:5")
    with pytest.raises(FaultInjected):
        run_resilient(step_fn, state, steps=6, ckpt_dir=d, ckpt_every=2,
                      layout_extra=extra)
    _arm("")
    assert checkpoint_step(latest_checkpoint(d)) == 4
    losses = {}
    step_fn, state, extra = _hybrid_setup(mesh_of(dims_b, n=n_b), zero1=z_b,
                                          fp8=fp8)
    _, info = run_resilient(step_fn, state, steps=6, ckpt_dir=d,
                            ckpt_every=2, layout_extra=extra,
                            on_step=lambda i, l: losses.__setitem__(i, l))
    assert info["resharded"] is True, info
    return losses, golden, info


def test_elastic_hybrid_pp_shrink_bitwise(tmp_path, reshard_on):
    """Acceptance: pp2 -> pp1 (dp/mp fixed) elastic resume is BITWISE equal
    to the uninterrupted mesh-B run — the pipeline schedule computes the
    same per-microbatch sums in the same order, and the reshard itself is
    lossless."""
    losses, golden, _ = _elastic_leg(
        tmp_path, {"dp": 1, "pp": 2, "mp": 2}, 4,
        {"dp": 1, "pp": 1, "mp": 2}, 2)
    assert {i: losses[i] for i in (4, 5)} == {i: golden[i] for i in (4, 5)}


def test_elastic_hybrid_zero1_on_to_off_bitwise(tmp_path, reshard_on):
    """Acceptance: zero1 on -> off at fixed dp is bitwise — the per-shard
    Adam update is elementwise and psum_scatter/dp adds the same terms as
    pmean."""
    losses, golden, _ = _elastic_leg(
        tmp_path, {"dp": 4, "pp": 1, "mp": 2}, 8,
        {"dp": 4, "pp": 1, "mp": 2}, 8, z_a=True, z_b=False)
    assert {i: losses[i] for i in (4, 5)} == {i: golden[i] for i in (4, 5)}


def test_elastic_hybrid_issue_pair_dp_regroup(tmp_path, reshard_on):
    """The ISSUE pair dp2·pp2·mp2 -> dp4·pp2·mp1: dp/mp regrouping
    reassociates the gradient reductions, so parity is ulp-level
    (measured ~5e-7 fp32), not bitwise."""
    losses, golden, _ = _elastic_leg(
        tmp_path, {"dp": 2, "pp": 2, "mp": 2}, 8,
        {"dp": 4, "pp": 2, "mp": 1}, 8)
    for i in (4, 5):
        assert abs(losses[i] - golden[i]) < 5e-5, (i, losses[i], golden[i])


def test_elastic_hybrid_fp8_carries_rescaled(tmp_path, reshard_on):
    """fp8 elastic resume across a pp change: the carried amax histories
    rescale by T_new/T_old (verified exactly against the checkpoint), and
    training continues on the new mesh tracking the golden closely (the
    pre-kill steps quantized on mesh A's grids, so bitwise is out of scope
    by construction)."""
    losses, golden, info = _elastic_leg(
        tmp_path, {"dp": 2, "pp": 2, "mp": 1}, 4,
        {"dp": 2, "pp": 1, "mp": 1}, 2, fp8=True)
    for i in (4, 5):
        assert abs(losses[i] - golden[i]) < 5e-2, (i, losses[i], golden[i])
    # exactness of the tick rescale: loaded history == saved x (T_b/T_a)
    ck = info["resumed_from"]
    saved = ckpt.load_full_state_dict(ck)
    hist_saved = saved["state"]["opt"]["fp8_meta"]["amax_history"]
    md = ckpt.load_metadata(ck)
    t_src = md.layout.extra["fp8_amax_ticks"]
    assert t_src == 2 + 2 - 1
    mesh_b = mesh_of({"dp": 2, "pp": 1, "mp": 1}, n=2)
    _, state_b, extra_b = _hybrid_setup(mesh_b, fp8=True)
    t_dst = extra_b["fp8_amax_ticks"]
    assert t_dst == 2 + 1 - 1
    out = ckpt.load_resharded(
        {"step": 0, "state": state_b}, ck, layout_extra=extra_b)
    hist_loaded = out["state"]["opt"]["fp8_meta"]["amax_history"]
    for site in hist_saved:
        np.testing.assert_allclose(
            np.asarray(hist_loaded[site]["w"]),
            np.asarray(hist_saved[site]["w"]) * (t_dst / t_src),
            rtol=1e-6, err_msg=site)


def test_reshard_1b_checkpoint_throughput(tmp_path):
    """Satellite (VERDICT weak #6): realistic-shape reshard — save the
    FULL hybrid train state (params + zero1-sharded Adam moments +
    telemetry carry) of a ~1.1B-param GPT on dp2·pp2·mp2 with the
    interleaved vpp=2 block layout through the ASYNC crash-safe commit,
    then reshard-load it onto dp4·pp2·mp1 / vpp=1 / zero1-OFF — mesh
    regroup, zero1 toggle, pp-adaptor block permutation and carry
    policies all at once, on hundreds of on-disk chunks. Prints the MB/s
    numbers recorded in BASELINE.md."""
    import time
    from paddle_tpu.models import gpt as G

    paddle.set_flags({"FLAGS_ckpt_reshard": True, "FLAGS_telemetry": True})
    cfg = G.GPTConfig(vocab_size=50304, hidden_size=1792, num_layers=24,
                      num_heads=16, max_seq_len=512, dtype=jnp.float32)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3)

    mesh_a = mesh_of({"dp": 2, "pp": 2, "mp": 2})
    _, shard_a, init_a = G.build_hybrid_train_step(
        cfg, mesh_a, opt, num_microbatches=2, virtual_pp=2, zero1_dp=True)
    host_params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(host_params))
    assert n_params > 1.0e9, n_params
    params_a = shard_a(host_params)
    state_a = {"params": params_a, "opt": init_a(params_a)}
    n_leaves = len(jax.tree.leaves(state_a))

    root = str(tmp_path / "ck")
    t0 = time.monotonic()
    path = commit_checkpoint({"step": 0, "state": state_a}, root, 0,
                             async_save=True,
                             layout_extra=init_a.layout_extra)
    save_s = time.monotonic() - t0
    total_mb = sum(os.path.getsize(os.path.join(path, f))
                   for f in os.listdir(path)) / 1e6
    md = ckpt.load_metadata(path)
    n_chunks = sum(len(v) for v in md.state_dict_metadata.values())
    assert md.schema_version == 2
    assert md.layout.mesh == {"dp": 2, "pp": 2, "mp": 2}
    assert n_chunks >= 150, n_chunks  # a real many-piece reshard
    del state_a, params_a  # free mesh-A buffers before the B-side load

    mesh_b = mesh_of({"dp": 4, "pp": 2, "mp": 1})
    _, shard_b, init_b = G.build_hybrid_train_step(
        cfg, mesh_b, opt, num_microbatches=2, virtual_pp=1, zero1_dp=False)
    params_b = shard_b(jax.tree.map(jnp.zeros_like, host_params))
    state_b = {"params": params_b, "opt": init_b(params_b)}
    template = {"step": 0, "state": state_b}
    mm = ckpt.layout_mismatch(md, template,
                              layout_extra=init_b.layout_extra)
    assert mm and "mesh" in mm and mm.get("pp_relayout") is True
    t0 = time.monotonic()
    out = ckpt.load_resharded(template, path, metadata=md,
                              layout_extra=init_b.layout_extra)
    load_s = time.monotonic() - t0

    # vpp2 chunk-major storage came back in canonical order
    got = out["state"]["params"]["blocks"]["qkv_w"]
    want = host_params["blocks"]["qkv_w"]
    for layer in (0, 7, 23):
        np.testing.assert_array_equal(np.asarray(got[layer, :4, :4]),
                                      np.asarray(want[layer, :4, :4]))
    np.testing.assert_array_equal(np.asarray(out["state"]["params"]
                                             ["head_w"][:8, :8]),
                                  np.asarray(host_params["head_w"][:8, :8]))
    # zero1-sharded moments arrived replicated (zero1 off) and zeroed
    m1 = out["state"]["opt"]["opt"]["slots"]["blocks"]["qkv_w"]["moment1"]
    assert tuple(m1.shape) == tuple(want.shape)
    assert float(jnp.max(jnp.abs(m1[0, :64, :64]))) == 0.0
    print(f"\n[1b-reshard] params={n_params / 1e9:.2f}B leaves={n_leaves} "
          f"chunks={n_chunks} bytes={total_mb:.0f}MB "
          f"save={save_s:.1f}s ({total_mb / save_s:.0f} MB/s) "
          f"reshard-load={load_s:.1f}s ({total_mb / load_s:.0f} MB/s)")


def test_two_process_elastic_restart(tmp_path):
    """Satellite: 2-process dp2 mesh hard-killed mid-run, resumed on a
    1-process half-size mesh with reshard-on-load, loss parity vs the
    uninterrupted mesh-B golden (SKIPs where the jaxlib CPU backend lacks
    multiprocess collectives, like the other spawn legs)."""
    from paddle_tpu.distributed import mp_smoke
    try:
        res = mp_smoke.elastic_restart_check(8, str(tmp_path / "ck"),
                                             devices=jax.devices())
    except mp_smoke.ClusterUnsupported as e:
        pytest.skip(f"mp spawn unsupported on this platform: {e}")
    assert res["resharded"] is True
    assert res["killed_at_step"] == 3
    assert res["max_loss_diff"] <= 5e-5
