"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
— naive_gate.py, gshard_gate.py, switch_gate.py, base_gate.py).

Each gate maps token activations to a capacity-bounded routing plan:

  combine_weights [T, E, C] — weight each token contributes to each
                              (expert, capacity-slot); zero where dropped
  dispatch_mask   [T, E, C] — boolean one-hot of slot assignment
  aux_loss        scalar    — load-balancing loss (0 for NaiveGate)

The [T, E, C] formulation is the GShard einsum dispatch: on TPU the
dispatch/combine einsums compile to MXU matmuls and the E dimension carries
the expert-parallel sharding, so XLA lowers the token exchange to a single
all-to-all over the 'ep' mesh axis. The reference instead materializes
variable-length per-expert token lists and NCCL-alltoalls them
(global_scatter) — dynamic shapes XLA cannot tile.

All routing math is fully vectorized (cumsum-based position assignment,
no data-dependent control flow) so it jits to one fused region.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .....nn.initializer import XavierUniform
from .....nn.layer.layers import Layer, Parameter

__all__ = ["BaseGate", "NaiveGate", "SwitchGate", "GShardGate", "TopKGate",
           "compute_capacity"]


def compute_capacity(num_tokens: int, num_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    """Slots per expert. Reference gates bound tokens-per-expert the same
    way (gshard_gate.py capacity arg)."""
    cap = int(math.ceil(num_tokens * top_k / num_experts * capacity_factor))
    return max(cap, top_k)


def _one_hot(idx, num):
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def _positions_in_expert(mask: jnp.ndarray) -> jnp.ndarray:
    """mask [T, E] 0/1 → slot index each token takes in its expert's queue
    (cumsum order = token order, the reference's prune_gate_by_capacity
    semantics)."""
    return (jnp.cumsum(mask, axis=0) - 1.0) * mask


def _slot_assign(expert_idx, capacity, num_experts, prev_counts=None):
    """Shared capacity-slot assignment for one routing choice (the ONE
    copy of the queueing math — both dispatch encodings derive from it).

    expert_idx [T] int. prev_counts [E] — slots already
    taken by earlier choices (top-2's second expert queues behind the
    first, matching GShard). Returns (mask [T,E], pos_idx [T] int32,
    keep_tok [T] bool, counts [E])."""
    mask = _one_hot(expert_idx, num_experts)  # [T, E]
    pos = _positions_in_expert(mask)
    if prev_counts is not None:
        pos = pos + prev_counts[None, :] * mask
    keep = (pos < capacity) & (mask > 0)
    pos_idx = pos.sum(axis=1).astype(jnp.int32)  # [T]
    keep_tok = keep.any(axis=1)
    counts = mask.sum(axis=0)
    return mask, pos_idx, keep_tok, counts


def _capacity_dispatch(expert_idx, gate_w, capacity, num_experts,
                       prev_counts=None):
    """Dense [T, E, C] one-hot encoding of _slot_assign (the GSPMD/einsum
    dispatch form). Returns (combine, kept_mask, counts)."""
    mask, pos_idx, keep_tok, counts = _slot_assign(
        expert_idx, capacity, num_experts, prev_counts)
    combine = (gate_w * keep_tok)[:, None, None] * (
        mask[:, :, None] * _one_hot(pos_idx, capacity)[:, None, :])
    return combine, keep_tok, counts


def _capacity_dispatch_idx(expert_idx, gate_w, capacity, num_experts,
                           prev_counts=None):
    """INDEX encoding of _slot_assign — flat slot ids instead of the dense
    [T, E, C] one-hot.

    Returns (slot [T] int32 = e*C + pos, or -1 when dropped;
    gate [T] f32 zeroed for dropped tokens; counts [E]). The MoE layer's
    gather/scatter dispatch consumes this: the dense [T,E,C] einsum costs
    2·T·E·C·D MXU flops per dispatch/combine (measured 54% of a 1.3B-class
    MoE step), where the reference's CUDA scatter
    (fluid/operators/collective/global_scatter_op.cu.cc) is ~free —
    index routing is the TPU analogue of that zero-flop scatter.
    """
    mask, pos_idx, keep_tok, counts = _slot_assign(
        expert_idx, capacity, num_experts, prev_counts)
    slot = jnp.where(keep_tok,
                     expert_idx.astype(jnp.int32) * capacity + pos_idx,
                     -1).astype(jnp.int32)
    return slot, gate_w * keep_tok, counts


class BaseGate(Layer):
    """Reference: moe/gate/base_gate.py — holds expert counts and the
    learned routing projection."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25, name: Optional[str] = None):
        super().__init__(name_scope=name)
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            [d_model, num_experts], default_initializer=XavierUniform())

    def logits(self, x):
        # route in fp32: softmax/cumsum numerics matter more than MXU speed
        return jnp.asarray(x, jnp.float32) @ jnp.asarray(
            self.weight.value, jnp.float32)

    def capacity(self, num_tokens: int) -> int:
        return compute_capacity(num_tokens, self.num_experts, self.top_k,
                                self.capacity_factor)

    def _route(self, x):
        """(choices, aux): choices = [(expert_idx [T], gate_w [T]), ...]
        in priority order (later choices queue behind earlier ones for
        capacity slots). Subclasses implement routing here ONCE; dense
        (forward) and index (forward_index) dispatch both derive from it."""
        raise NotImplementedError

    def forward(self, x) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        choices, aux = self._route(x)
        cap = self.capacity(x.shape[0])
        combine = jnp.zeros((x.shape[0], self.num_experts, cap),
                            jnp.float32)
        counts = None
        for ei, wi in choices:
            c, _, n = _capacity_dispatch(ei, wi, cap, self.num_experts,
                                         counts)
            combine = combine + c
            counts = n if counts is None else counts + n
        return combine, combine > 0, aux

    def forward_index(self, x):
        """(slots [T, K] int32 (-1 = dropped), gates [T, K] f32, aux) —
        the gather/scatter dispatch form (see _capacity_dispatch_idx)."""
        choices, aux = self._route(x)
        cap = self.capacity(x.shape[0])
        slots, gates = [], []
        counts = None
        for ei, wi in choices:
            s, g, n = _capacity_dispatch_idx(ei, wi, cap, self.num_experts,
                                             counts)
            slots.append(s)
            gates.append(g)
            counts = n if counts is None else counts + n
        return jnp.stack(slots, axis=1), jnp.stack(gates, axis=1), aux


class NaiveGate(BaseGate):
    """Reference: moe/gate/naive_gate.py — plain top-k, no aux loss. Kept
    capacity-bounded here (capacity_factor defaults high enough that drops
    are rare at test scale)."""

    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=2.0):
        super().__init__(d_model, num_experts, top_k, capacity_factor)

    def _route(self, x):
        logits = self.logits(x)
        probs = jax.nn.softmax(logits, axis=-1)
        topw, topi = jax.lax.top_k(probs, self.top_k)
        topw = topw / jnp.clip(topw.sum(-1, keepdims=True), 1e-9)
        choices = [(topi[:, k], topw[:, k]) for k in range(self.top_k)]
        return choices, jnp.zeros((), jnp.float32)


class SwitchGate(BaseGate):
    """Reference: moe/gate/switch_gate.py — top-1 routing with the Switch
    Transformer load-balance loss E·Σ_e f_e·P_e."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25,
                 jitter_eps: float = 0.0):
        super().__init__(d_model, num_experts, top_k=1,
                         capacity_factor=capacity_factor)
        self.jitter_eps = jitter_eps

    def _route(self, x):
        logits = self.logits(x)
        if self.jitter_eps > 0.0:
            # Switch-Transformer multiplicative routing jitter; key drawn
            # from the framework RNG so seeding stays reproducible.
            from .....random import next_key
            noise = jax.random.uniform(
                next_key(), logits.shape, jnp.float32,
                1.0 - self.jitter_eps, 1.0 + self.jitter_eps)
            logits = logits * noise
        probs = jax.nn.softmax(logits, axis=-1)
        gate_w = probs.max(axis=-1)
        expert = probs.argmax(axis=-1)
        me = probs.mean(axis=0)
        ce = _one_hot(expert, self.num_experts).mean(axis=0)
        aux = jnp.sum(me * ce) * self.num_experts
        return [(expert, gate_w)], aux


class GShardGate(BaseGate):
    """Reference: moe/gate/gshard_gate.py — top-2 with aux loss on the
    first choice and the second expert queued behind the first's slots."""

    def __init__(self, d_model, num_experts, capacity_factor=2.0):
        super().__init__(d_model, num_experts, top_k=2,
                         capacity_factor=capacity_factor)

    def _route(self, x):
        logits = self.logits(x)
        probs = jax.nn.softmax(logits, axis=-1)
        e1 = probs.argmax(axis=-1)
        w1 = probs.max(axis=-1)
        masked = probs - _one_hot(e1, self.num_experts) * probs
        e2 = masked.argmax(axis=-1)
        w2 = masked.max(axis=-1)
        denom = jnp.clip(w1 + w2, 1e-9)
        me = probs.mean(axis=0)
        ce = _one_hot(e1, self.num_experts).mean(axis=0)
        aux = jnp.sum(me * ce) * self.num_experts
        return [(e1, w1 / denom), (e2, w2 / denom)], aux


class TopKGate(NaiveGate):
    """General top-k alias (the reference exposes NaiveGate(topk=k))."""
    pass
