"""Fleet facade tests (reference analog: fleet.init/distributed_model/
distributed_optimizer usage in test/collective/fleet/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import (DistributedStrategy,
                                          HybridParallelClipGrad,
                                          HybridParallelOptimizer)


def test_strategy_defaults_and_merge():
    s = DistributedStrategy()
    assert s.hybrid_configs["dp_degree"] == 1
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    assert s.hybrid_configs["dp_degree"] == 2
    assert s.hybrid_configs["mp_degree"] == 4
    assert s.hybrid_configs["pp_degree"] == 1  # untouched default
    with pytest.raises(KeyError):
        s.hybrid_configs = {"dp_degre": 2}  # typo rejected
    assert s.mesh_dims() == {"dp": 2, "pp": 1, "sharding": 1, "sep": 1,
                             "mp": 4}


def test_fleet_init_builds_hcg():
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=s)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert set(hcg.mesh.axis_names) == {"dp", "pp", "sharding", "sep", "mp"}
    assert fleet.fleet.is_initialized()


def test_fleet_init_defaults_to_pure_dp():
    fleet.init(is_collective=True)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == len(jax.devices())


def test_distributed_model_dp_and_optimizer():
    fleet.init(is_collective=True)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    dmodel = fleet.distributed_model(model)
    out = dmodel(jnp.ones((8, 8)))
    assert out.shape == (8, 2)

    opt = paddle.optimizer.AdamW(
        0.01, parameters=model.parameters(),
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    dopt = fleet.distributed_optimizer(opt)
    assert isinstance(dopt, HybridParallelOptimizer)
    assert isinstance(opt._grad_clip, HybridParallelClipGrad)


def test_distributed_model_sharding_mode():
    s = DistributedStrategy()
    s.hybrid_configs = {"sharding_degree": 8}
    s.sharding_configs = {"stage": 2}
    fleet.init(is_collective=True, strategy=s)
    model = nn.Linear(8, 8)
    wrapped = fleet.distributed_model(model)
    from paddle_tpu.distributed.fleet.meta_parallel.sharding.group_sharded_stage import (
        GroupShardedStage2)
    assert isinstance(wrapped, GroupShardedStage2)


def test_hybrid_clip_grad_matches_global_norm():
    clip = HybridParallelClipGrad(clip_norm=1.0)
    g = {"a": jnp.full((4,), 3.0), "b": jnp.full((2, 2), -4.0)}
    clipped = clip(g)
    total = np.sqrt(sum(np.sum(np.square(np.asarray(v)))
                        for v in g.values()))
    for k in g:
        np.testing.assert_allclose(np.asarray(clipped[k]),
                                   np.asarray(g[k]) / total, rtol=1e-5)
    # under the norm → untouched
    g2 = {"a": jnp.full((2,), 1e-3)}
    out2 = clip(g2)
    np.testing.assert_allclose(np.asarray(out2["a"]), np.asarray(g2["a"]))


def test_hybrid_optimizer_functional_core_jits():
    fleet.init(is_collective=True)
    opt = paddle.optimizer.AdamW(
        0.01, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    dopt = fleet.distributed_optimizer(opt)
    params = {"w": jnp.ones((4, 4))}
    state = dopt.init_state(params)

    @jax.jit
    def step(p, s):
        g = {"w": jnp.full((4, 4), 2.0)}
        return dopt.apply(p, g, s, 0.1)

    p2, s2 = step(params, state)
    assert not np.allclose(np.asarray(p2["w"]), 1.0)
    assert int(s2["step"]) == 1


@pytest.mark.parametrize("op", ["allreduce", "allgather", "reduce_scatter",
                                "broadcast", "alltoall"])
def test_collective_perf_runs(op):
    res = fleet.collective_perf(op, round=2, size_and_time={1: 0.0})
    assert set(res) == {1}
    assert res[1] > 0

def test_hybrid_parallel_inference_helper():
    """Sharded forward + generate over a dp x pp x mp mesh (reference:
    fleet/utils/hybrid_parallel_inference.py)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.utils import (
        HybridParallelInferenceHelper)
    from paddle_tpu.models import gpt as G
    cfg = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                      num_heads=4, max_seq_len=32, dtype=jnp.float32)
    mesh = dist.build_mesh({"dp": 2, "pp": 2, "mp": 2})
    helper = HybridParallelInferenceHelper(mesh, G, cfg)
    params = helper.shard_params(G.init_hybrid_params(cfg,
                                                      jax.random.PRNGKey(0)))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 8)))
    logits = helper(params, tokens)
    assert logits.shape == (4, 8, 64)
    # matches the unsharded dense forward
    ref = G.dense_forward(G.init_hybrid_params(cfg, jax.random.PRNGKey(0)),
                          tokens, cfg, remat=False)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               atol=1e-4)
    out = helper.generate(params, tokens, max_new_tokens=3)
    assert out.shape == (4, 11)


def test_distributed_optimizer_gradient_merge():
    s = DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 3, "avg": True}
    fleet.init(is_collective=True, strategy=s)
    from paddle_tpu.optimizer import GradientMergeOptimizer
    dopt = fleet.distributed_optimizer(paddle.optimizer.SGD(0.1))
    assert isinstance(dopt.inner_opt, GradientMergeOptimizer)
    p = {"w": jnp.zeros(())}
    st = dopt.init_state(p)
    for i in range(3):
        p, st = dopt.apply(p, {"w": jnp.asarray(3.0)}, st, 0.1)
    np.testing.assert_allclose(float(p["w"]), -0.3, rtol=1e-6)
