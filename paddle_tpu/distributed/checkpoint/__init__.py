"""Distributed (sharding-aware) checkpoint with reshard-on-load
(reference: python/paddle/distributed/checkpoint/ — SURVEY §2.9)."""

from .load_state_dict import (load_full_state_dict, load_metadata,
                              load_state_dict)
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata
from .pp_adaptor import pp_relayout_state_dict
from .save_state_dict import save_state_dict, wait_async_save
from .utils import flatten_state_dict, unflatten_state_dict
from . import pp_adaptor

__all__ = [
    "save_state_dict", "load_state_dict", "load_full_state_dict",
    "wait_async_save", "load_metadata",
    "Metadata", "LocalTensorMetadata", "LocalTensorIndex",
    "flatten_state_dict", "unflatten_state_dict",
    "pp_adaptor", "pp_relayout_state_dict",
]
