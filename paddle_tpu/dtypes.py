"""Dtype system.

Mirrors the reference's dtype surface (reference: paddle/phi/common/data_type.h;
python/paddle/framework/dtype.py) on top of numpy/jax dtypes. On TPU the
first-class compute dtype is bfloat16 (MXU-native); float32 is the accumulation
and reference dtype.
"""

from __future__ import annotations

import jax.numpy as jnp
from .enforce import InvalidTypeError
import numpy as np

import ml_dtypes

__all__ = [
    "bfloat16", "float16", "float32", "float64", "float8_e4m3fn", "float8_e5m2",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128",
    "dtype", "convert_np_dtype_to_dtype_", "is_floating_point", "is_integer",
    "get_default_dtype", "set_default_dtype", "finfo", "iinfo", "promote_types",
]

dtype = np.dtype

bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
float8_e4m3fn = ml_dtypes.float8_e4m3fn
float8_e5m2 = ml_dtypes.float8_e5m2
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
    "half": "float16",
    "float": "float32",
    "double": "float64",
    "bool": "bool_",
    "int": "int32",
    "long": "int64",
}

_DEFAULT_DTYPE = [jnp.float32]


def convert_np_dtype_to_dtype_(d):
    """Normalize any dtype-like (str, np.dtype, jnp scalar type) to np.dtype."""
    if isinstance(d, str):
        d = _ALIASES.get(d, d)
        if d == "bool_":
            return np.dtype(bool)
        return np.dtype(getattr(jnp, d, d))
    return np.dtype(d)


def is_floating_point(d) -> bool:
    d = convert_np_dtype_to_dtype_(d)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(d) -> bool:
    d = convert_np_dtype_to_dtype_(d)
    return jnp.issubdtype(d, jnp.integer)


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def set_default_dtype(d):
    d = convert_np_dtype_to_dtype_(d)
    if not is_floating_point(d):
        raise InvalidTypeError(
            f"default dtype must be floating point, got {d}",
            op="set_default_dtype")
    _DEFAULT_DTYPE[0] = jnp.dtype(d).type


def finfo(d):
    return jnp.finfo(convert_np_dtype_to_dtype_(d))


def iinfo(d):
    return jnp.iinfo(convert_np_dtype_to_dtype_(d))


def promote_types(a, b):
    return jnp.promote_types(convert_np_dtype_to_dtype_(a), convert_np_dtype_to_dtype_(b))
