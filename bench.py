"""Benchmark: GPT pretraining throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/sec/chip for a GPT-small-class model (bf16, full train step:
fwd + bwd + AdamW). vs_baseline = achieved_MFU / 0.45 (the north-star MFU
target from BASELINE.json; the reference publishes no absolute numbers).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as G

    on_tpu = any(d.platform.lower() != "cpu" for d in jax.devices())
    if on_tpu:
        cfg = G.GPTConfig(vocab_size=32768, hidden_size=1024, num_layers=16,
                          num_heads=16, max_seq_len=1024, dtype=jnp.bfloat16)
        batch, seq, iters = 16, 1024, 20
    else:  # CPU smoke fallback
        cfg = G.GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                          num_heads=4, max_seq_len=128, dtype=jnp.float32)
        batch, seq, iters = 2, 128, 3

    params = G.init_hybrid_params(cfg, jax.random.PRNGKey(0))
    opt = paddle.optimizer.AdamW(learning_rate=1e-4)
    state = jax.jit(opt.init_state)(params)

    @jax.jit
    def step(params, state, tokens, labels):
        loss, grads = jax.value_and_grad(
            lambda p: G.dense_loss(p, tokens, labels, cfg))(params)
        params, state = opt.apply(params, grads, state, 1e-4)
        return params, state, loss

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # warmup/compile (fetch a concrete value — block_until_ready alone can
    # return early through remote-execution tunnels)
    params, state, loss = step(params, state, tokens, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        params, state, loss = step(params, state, tokens, labels)
    jax.block_until_ready(params)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt

    # params count (excluding embeddings for flops-per-token ~ 6N rule)
    n_params = sum(int(np.prod(v.shape)) for v in jax.tree.leaves(params))
    n_emb = int(np.prod(params["wte"].shape)) + int(np.prod(params["wpe"].shape))
    flops_per_token = 6 * (n_params - n_emb) + 12 * cfg.num_layers * cfg.hidden_size * seq
    achieved_flops = tokens_per_sec * flops_per_token
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = achieved_flops / peak

    print(json.dumps({
        "metric": "gpt_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
