"""OpTest harness (reference: test/legacy_test/op_test.py — the golden-value
framework behind thousands of test_*_op.py files: forward vs a numpy
reference, gradients vs numeric finite differences).

TPU shape: forward checks run the op under jit; gradient checks compare
jax.grad against central finite differences in fp64-free form (fp32 with
scaled tolerances)."""

from __future__ import annotations

from typing import Callable, Dict, Sequence

import numpy as np
import jax
import jax.numpy as jnp


def check_forward(op: Callable, np_ref: Callable, inputs: Sequence[np.ndarray],
                  rtol=1e-5, atol=1e-6):
    """op(*jnp_inputs) vs np_ref(*np_inputs). Bind op-specific keyword
    arguments into the callables (lambdas) — forwarding one kwargs dict to
    both op and reference would force their signatures to match."""
    got = jax.jit(lambda *a: op(*a))(*map(jnp.asarray, inputs))
    want = np_ref(*inputs)
    np.testing.assert_allclose(np.asarray(got), want, rtol=rtol, atol=atol)


def check_grad(op: Callable, inputs: Sequence[np.ndarray], argnums=0,
               eps=1e-3, rtol=2e-2, atol=1e-3, reduce_fn=None):
    """jax.grad vs central finite differences on a scalarized output
    (reference: OpTest.check_grad's numeric jacobian). fp32 inputs only —
    the FD perturbation and tolerances are calibrated for fp32."""
    assert np.asarray(inputs[argnums]).dtype == np.float32, (
        "check_grad expects float32 inputs (FD eps/tolerances assume it)")
    if reduce_fn is None:
        reduce_fn = lambda y: jnp.sum(y * jnp.cos(
            jnp.arange(y.size, dtype=jnp.float32).reshape(y.shape)))

    def scalar(*args):
        return reduce_fn(op(*args))

    analytic = np.asarray(
        jax.grad(scalar, argnums=argnums)(*map(jnp.asarray, inputs)))

    x = np.array(inputs[argnums], dtype=np.float32)
    numeric = np.zeros_like(x)
    flat = x.ravel()
    nflat = numeric.ravel()
    f = jax.jit(scalar)

    def eval_at(v):
        args = list(inputs)
        args[argnums] = v.reshape(x.shape)
        return float(f(*map(jnp.asarray, args)))

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = eval_at(flat)
        flat[i] = orig - eps
        fm = eval_at(flat)
        flat[i] = orig
        nflat[i] = (fp - fm) / (2 * eps)

    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)


def run_op_test(op: Callable, np_ref: Callable,
                inputs: Sequence[np.ndarray],
                grad_argnums: Sequence[int] = (0,),
                fwd_tol: Dict = None, grad_tol: Dict = None):
    """Full OpTest: forward golden check + gradient check per input."""
    check_forward(op, np_ref, inputs, **(fwd_tol or {}))
    for a in grad_argnums:
        check_grad(op, inputs, argnums=a, **(grad_tol or {}))
