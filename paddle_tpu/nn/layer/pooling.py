"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    _fn = None
    _default_fmt = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format=None, name=None, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format or self._default_fmt

    def forward(self, x):
        return type(self)._fn(x, self.kernel_size, self.stride, self.padding,
                              ceil_mode=self.ceil_mode, data_format=self.data_format)


class AvgPool1D(_Pool):
    _fn = staticmethod(F.avg_pool1d)
    _default_fmt = "NCL"


class AvgPool2D(_Pool):
    _fn = staticmethod(F.avg_pool2d)


class AvgPool3D(_Pool):
    _fn = staticmethod(F.avg_pool3d)
    _default_fmt = "NCDHW"


class MaxPool1D(_Pool):
    _fn = staticmethod(F.max_pool1d)
    _default_fmt = "NCL"


class MaxPool2D(_Pool):
    _fn = staticmethod(F.max_pool2d)


class MaxPool3D(_Pool):
    _fn = staticmethod(F.max_pool3d)
    _default_fmt = "NCDHW"


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, data_format=None, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return type(self)._fn(x, self.output_size)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool1d)


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool2d)


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool3d)


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool1d)


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool2d)


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool3d)
