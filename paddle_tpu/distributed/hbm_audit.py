"""Realistic-shape multi-chip compile audits (no buffers materialized).

VERDICT r4 missing-3: every hybrid test ran a toy GPT (H=32, L=2) — the
north-star 6.7B shape had never been fed through the multi-chip path, so
sharded-memory math at H=4096/L=32 was untested code. These audits AOT-
compile (``jit(...).lower(shapes).compile()``) the FULL training step at
the real shape over a virtual device mesh: XLA partitions, schedules and
memory-plans the program exactly as it would on hardware, but no 27 GB
parameter tree ever exists. The compiled executable's
``memory_analysis()`` gives per-device argument/temp bytes — the numbers
a v5p-128 deployment plans against (BASELINE.md "6.7B multi-chip
projection").

Reference anchor: the reference's hybrid tests train real Llama-shaped
models (test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model
.py:93); this is the compile-time analogue scaled to the real GPT-3 6.7B
config on CPU hosts without TPU HBM.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["per_device_bytes", "audit_hybrid_compile",
           "audit_stage3_compile", "audit_plan_compile"]


def per_device_bytes(shapes, specs, mesh: Mesh) -> int:
    """Bytes one device holds for a (shape-tree, spec-tree) pair: each
    leaf's bytes divided by the product of the mesh axes its spec shards
    over (replicated dims count fully — that IS the per-device cost)."""
    def leaf_bytes(s, sp):
        shard = 1
        for ax in (sp or ()):  # a None spec = fully replicated
            if ax is None:
                continue
            for a in (ax if isinstance(ax, tuple) else (ax,)):
                shard *= mesh.shape[a]
        return s.size * jnp.dtype(s.dtype).itemsize // shard

    # tree_map pairs by STRUCTURE (a zip over two leaves() lists would
    # silently misalign when a spec is None, since leaves() drops Nones).
    # specs leads so its None/P nodes are leaves (is_leaf sees tree #1);
    # a None spec then counts as fully replicated instead of vanishing.
    sized = jax.tree.map(lambda sp, s: leaf_bytes(s, sp), specs, shapes,
                         is_leaf=lambda x: x is None or isinstance(x, P))
    return sum(jax.tree.leaves(sized))


def _mem_stats(compiled) -> Dict[str, int]:
    try:
        ma = compiled.memory_analysis()
        return {"argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes)}
    except Exception:  # backend without memory analysis
        return {}


def audit_hybrid_compile(mesh: Mesh, *, seq: int = 2048, batch: int = 4,
                         microbatches: int = 2,
                         moment_dtype=jnp.bfloat16,
                         zero1_dp: bool = False,
                         zero_stage: int = None) -> Dict[str, Any]:
    """Compile the full dp x pp x mp hybrid train step (1F1B pipeline,
    vocab-parallel CE, dp grad pmean, fused AdamW update) at the REAL
    GPT-3 6.7B shape (H=4096, L=32, heads=32, vocab 50304) and return
    per-device byte accounting.

    Asserts the spec-derived per-device param bytes against the analytic
    expectation: matrix params shard over pp x mp; embeddings shard over
    mp (vocab-parallel) but not pp; LN vectors replicate (under
    zero_stage=3 the dp-shardable leaves additionally divide by dp —
    the zero_param_specs rule).
    """
    import time

    import paddle_tpu as paddle
    from ..models import gpt as G
    from ..models.hybrid_engine import state_specs_for

    stage = (1 if zero1_dp else 0) if zero_stage is None else int(zero_stage)
    cfg = G.gpt_6p7b(dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 moment_dtype=moment_dtype)
    step, _, init_state = G.build_hybrid_train_step(
        cfg, mesh, opt, num_microbatches=microbatches, zero_stage=stage)

    pshape = jax.eval_shape(
        lambda: G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    # the engine's published AOT layout: param specs grow dp under
    # stage 3, state specs grow dp under any stage (the ONE zero_dims
    # rule) — reading them off init_state keeps this audit honest
    specs = init_state.param_specs
    if stage:
        from ..models.hybrid_engine import zero_state_specs
        base_specs = G.hybrid_param_specs(cfg)
        _, sspec = zero_state_specs(opt, base_specs, pshape, mesh, "dp")
    else:
        sspec = state_specs_for(opt, specs, pshape)
    sshape = jax.eval_shape(opt.init_state, pshape)

    def shaped(shapes, spec_tree):
        return jax.tree.map(
            lambda s, sp: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
            shapes, spec_tree)

    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                               sharding=NamedSharding(mesh, P("dp")))
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    t0 = time.perf_counter()
    compiled = step.lower(shaped(pshape, specs), shaped(sshape, sspec),
                          tok, tok, lr).compile()
    compile_s = time.perf_counter() - t0

    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(pshape))
    param_b = per_device_bytes(pshape, specs, mesh)
    state_b = per_device_bytes(sshape, sspec, mesh)

    # analytic cross-check of the spec-derived number, from the model's
    # own config — catches silently-replicated big tensors. Layout per
    # hybrid_param_specs: matrices (qkv 3H², proj H², fc1/fc2 8H²) shard
    # over pp x mp along with the mp-dim biases (qkv_b 3H + fc1_b 4H);
    # per-layer H-vectors (2 LN pairs + proj_b + fc2_b = 6H) shard over
    # pp only; wte/head shard over mp (vocab-parallel); wpe + final LN
    # replicate.
    H, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    pp, mp = mesh.shape["pp"], mesh.shape["mp"]
    itemsize = 2  # bf16
    expect = itemsize * (
        (12 * L * H * H + 7 * L * H) // (pp * mp)
        + (6 * L * H) // pp
        + 2 * (V * H) // mp
        + cfg.max_seq_len * H + 2 * H)
    if stage >= 3:
        # every one of the leaves above has a dp-shardable free dim at
        # the 6.7B shape, so resident params divide by dp exactly
        expect = expect // mesh.shape["dp"]
    assert abs(param_b - expect) / expect < 0.001, (param_b, expect)

    stage_note = {0: "", 1: " + zero1 dp-sharded state",
                  2: " + zero2 dp-sharded state+grads",
                  3: " + zero3 dp-sharded params"}[stage]
    out = {"config": "gpt3_6p7b H=4096 L=32 heads=32 vocab=50304"
                     + stage_note,
           "mesh": dict(mesh.shape), "seq": seq, "batch": batch,
           "microbatches": microbatches,
           "n_params": n_params,
           "per_device_param_bytes": param_b,
           "per_device_state_bytes": state_b,
           "compile_s": round(compile_s, 1)}
    out.update(_mem_stats(compiled))
    return out


def audit_plan_compile(cand, cfg, *, family: str = "gpt",
                       global_batch: int, seq: int, optimizer=None,
                       devices=None) -> Dict[str, Any]:
    """AOT-compile ONE auto-parallel PlanCandidate's full hybrid train
    step on a virtual mesh and return its ``memory_analysis`` byte
    accounting — the compiled cross-check for the planner's analytic
    per-chip HBM model (no buffer is ever materialized; the engine's
    ``init_state.abstract``/``init_state.state_specs`` AOT hook supplies
    the state carry shapes)."""
    import time

    import paddle_tpu as paddle
    if family == "gpt":
        from ..models import gpt as M
    else:
        from ..models import llama as M

    mesh = cand.build_mesh(devices)
    opt = optimizer if optimizer is not None \
        else paddle.optimizer.AdamW(learning_rate=1e-4)
    kw = cand.engine_kwargs(family=family, global_batch=global_batch,
                            seq=seq)
    step, _, init_state = M.build_hybrid_train_step(cfg, mesh, opt, **kw)

    # the engine's PUBLISHED layout, not the raw model table: under
    # zero_stage=3 the param specs grow the dp axis (zero_param_specs)
    specs = init_state.param_specs
    pshape = jax.eval_shape(
        lambda: M.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    sshape = init_state.abstract(pshape)
    sspec = init_state.state_specs

    def shaped(shapes, spec_tree):
        return jax.tree.map(
            lambda s, sp: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
            shapes, spec_tree)

    data_spec = P(("dp", "ep")) if getattr(cfg, "moe_on", False) else P("dp")
    tok = jax.ShapeDtypeStruct((global_batch, seq), jnp.int32,
                               sharding=NamedSharding(mesh, data_spec))
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    t0 = time.perf_counter()
    compiled = step.lower(shaped(pshape, specs), shaped(sshape, sspec),
                          tok, tok, lr).compile()
    out = {"candidate": str(cand), "mesh": dict(mesh.shape),
           "global_batch": global_batch, "seq": seq,
           "per_device_param_bytes": per_device_bytes(pshape, specs, mesh),
           "per_device_state_bytes": per_device_bytes(sshape, sspec, mesh),
           "compile_s": round(time.perf_counter() - t0, 2)}
    out.update(_mem_stats(compiled))
    return out


def audit_stage3_compile(mesh: Mesh, *, seq: int = 2048, batch: int = 8,
                         shard_axis: str = "sharding") -> Dict[str, Any]:
    """Compile the ZeRO stage-3 (p_g_os) sharded train step at the real
    6.7B shape: params, grads and optimizer state all sharded over the
    axis; asserts per-device param bytes ~= total/n for the shardable
    leaves (BASELINE config 4's layout)."""
    import time

    import paddle_tpu as paddle
    from ..models import gpt as G
    from .sharding.group_sharded import (_state_specs, build_sharded_train_step,
                                         param_specs)

    cfg = G.gpt_6p7b(dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 moment_dtype=jnp.bfloat16)

    def loss_fn(p, tokens, labels):
        return G.dense_loss(p, tokens, labels, cfg, remat_save=())

    _, _, compile_for = build_sharded_train_step(
        loss_fn, opt, mesh, level="p_g_os", data_axes=shard_axis)

    pshape = jax.eval_shape(
        lambda: G.init_hybrid_params(cfg, jax.random.PRNGKey(0)))
    p_specs = param_specs(pshape, mesh, shard_axis, stage=3)
    s_specs = _state_specs(opt, pshape, mesh, shard_axis)
    sshape = jax.eval_shape(opt.init_state, pshape)

    jstep, _ = compile_for(pshape)

    def shaped(shapes, spec_tree):
        return jax.tree.map(
            lambda s, sp: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
            shapes, spec_tree)

    n = mesh.shape[shard_axis]
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                               sharding=NamedSharding(mesh, P(shard_axis)))
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    t0 = time.perf_counter()
    compiled = jstep.lower(shaped(pshape, p_specs), shaped(sshape, s_specs),
                           tok, tok, lr).compile()
    compile_s = time.perf_counter() - t0

    param_b = per_device_bytes(pshape, p_specs, mesh)
    total_b = sum(s.size * jnp.dtype(s.dtype).itemsize
                  for s in jax.tree.leaves(pshape))
    # shardable leaves divide by n; small vectors (LN) replicate — at 6.7B
    # the matrix mass dominates, so per-device must sit within 5% of 1/n
    assert param_b < total_b / n * 1.05, (param_b, total_b / n)

    out = {"config": "gpt3_6p7b stage-3 (p_g_os)",
           "mesh": dict(mesh.shape), "seq": seq, "batch": batch,
           "per_device_param_bytes": param_b,
           "total_param_bytes": total_b,
           "compile_s": round(compile_s, 1)}
    out.update(_mem_stats(compiled))
    return out
