"""Portable tile primitives (reference: paddle/phi/kernels/primitive/ —
the "Kernel Primitive API": ReadData/WriteData/ElementwiseUnary/
ElementwiseBinary/Reduce building blocks the reference composes CUDA/XPU
kernels from, SURVEY §2.2 KPS).

TPU translation: the primitives are Pallas TILE builders. Each returns a
ready pallas_call over a [rows, cols] tiling discipline (rows on
sublanes, cols on lanes; tiles sized to VMEM), so a kernel author writes
only the per-tile math — exactly the KPS division of labor. The
framework's composed ops don't NEED these for fusion (XLA fuses
elementwise chains); they exist for custom-kernel authors (the same
audience as the reference's primitive/) and back the fused LN kernel
below.

Primitives:
  elementwise(fn, *arrays)            y = fn(*xs), tiled
  row_reduce(fn, identity, x)         [R, C] -> [R] with a VMEM carry
                                      across column tiles
  online_softmax_update(s, m, l, acc) the flash-attention streaming-
                                      softmax update rule, shared math
  layer_norm(x, g, b)                 fused row LN (fwd+bwd custom_vjp)
                                      built on the tiling discipline
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from ...enforce import InvalidArgumentError
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import LANES
from ._common import interpret as _interpret

__all__ = ["elementwise", "row_reduce", "online_softmax_update",
           "layer_norm"]


def _tile(n, target):
    t = min(target, n)
    while n % t:
        t -= 1
    return max(t, 1)


def _as2d(x):
    """[*, C] view -> [R, C] (the tiling discipline is 2-D)."""
    x = jnp.asarray(x)
    if x.ndim == 0:
        return x.reshape(1, 1), ()
    return x.reshape(-1, x.shape[-1]), x.shape


def elementwise(fn: Callable, *arrays, block_rows: int = 256,
                out_dtype=None):
    """KPS ElementwiseUnary/Binary/Ternary: apply `fn` tile-by-tile.
    Arrays must share a shape (broadcast upstream); the last dim rides
    lanes. Equivalent XLA fusion exists — this is the explicit-kernel
    form for custom-kernel composition."""
    xs2, shape = zip(*[_as2d(a) for a in arrays])
    r, c = xs2[0].shape
    for a in xs2[1:]:
        if a.shape != (r, c):
            raise InvalidArgumentError(
                f"elementwise primitive needs equal shapes, got "
                f"{[tuple(a.shape) for a in xs2]}")
    br = _tile(r, block_rows)
    out_dtype = out_dtype or xs2[0].dtype

    def kernel(*refs):
        o_ref = refs[-1]
        o_ref[...] = fn(*(ref[...] for ref in refs[:-1])).astype(
            o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(r // br,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0))] * len(xs2),
        out_specs=pl.BlockSpec((br, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r, c), out_dtype),
        interpret=_interpret(),
    )(*xs2)
    return out.reshape(shape[0] or (1,)) if shape[0] != () else out[0, 0]


def row_reduce(fn: Callable, identity, x, block_rows: int = 256,
               block_cols: int = 2048):
    """KPS Reduce (kps::details::Reduce row mode): [R, C] -> [R] for an
    associative elementwise `fn` (jnp.add/maximum/minimum/...).
    Column tiles stream through a VMEM accumulator carried across the
    innermost grid axis — the scores-row pattern every flash kernel uses,
    exposed as a primitive. In-kernel folds stay LANE-ALIGNED (the
    accumulator is [rows, 128]); the final 128-way cross-lane fold
    happens outside, where it costs one tiny fused op instead of a
    per-tile relayout. C must be a multiple of the 128-lane width."""
    from ...enforce import enforce
    x2, shape = _as2d(x)
    r, c = x2.shape
    enforce(c % LANES == 0,
            f"row_reduce needs the reduced dim ({c}) to be a multiple of "
            f"the {LANES}-lane width (pad upstream)", op="row_reduce", x=x)
    br = _tile(r, block_rows)
    bc = c
    while bc > block_cols and bc % 2 == 0 and (bc // 2) % LANES == 0:
        bc //= 2
    nc = c // bc

    def kernel(x_ref, o_ref, acc):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            acc[...] = jnp.full_like(acc, identity)

        tile = x_ref[...].astype(jnp.float32)
        parts = [tile[:, k * LANES:(k + 1) * LANES]
                 for k in range(bc // LANES)]
        acc[...] = fn(acc[...], functools.reduce(fn, parts))

        @pl.when(j == nc - 1)
        def _out():
            o_ref[...] = acc[...]

    out = pl.pallas_call(
        kernel,
        grid=(r // br, nc),
        in_specs=[pl.BlockSpec((br, bc), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((br, LANES), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r, LANES), jnp.float32),
        scratch_shapes=[pltpu.VMEM((br, LANES), jnp.float32)],
        interpret=_interpret(),
    )(x2)
    res = functools.reduce(fn, [out[:, k] for k in range(LANES)])
    return res.reshape(shape[:-1])


def online_softmax_update(s, m_prev, l_prev, acc_prev, v=None):
    """The streaming-softmax update rule (KPS-style shared math used by
    every flash/ring kernel): returns (m, l, acc, p). s: [bq, bk] scores
    tile; acc accumulates p @ v when v is given, else p itself."""
    m_cur = jnp.max(s, axis=-1)
    m = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m)
    p = jnp.exp(s - m[:, None])
    l = l_prev * alpha + jnp.sum(p, axis=-1)
    if v is not None:
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc = acc_prev * alpha[:, None] + pv
    else:
        acc = acc_prev * alpha[:, None] + p
    return m, l, acc, p


# ---------------------------------------------------------------------------
# fused layer norm on the primitives' tiling discipline
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mu) * rstd
    y_ref[...] = (y * g_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(y_ref.dtype)
    mu_ref[...] = jnp.broadcast_to(mu, mu_ref.shape)
    rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _ln_bwd_kernel(x_ref, g_ref, mu_ref, rstd_ref, dy_ref, dx_ref,
                   dg_ref, db_ref, dg_acc, db_acc, *, nrows):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mu = mu_ref[...][:, :1]
    rstd = rstd_ref[...][:, :1]
    xhat = (x - mu) * rstd
    dyg = dy * g
    c1 = jnp.mean(dyg, axis=1, keepdims=True)
    c2 = jnp.mean(dyg * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((dyg - c1 - xhat * c2) * rstd).astype(dx_ref.dtype)

    @pl.when(i == 0)
    def _init():
        dg_acc[...] = jnp.zeros_like(dg_acc)
        db_acc[...] = jnp.zeros_like(db_acc)

    dg_acc[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_acc[...] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == nrows - 1)
    def _out():
        dg_ref[...] = dg_acc[...]
        db_ref[...] = db_acc[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, weight, bias, eps: float = 1e-5):
    """Fused row LayerNorm over the last dim — the KPS-primitives demo
    kernel (reference analogue: phi/kernels/gpu/layer_norm_kernel.cu's
    welford+affine fusion). Matches the composed fp32 LN numerics."""
    return _ln_fwd(x, weight, bias, eps)[0]


def _ln_fwd(x, weight, bias, eps):
    x2, shape = _as2d(x)
    r, c = x2.shape
    br = _tile(r, 256)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(r // br,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                   pl.BlockSpec((br, LANES), lambda i: (i, 0)),
                   pl.BlockSpec((br, LANES), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((r, c), x2.dtype),
                   jax.ShapeDtypeStruct((r, LANES), jnp.float32),
                   jax.ShapeDtypeStruct((r, LANES), jnp.float32)],
        interpret=_interpret(),
    )(x2, jnp.asarray(weight)[None, :], jnp.asarray(bias)[None, :])
    return y.reshape(shape), (x2, shape, mu, rstd)


def _ln_fwd_rule(x, weight, bias, eps):
    y, res = _ln_fwd(x, weight, bias, eps)
    return y, res + (jnp.asarray(weight),)


def _ln_bwd_rule(eps, res, dy):
    x2, shape, mu, rstd, weight = res
    r, c = x2.shape
    br = _tile(r, 256)
    n = r // br
    dy2 = jnp.asarray(dy).reshape(r, c)
    dx, dg, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, nrows=n),
        grid=(n,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((br, LANES), lambda i: (i, 0)),
                  pl.BlockSpec((br, LANES), lambda i: (i, 0)),
                  pl.BlockSpec((br, c), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                   pl.BlockSpec((1, c), lambda i: (0, 0)),
                   pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((r, c), x2.dtype),
                   jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, c), jnp.float32),
                        pltpu.VMEM((1, c), jnp.float32)],
        interpret=_interpret(),
    )(x2, weight[None, :], mu, rstd, dy2)
    return (dx.reshape(shape), dg[0].astype(weight.dtype),
            db[0].astype(weight.dtype))


layer_norm.defvjp(_ln_fwd_rule, _ln_bwd_rule)
