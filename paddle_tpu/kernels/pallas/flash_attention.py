"""Tiled flash attention for TPU (Pallas).

TPU-native replacement for the reference's CUDA FlashAttention-2 integration
(reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu — dense :68 and
varlen :213 entry points; third_party/flashattn; Python surface
python/paddle/nn/functional/flash_attention.py:242,976,1098).

Design (FlashAttention-2 style, mapped onto the TPU memory hierarchy):
  * grid = (batch*heads, q_blocks, k_blocks); the k axis is innermost so the
    online-softmax state (m, l, acc) carries across k steps in VMEM scratch —
    the scores matrix never exists in HBM (O(S) memory instead of O(S^2)).
  * QK^T and PV run on the MXU with fp32 accumulation
    (preferred_element_type); rescaling on the VPU.
  * fully-masked blocks are skipped (predicated with pl.when) from the
    *structure* of the mask — causal diagonal and sliding-window band — not
    from a dense mask tensor; structured masks that can't be block-skipped
    (segments, flashmask rows, additive bias) are applied elementwise inside
    the tile, still O(S) HBM.
  * backward = two kernels (dkv with q innermost; dq with k innermost) using
    the saved logsumexp and a precomputed delta = rowsum(dO * O), per the
    FlashAttention-2 backward recurrence.

Mask/variant support (all inside the kernel — nothing falls back to an
O(S^2) composed path):
  * causal, bottom-right aligned when sq != sk (matches the composed
    reference and FlashAttention-2 semantics);
  * GQA/MQA native: key/value may carry fewer heads (H % H_kv == 0); KV
    blocks are *indexed* per query-head group via BlockSpec index maps — KV
    is never repeated in HBM (reference repeats via expand before the CUDA
    kernel when num_heads differ);
  * packed-varlen segment ids (q/kv position → sequence id; cross-segment
    scores masked) — the TPU analogue of the reference's cu_seqlens varlen
    kernel (flash_attn_kernel.cu:213);
  * sliding window (left, right) with block-level skipping;
  * flashmask start/end row indices per key column ([B, 1|H, Sk] each; key
    j masked for queries start<=q<end — the reference's
    flashmask_attention O(S) mask representation);
  * additive bias [1|B, 1|H, Sq, Sk] (covers bool masks converted to 0/-inf);
  * dropout via the in-kernel TPU PRNG: the forward draws the keep mask from
    (seed, head, q_block, k_block) and the backward re-derives the identical
    mask from the same counters — no O(S^2) mask tensor is ever saved
    (reference: philox seed/offset round-tripped through the CUDA kernel).

Layouts: public API takes paddle convention [B, S, H, D]; kernels run on
[B*H, S, D] (queries) and [B*H_kv, S, D] (keys/values).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from ...enforce import enforce
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import LANES as _LANES
from ._common import interpret as _interpret

__all__ = ["flash_attention", "supported", "FLASH_REMAT_NAMES"]

# checkpoint_name tags on the differentiation residuals (the kernel output
# and its logsumexp) — the FP8_REMAT_NAMES pattern: inert under plain
# jax.checkpoint (the always-checkpointed pipeline stages replay the flash
# KERNEL, O(S) HBM, never a composed einsum), but a selective-remat policy
# (dense_forward remat_save + these names) keeps (out, lse) so the backward
# reuses the flash forward instead of re-running it.
FLASH_REMAT_NAMES = ("flash_out", "flash_lse")

_NEG_INF = -1e30


def _pick_block(s: int, target: int = 1024) -> int:
    """Largest power-of-two-ish divisor of s up to `target`. Measured on
    v5e (GPT-268M, seq 1024): 1024 > 512 > 256 (47.4k vs 43.3k vs 34.2k
    tok/s end-to-end) — bigger q/k tiles amortize the softmax rescale; the
    fp32 scores tile at 1024x1024 (4 MB) still fits VMEM comfortably."""
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def _block_target(has_extras: bool) -> int:
    # 1024 tiles fit VMEM even WITH the extra per-tile inputs (bias 2 MB
    # bf16 + dropout bits 4 MB beside the 4 MB fp32 scores) and measured
    # ~1.8x faster than 512 on the masked paths (v5e: bias 72.7 vs 39.8
    # TF/s, segments 71.0 vs 48.4 at B=8 H=16 S=1024 d=64)
    del has_extras
    return 1024


def supported(query, key, value, attn_mask=None, dropout_p=0.0,
              is_causal=False, *args, **kwargs) -> bool:
    """Gate for registry dispatch. The tiled kernel handles dense/causal/
    masked/GQA attention with dropout; remaining fallbacks: rank != 4,
    head_dim > 256, sequence lengths not multiples of 128 (pad upstream),
    or a mask that isn't [1|B, 1|H, Sq, Sk]."""
    if getattr(query, "ndim", 0) != 4 or key.ndim != 4 or value.ndim != 4:
        return False
    b, sq, h, d = query.shape
    kb, sk, h_kv, kd = key.shape
    if kb != b or kd != d or tuple(value.shape) != tuple(key.shape):
        return False
    if h_kv == 0 or h % h_kv != 0:
        return False
    if d > 256:
        return False
    if not (sq % 128 == 0 and sk % 128 == 0):
        return False
    if attn_mask is not None:
        if getattr(attn_mask, "ndim", 0) != 4:
            return False
        mb, mh, msq, msk = attn_mask.shape
        if (msq, msk) != (sq, sk) or mb not in (1, b) or mh not in (1, h):
            return False
    seg = kwargs.get("segment_ids")
    if seg is not None and tuple(getattr(seg, "shape", ())) != (b, sq):
        return False
    if dropout_p and not 0.0 <= float(dropout_p) < 1.0:
        return False
    return True


# ---------------------------------------------------------------------------
# in-kernel mask application / block skipping
# ---------------------------------------------------------------------------

def _mask_scores(s, i, j, *, block_q, block_k, causal, offset, window,
                 bias=None, qseg=None, kseg=None, fm_start=None, fm_end=None):
    """Apply bias + structured masks to a scores tile. i/j are q/k block
    ids; offset aligns causal bottom-right for sq != sk."""
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    need_pos = causal or window is not None or fm_start is not None
    qpos = kpos = None
    if need_pos:
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
    masked = None

    def _or(a, b):
        return b if a is None else a | b

    if causal:
        masked = _or(masked, kpos > qpos + offset)
    if window is not None:
        left, right = window
        if left is not None:
            masked = _or(masked, kpos < qpos + offset - left)
        if right is not None:
            masked = _or(masked, kpos > qpos + offset + right)
    if qseg is not None:
        # qseg arrives as a [bq, 1] COLUMN (sublane-major, pre-broadcast
        # outside the kernel); kseg as a [bk] lane vector. A [bq]
        # lane-vector qseg here would need an in-tile cross-lane
        # transpose — measured 8x slower bwd at BERT shapes.
        masked = _or(masked, qseg != kseg[None, :])
    if fm_start is not None:
        masked = _or(masked, (qpos >= fm_start[None, :])
                     & (qpos < fm_end[None, :]))
    if masked is not None:
        s = jnp.where(masked, _NEG_INF, s)
    return s


def _seg_block_overlap(masks):
    """Dynamic per-tile gate for packed-varlen inputs: False iff the q and
    k segment-id RANGES in this tile are disjoint — every (q, k) pair then
    has qseg != kseg, the tile is fully masked, and skipping its matmuls/
    softmax entirely is exact. Range overlap is conservative for arbitrary
    id layouts; for first-fit packing (ids ascend within a row,
    models/bert.py pack_sequences) it skips ~1 - sum(len_i^2)/S^2 of the
    tiles — the TPU analogue of the reference varlen kernel launching
    per-sequence (flash_attn_kernel.cu cu_seqlens). Pad tails (-1) keep
    their current semantics: all-pad x all-pad tiles still run."""
    _, qseg_ref, kseg_ref, _, _ = masks
    if qseg_ref is None:
        return None
    qcol = qseg_ref[0][:, :1]   # [bq, 1] sublane column
    klane = kseg_ref[0, 0]      # [bk] lane vector
    return ((jnp.min(qcol) <= jnp.max(klane))
            & (jnp.max(qcol) >= jnp.min(klane)))


def _block_run(i, j, *, block_q, block_k, causal, offset, window):
    """True iff block (i, j) can contain any unmasked score, from the
    causal diagonal and window band alone (segments/flashmask/bias are
    handled elementwise)."""
    run = None
    q_lo = i * block_q
    q_hi = i * block_q + block_q - 1
    k_lo = j * block_k
    k_hi = j * block_k + block_k - 1

    def _and(a, b):
        return b if a is None else jnp.logical_and(a, b)

    if causal:
        run = _and(run, k_lo <= q_hi + offset)
    if window is not None:
        left, right = window
        if left is not None:
            run = _and(run, k_hi >= q_lo + offset - left)
        if right is not None:
            run = _and(run, k_lo <= q_hi + offset + right)
    return True if run is None else run


def _last_k_block(i, num_k, *, block_q, block_k, causal, offset, window):
    """Index of the last k block that runs for q block i (finalize point)."""
    if not causal and (window is None or window[1] is None):
        return num_k - 1
    hi = i * block_q + block_q - 1 + offset
    if causal and window is not None and window[1] is not None:
        hi = hi + 0  # causal is the tighter bound (right >= 0)
    elif window is not None and window[1] is not None and not causal:
        hi = hi + window[1]
    return jnp.clip(hi // block_k, 0, num_k - 1)


def _dropout_keep(seed_ref, bh, i, j, num_q, num_k, shape, dropout_p):
    """Deterministic keep-mask for tile (bh, i, j): forward and backward
    re-derive identical bits from the same counters. Mosaic allows at most
    two seed words, so the tile coordinates fold into one id."""
    tile = (bh * num_q + i) * num_k + j
    pltpu.prng_seed(seed_ref[0], tile)
    bits = pltpu.prng_random_bits(shape)
    thresh = min(int(dropout_p * 2.0 ** 32), 2 ** 32 - 1)
    return bits.astype(jnp.uint32) >= jnp.uint32(thresh)


def _unpack_refs(refs, *, n_main, has_bias, has_seg, has_fm, dropout_p):
    """Split positional pallas refs into (seed, main tensors, mask refs,
    outputs+scratch) by the active feature flags — ONE walk shared by all
    three kernels so the layouts cannot drift from the input assembly in
    _fwd/_bwd_impl."""
    idx = 0
    seed = None
    if dropout_p:
        seed = refs[idx]; idx += 1
    main = refs[idx:idx + n_main]; idx += n_main
    bias = qseg = kseg = fms = fme = None
    if has_bias:
        bias = refs[idx]; idx += 1
    if has_seg:
        qseg, kseg = refs[idx:idx + 2]; idx += 2
    if has_fm:
        fms, fme = refs[idx:idx + 2]; idx += 2
    return seed, main, (bias, qseg, kseg, fms, fme), refs[idx:]


def _mask_ref_args(masks):
    """Materialize the per-tile mask operands for _mask_scores."""
    bias_ref, qseg_ref, kseg_ref, fms_ref, fme_ref = masks
    return dict(
        bias=bias_ref[0, 0] if bias_ref is not None else None,
        # [bq, 1] column slice of the lane-broadcast q-side ids
        qseg=qseg_ref[0][:, :1] if qseg_ref is not None else None,
        kseg=kseg_ref[0, 0] if kseg_ref is not None else None,
        fm_start=fms_ref[0, 0] if fms_ref is not None else None,
        fm_end=fme_ref[0, 0] if fme_ref is not None else None)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, sm_scale, causal, offset, window, block_q, block_k,
                num_k, has_bias, has_seg, has_fm, dropout_p, save_lse):
    seed_ref, (q_ref, k_ref, v_ref), masks, rest = _unpack_refs(
        refs, n_main=3, has_bias=has_bias, has_seg=has_seg, has_fm=has_fm,
        dropout_p=dropout_p)
    if save_lse:
        o_ref, lse_ref = rest[0], rest[1]
        acc_sc, m_sc, l_sc = rest[2:5]
    else:
        o_ref, lse_ref = rest[0], None
        acc_sc, m_sc, l_sc = rest[1:4]

    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    run = _block_run(i, j, block_q=block_q, block_k=block_k, causal=causal,
                     offset=offset, window=window)
    if has_seg:
        run = jnp.logical_and(run, _seg_block_overlap(masks))

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        s = _mask_scores(
            s, i, j, block_q=block_q, block_k=block_k, causal=causal,
            offset=offset, window=window, **_mask_ref_args(masks))
        m_prev = m_sc[:, 0]                      # [bq]
        m_cur = jnp.max(s, axis=1)               # [bq]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)          # [bq]
        p = jnp.exp(s - m_new[:, None])          # [bq, bk] f32
        l_sc[:] = (l_sc[:] * alpha[:, None]
                   + jnp.sum(p, axis=1)[:, None])
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        if dropout_p:
            keep = _dropout_keep(seed_ref, b, i, j, pl.num_programs(1),
                                  num_k, p.shape, dropout_p)
            p_use = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        else:
            p_use = p
        pv = jax.lax.dot_general(
            p_use.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv

    j_last = _last_k_block(i, num_k, block_q=block_q, block_k=block_k,
                           causal=causal, offset=offset, window=window)

    @pl.when(j == j_last)
    def _finalize():
        l = l_sc[:, 0]
        # a row is dead if no block ran for it (l == 0) OR every score was
        # elementwise-masked to _NEG_INF (m never rose above it; p = exp(0)
        # makes l = block_k there, so l alone can't detect it) — emit zeros,
        # matching the composed path's fully-masked-row contract
        dead = (l == 0.0) | (m_sc[:, 0] <= _NEG_INF * 0.5)
        inv = jnp.where(dead, 0.0, 1.0 / jnp.maximum(l, 1e-37))
        o_ref[0] = (acc_sc[:] * inv[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            # dead rows keep lse ~ _NEG_INF so the backward can re-detect
            # them (p must be zero there, not exp(0))
            lse = m_sc[:, 0] + jnp.log(jnp.maximum(l, 1e-37))
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _bias_index(fwd_grid, bias_shape, h, h_kv, g, nq):
    """Index map for the [1|B, 1|H, Sq, Sk] bias under a given grid
    convention. fwd_grid: True for (bh, i, j) grids (fwd/dq), False for the
    dkv grid (bh_kv, j, t)."""
    mb, mh = bias_shape[0], bias_shape[1]
    if fwd_grid:
        def idx(b, i, j):
            bi = b // h if mb > 1 else 0
            hi = b % h if mh > 1 else 0
            return (bi, hi, i, j)
    else:
        def idx(bkv, j, t):
            bi = bkv // h_kv if mb > 1 else 0
            hi = ((bkv % h_kv) * g + t // nq) if mh > 1 else 0
            return (bi, hi, t % nq, j)
    return idx


def _build_specs(*, grid_kind, h, h_kv, g, nq, block_q, block_k, d,
                 bias_shape, has_seg, has_fm, dropout_p, fm_mh=None):
    """in_specs tail (bias/segments/flashmask) shared by fwd/dq/dkv, plus
    the optional SMEM seed spec at the head."""
    fwd_grid = grid_kind in ("fwd", "dq")
    head = []
    if dropout_p:
        head.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    tail = []
    if bias_shape is not None:
        tail.append(pl.BlockSpec(
            (1, 1, block_q, block_k),
            _bias_index(fwd_grid, bias_shape, h, h_kv, g, nq)))
    if has_seg:
        # q-side ids ride lane-BROADCAST as [B, Sq, LANES] (the lse/delta
        # pattern) so the kernel reads a sublane-major [bq, 1] column with
        # no in-tile transpose; k-side ids ride lane-major [B, 1, Sk]
        if fwd_grid:
            qidx = lambda b, i, j: (b // h, i, 0)
            kidx = lambda b, i, j: (b // h, 0, j)
        else:
            qidx = lambda bkv, j, t: (bkv // h_kv, t % nq, 0)
            kidx = lambda bkv, j, t: (bkv // h_kv, 0, j)
        tail.append(pl.BlockSpec((1, block_q, _LANES), qidx))
        tail.append(pl.BlockSpec((1, 1, block_k), kidx))
    if has_fm:
        # flashmask arrays ride flattened as [B*Hm, 1, Sk] (same tiling rule)
        mh = fm_mh
        if fwd_grid:
            def fm_idx(b, i, j):
                return (b // h * mh + (b % h if mh > 1 else 0), 0, j)
        else:
            def fm_idx(bkv, j, t):
                hi = ((bkv % h_kv) * g + t // nq) if mh > 1 else 0
                return (bkv // h_kv * mh + hi, 0, j)
        tail.append(pl.BlockSpec((1, 1, block_k), fm_idx))
        tail.append(pl.BlockSpec((1, 1, block_k), fm_idx))
    return head, tail


def _sds(shape, dtype, vma=None):
    """ShapeDtypeStruct with an optional varying-mesh-axes set — required
    when the kernel runs inside shard_map with check_vma=True (the ring
    attention path passes its mesh axis here). Older jax has no vma kwarg
    (and no vma checking): degrade gracefully."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    except TypeError:  # pre-vma jax: nothing to declare
        return jax.ShapeDtypeStruct(shape, dtype)


def _prep_mask_operands(qseg, kseg, fm_start, fm_end):
    """Reshape mask operands to their kernel ride layouts (q segments
    lane-broadcast [B,Sq,LANES], k segments [B,1,Sk], flashmask
    [B*Hm,1,Sk]) — shared by _fwd and _bwd_impl."""
    fm_mh = None
    if qseg is not None:
        qseg = jnp.broadcast_to(qseg[:, :, None],
                                (*qseg.shape, _LANES))
        kseg = kseg[:, None, :]
    if fm_start is not None:
        fm_mh = fm_start.shape[1]
        fm_start = fm_start.reshape(-1, 1, fm_start.shape[-1])
        fm_end = fm_end.reshape(-1, 1, fm_end.shape[-1])
    return qseg, kseg, fm_start, fm_end, fm_mh


def _mask_input_list(bias, qseg, kseg, fm_start, fm_end):
    """Input-list tail matching _build_specs' tail ordering exactly."""
    inputs = []
    if bias is not None:
        inputs.append(bias)
    if qseg is not None:
        inputs += [qseg, kseg]
    if fm_start is not None:
        inputs += [fm_start, fm_end]
    return inputs


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, *, h, h_kv,
         bias=None, qseg=None, kseg=None, fm_start=None, fm_end=None,
         window=None, dropout_p=0.0, seed=None, save_lse=True, vma=None,
         native=False):
    """q: [B*H, Sq, D]; k/v: [B*H_kv, Sk, D]. With native=True the main
    tensors arrive HEAD-NATIVE as [B, Sq, H*D] / [B, Sk, H_kv*D] (a free
    reshape of the model's [B, S, H, D]) and each program's (1, block, d)
    tile is lane-sliced out of the fused head dim by the index map — no
    host-side [B,S,H,D] -> [B*H,S,D] transpose copy ever happens. Only
    legal when d % 128 == 0 (Mosaic lane-block divisibility); the kernel
    body is identical either way."""
    if native:
        b_n, sq, hd = q.shape
        d = hd // h
        bh = b_n * h
    else:
        bh, sq, d = q.shape
    sk = k.shape[1]
    g = h // h_kv
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq
    grid = (bh, nq, nk)
    qseg, kseg, fm_start, fm_end, fm_mh = _prep_mask_operands(
        qseg, kseg, fm_start, fm_end)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, offset=offset,
        window=window, block_q=block_q, block_k=block_k, num_k=nk,
        has_bias=bias is not None, has_seg=qseg is not None,
        has_fm=fm_start is not None, dropout_p=dropout_p, save_lse=save_lse)

    if native:
        kv_idx = lambda b, i, j: (b // h, j, (b % h) // g)
        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b // h, i, b % h)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ]
    else:
        kv_idx = lambda b, i, j: (b // h * h_kv + (b % h) // g, j, 0)
        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ]
    head, tail = _build_specs(
        grid_kind="fwd", h=h, h_kv=h_kv, g=g, nq=nq, block_q=block_q,
        block_k=block_k, d=d, bias_shape=None if bias is None else bias.shape,
        has_seg=qseg is not None, has_fm=fm_start is not None,
        dropout_p=dropout_p, fm_mh=fm_mh)
    in_specs = head + in_specs + tail

    inputs = ([seed] if dropout_p else []) + [q, k, v] + _mask_input_list(
        bias, qseg, kseg, fm_start, fm_end)

    if native:
        ospec = pl.BlockSpec((1, block_q, d),
                             lambda b, i, j: (b // h, i, b % h))
        oshape = (bh // h, sq, h * d)
    else:
        ospec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
        oshape = (bh, sq, d)
    lspec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    if save_lse:
        out_specs = [ospec, lspec]
        out_shape = [_sds(oshape, q.dtype, vma),
                     _sds((bh, sq, _LANES), jnp.float32, vma)]
    else:
        out_specs = ospec
        out_shape = _sds(oshape, q.dtype, vma)
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*inputs)
    if save_lse:
        out, lse = res
        return out, lse[:, :, 0]
    return res, None


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dkv_kernel(*refs, sm_scale, causal, offset, window, block_q, block_k,
                num_q, num_t, h, h_kv, g, has_bias, has_seg, has_fm,
                dropout_p):
    seed_ref, main, masks, rest = _unpack_refs(
        refs, n_main=6, has_bias=has_bias, has_seg=has_seg, has_fm=has_fm,
        dropout_p=dropout_p)
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = main
    dk_ref, dv_ref, dk_sc, dv_sc = rest

    bkv = pl.program_id(0)
    j = pl.program_id(1)   # k block
    t = pl.program_id(2)   # (q head in group) * num_q + q block — innermost
    i = t % num_q
    bh_q = bkv // h_kv * h + (bkv % h_kv) * g + t // num_q

    @pl.when(t == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    run = _block_run(i, j, block_q=block_q, block_k=block_k, causal=causal,
                     offset=offset, window=window)
    if has_seg:
        run = jnp.logical_and(run, _seg_block_overlap(masks))

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        kk = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]      # [bq]
        # delta = rowsum(dO * O) computed in-VMEM: a [bq] reduce over d is
        # ~free here, while the precomputed lane-replicated delta tensor
        # cost a [bh, sq, 128] fp32 broadcast + two big HBM reads per layer
        delta = jnp.sum(o_ref[0].astype(jnp.float32)
                        * do.astype(jnp.float32), axis=-1)
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        s = _mask_scores(
            s, i, j, block_q=block_q, block_k=block_k, causal=causal,
            offset=offset, window=window, **_mask_ref_args(masks))
        p = jnp.exp(s - lse[:, None])  # [bq, bk], undropped softmax
        # fully-masked (dead) rows carry lse ~ _NEG_INF: exp(s - lse) would
        # be exp(0) = 1 there — zero them so no gradient leaks through
        p = jnp.where((lse <= _NEG_INF * 0.5)[:, None], 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p:
            keep = _dropout_keep(seed_ref, bh_q, i, j, num_q,
                                 pl.num_programs(1), p.shape, dropout_p)
            inv_keep = 1.0 / (1.0 - dropout_p)
            p_drop = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        else:
            p_drop = p
        # dv += D(P)^T dO
        dv_sc[:] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dS = P*(dP∘M/keep - delta)*scale
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == num_t - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _dq_kernel(*refs, sm_scale, causal, offset, window, block_q, block_k,
               num_k, has_bias, has_seg, has_fm, dropout_p,
               bias_grad=False):
    seed_ref, main, masks, rest = _unpack_refs(
        refs, n_main=6, has_bias=has_bias, has_seg=has_seg, has_fm=has_fm,
        dropout_p=dropout_p)
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = main
    if bias_grad:
        dq_ref, db_ref, dq_sc = rest
    else:
        dq_ref, dq_sc = rest
        db_ref = None

    b = pl.program_id(0)
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (innermost: carry dq)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    run = _block_run(i, j, block_q=block_q, block_k=block_k, causal=causal,
                     offset=offset, window=window)
    if has_seg:
        run = jnp.logical_and(run, _seg_block_overlap(masks))

    if bias_grad:
        @pl.when(jnp.logical_not(run))
        def _zero_db():
            # block-skipped tiles (outside causal/window bands) still own
            # their slice of the dbias output — make it zeros, not garbage
            db_ref[0] = jnp.zeros_like(db_ref[0])

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        kk = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = jnp.sum(o_ref[0].astype(jnp.float32)
                        * do.astype(jnp.float32), axis=-1)  # [bq], in-VMEM
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = _mask_scores(
            s, i, j, block_q=block_q, block_k=block_k, causal=causal,
            offset=offset, window=window, **_mask_ref_args(masks))
        p = jnp.exp(s - lse[:, None])
        p = jnp.where((lse <= _NEG_INF * 0.5)[:, None], 0.0, p)  # dead rows
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p:
            keep = _dropout_keep(seed_ref, b, i, j, pl.num_programs(1),
                                  num_k, p.shape, dropout_p)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        # dS without the sm_scale factor IS the additive-bias gradient
        # (s = qk*scale + bias): emit it per tile — every mask/dropout
        # effect is already inside p/dp, so dbias composes with all of them
        ds_raw = p * (dp - delta[:, None])
        if bias_grad:
            db_ref[0] = ds_raw.astype(db_ref.dtype)
        ds = ds_raw * sm_scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    j_last = _last_k_block(i, num_k, block_q=block_q, block_k=block_k,
                           causal=causal, offset=offset, window=window)

    @pl.when(j == j_last)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_impl(q, k, v, out, lse, do, sm_scale, causal, block_q, block_k, *,
              h, h_kv, bias=None, qseg=None, kseg=None, fm_start=None,
              fm_end=None, window=None, dropout_p=0.0, seed=None, vma=None,
              bias_grad=False, native=False):
    if native:
        b_n, sq, hd = q.shape
        d = hd // h
        bh, bh_kv = b_n * h, b_n * h_kv
        sk = k.shape[1]
    else:
        bh, sq, d = q.shape
        bh_kv, sk, _ = k.shape
    g = h // h_kv
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq
    # delta = rowsum(dO * O) is computed inside the kernels (o rides the
    # same BlockSpec as do — 4 bytes/elem bf16 read vs a lane-replicated
    # [bh, sq, 128] fp32 broadcast + two reads)
    lse_r = jnp.broadcast_to(lse[:, :, None], (bh, sq, _LANES))

    qseg, kseg, fm_start, fm_end, fm_mh = _prep_mask_operands(
        qseg, kseg, fm_start, fm_end)
    bias_shape = None if bias is None else bias.shape
    has_seg = qseg is not None
    has_fm = fm_start is not None
    extra_inputs = _mask_input_list(bias, qseg, kseg, fm_start, fm_end)
    seed_inputs = [seed] if dropout_p else []

    # ---- dk/dv: grid (B*H_kv, k blocks, group*q blocks) — the q-head
    # group is folded into the innermost axis so GQA reductions accumulate
    # in the VMEM scratch rather than racing on an HBM block.
    num_t = g * nq
    if native:
        qspec = pl.BlockSpec(
            (1, block_q, d),
            lambda bkv, j, t: (bkv // h_kv, t % nq,
                               (bkv % h_kv) * g + t // nq))
        kspec = pl.BlockSpec((1, block_k, d),
                             lambda bkv, j, t: (bkv // h_kv, j, bkv % h_kv))
    else:
        qspec = pl.BlockSpec(
            (1, block_q, d),
            lambda bkv, j, t: (bkv // h_kv * h + (bkv % h_kv) * g + t // nq,
                               t % nq, 0))
        kspec = pl.BlockSpec((1, block_k, d), lambda bkv, j, t: (bkv, j, 0))
    rspec = pl.BlockSpec(
        (1, block_q, _LANES),
        lambda bkv, j, t: (bkv // h_kv * h + (bkv % h_kv) * g + t // nq,
                           t % nq, 0))
    head, tail = _build_specs(
        grid_kind="dkv", h=h, h_kv=h_kv, g=g, nq=nq, block_q=block_q,
        block_k=block_k, d=d, bias_shape=bias_shape, has_seg=has_seg,
        has_fm=has_fm, dropout_p=dropout_p, fm_mh=fm_mh)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, offset=offset,
            window=window, block_q=block_q, block_k=block_k, num_q=nq,
            num_t=num_t, h=h, h_kv=h_kv, g=g, has_bias=bias is not None,
            has_seg=has_seg, has_fm=has_fm, dropout_p=dropout_p),
        grid=(bh_kv, nk, num_t),
        in_specs=head + [qspec, kspec, kspec, qspec, qspec, rspec] + tail,
        out_specs=[kspec, kspec],
        out_shape=[
            _sds(k.shape, k.dtype, vma),
            _sds(v.shape, v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*seed_inputs, q, k, v, out, do, lse_r, *extra_inputs)

    # ---- dq: grid (B*H, q blocks, k blocks)
    if native:
        qspec2 = pl.BlockSpec((1, block_q, d),
                              lambda b, i, j: (b // h, i, b % h))
        kspec2 = pl.BlockSpec((1, block_k, d),
                              lambda b, i, j: (b // h, j, (b % h) // g))
    else:
        kv_idx = lambda b, i, j: (b // h * h_kv + (b % h) // g, j, 0)
        qspec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
        kspec2 = pl.BlockSpec((1, block_k, d), kv_idx)
    rspec2 = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    head, tail = _build_specs(
        grid_kind="dq", h=h, h_kv=h_kv, g=g, nq=nq, block_q=block_q,
        block_k=block_k, d=d, bias_shape=bias_shape, has_seg=has_seg,
        has_fm=has_fm, dropout_p=dropout_p, fm_mh=fm_mh)
    emit_db = bias_grad and bias is not None
    dq_ospec = qspec2
    dq_oshape = _sds(q.shape, q.dtype, vma)
    if emit_db:
        # in-kernel dbias: each (b, i, j) tile writes its slice of the
        # full-resolution [B*H, Sq, Sk] gradient once (fp32); broadcast
        # bias shapes reduce OUTSIDE the kernel. Strictly cheaper than the
        # old composed recompute (no second QK/PV matmul pass) and composes
        # with dropout/segments/window/flashmask since ds already does.
        out_specs = [dq_ospec, pl.BlockSpec((1, block_q, block_k),
                                            lambda b, i, j: (b, i, j))]
        out_shape = [dq_oshape, _sds((bh, sq, sk), jnp.float32, vma)]
    else:
        out_specs, out_shape = dq_ospec, dq_oshape
    res = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, offset=offset,
            window=window, block_q=block_q, block_k=block_k, num_k=nk,
            has_bias=bias is not None, has_seg=has_seg, has_fm=has_fm,
            dropout_p=dropout_p, bias_grad=emit_db),
        grid=(bh, nq, nk),
        in_specs=head + [qspec2, kspec2, kspec2, qspec2, qspec2, rspec2]
        + tail,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*seed_inputs, q, k, v, out, do, lse_r, *extra_inputs)
    if emit_db:
        dq, db_full = res
        return dq, dk, dv, db_full
    return res, dk, dv, None


# ---------------------------------------------------------------------------
# public API ([B, S, H, D] layout, custom_vjp)
# ---------------------------------------------------------------------------

def _prep(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _unprep(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


_STATIC = (7, 8, 9, 10, 11, 12, 13)  # causal, sm_scale, block_q, block_k,
#                                       window, dropout_p, bias_grad


@functools.partial(jax.custom_vjp, nondiff_argnums=_STATIC)
def _flash(query, key, value, bias, q_seg, kv_seg, seed,
           causal, sm_scale, block_q, block_k, window, dropout_p,
           bias_grad=False):
    out, _ = _flash_fwd_impl(query, key, value, bias, q_seg, kv_seg, seed,
                             causal, sm_scale, block_q, block_k, window,
                             dropout_p, save_lse=False)
    return out


def _flash_fwd_impl(query, key, value, bias, q_seg, kv_seg, seed,
                    causal, sm_scale, block_q, block_k, window, dropout_p,
                    save_lse):
    b, sq, h, d = query.shape
    h_kv = key.shape[2]
    fm_start = fm_end = None
    if bias is not None and isinstance(bias, tuple):
        bias, fm_start, fm_end = bias
    # head-native lane slicing needs d % 128 == 0 (Mosaic lane blocks);
    # smaller heads pay the [B,S,H,D] -> [B*H,S,D] transpose copy
    native = d % 128 == 0
    if native:
        q = query.reshape(b, sq, h * d)
        k = key.reshape(b, key.shape[1], h_kv * d)
        v = value.reshape(b, value.shape[1], h_kv * d)
    else:
        q, k, v = _prep(query), _prep(key), _prep(value)
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, h=h,
                    h_kv=h_kv, bias=bias, qseg=q_seg, kseg=kv_seg,
                    fm_start=fm_start, fm_end=fm_end, window=window,
                    dropout_p=dropout_p, seed=seed, save_lse=save_lse,
                    native=native)
    out4 = out.reshape(b, sq, h, d) if native else _unprep(out, b, h)
    return out4, (q, k, v, out, lse, b, h, h_kv, native)


def _flash_fwd(query, key, value, bias, q_seg, kv_seg, seed,
               causal, sm_scale, block_q, block_k, window, dropout_p,
               bias_grad=False):
    out, res = _flash_fwd_impl(query, key, value, bias, q_seg, kv_seg, seed,
                               causal, sm_scale, block_q, block_k, window,
                               dropout_p, save_lse=True)
    q, k, v, out_r, lse, b, h, h_kv, native = res
    # tag the forward's residuals (FLASH_REMAT_NAMES) so a selective-remat
    # policy can keep them: (out, lse) saved => the backward kernels run
    # without replaying the forward kernel (q/k/v are cheap reshapes of the
    # projection outputs the model tags itself, e.g. dense_block's "qkv")
    out_r = checkpoint_name(out_r, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    # re-derive the public [B, Sq, H, D] output FROM the tagged residual:
    # downstream primal uses and the backward then both hang off the SAVED
    # value — reshaping the untagged kernel output instead would leave the
    # recompute side needing the original var and re-running the kernel
    out = (out_r.reshape(b, out_r.shape[1], h, -1) if native
           else _unprep(out_r, b, h))
    res = (q, k, v, out_r, lse, b, h, h_kv, native)
    return out, res + (bias, q_seg, kv_seg, seed)


def _flash_bwd(causal, sm_scale, block_q, block_k, window, dropout_p,
               bias_grad, res, g):
    q, k, v, out, lse, b, h, h_kv, native, bias, q_seg, kv_seg, seed = res
    fm_start = fm_end = None
    is_fm = bias is not None and isinstance(bias, tuple)
    if is_fm:
        bias, fm_start, fm_end = bias
    bsq, d4 = g.shape[1], g.shape[3]
    do = g.reshape(b, bsq, h * d4) if native else _prep(g)
    dq, dk, dv, db_full = _bwd_impl(
        q, k, v, out, lse, do, sm_scale, causal, block_q, block_k, h=h,
        h_kv=h_kv, bias=bias, qseg=q_seg, kseg=kv_seg, fm_start=fm_start,
        fm_end=fm_end, window=window, dropout_p=dropout_p, seed=seed,
        bias_grad=bias_grad, native=native)
    dbias = None
    if bias is not None or is_fm:
        if bias_grad and bias is not None:
            # in-kernel dbias: the dq kernel emitted the full-resolution
            # [B*H, Sq, Sk] dS; reduce to the (possibly broadcast) bias
            # shape here
            ds = db_full.reshape(b, h, *db_full.shape[-2:])
            if bias.shape[0] == 1:
                ds = ds.sum(axis=0, keepdims=True)
            if bias.shape[1] == 1:
                ds = ds.sum(axis=1, keepdims=True)
            db = ds.astype(bias.dtype)
            dbias = (db, jnp.zeros_like(fm_start),
                     jnp.zeros_like(fm_end)) if is_fm else db
        else:
            # constant-mask contract (padding masks, flashmask rows) — the
            # reference flash kernels likewise emit no mask gradient. Pass
            # bias_grad=True for a LEARNED bias (in-kernel dS emission);
            # flashmask rows are integer indices and always get zeros, so
            # bias_grad with flashmask-but-no-bias degrades to that.
            dbias = jax.tree_util.tree_map(jnp.zeros_like,
                                           (bias, fm_start, fm_end)
                                           if is_fm else bias)
    if native:
        sk = k.shape[1]
        return (dq.reshape(b, bsq, h, d4), dk.reshape(b, sk, h_kv, d4),
                dv.reshape(b, sk, h_kv, d4), dbias, None, None, None)
    return (_unprep(dq, b, h), _unprep(dk, b, h_kv), _unprep(dv, b, h_kv),
            dbias, None, None, None)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(query, key, value, causal=False, sm_scale=None,
                    block_q=None, block_k=None, *, bias=None,
                    q_segment_ids=None, kv_segment_ids=None,
                    startend_row_indices=None, window=None,
                    dropout_p=0.0, dropout_seed=None, bias_grad=False):
    """Fused attention. query: [B, Sq, H, D]; key/value: [B, Sk, H_kv, D]
    with H % H_kv == 0 (GQA/MQA native — KV heads are indexed, not
    repeated) → [B, Sq, H, D].

    bias: additive [1|B, 1|H, Sq, Sk] (use 0/-1e30 for bool masks). Treated
    as a constant by the vjp (no dbias).
    q_segment_ids/kv_segment_ids: int32 [B, Sq]/[B, Sk] packed-varlen ids;
    scores across different ids are masked.
    startend_row_indices: (start, end) int32 [B, 1|H, Sk] flashmask pair —
    key column j is masked for queries start[j] <= q < end[j].
    window: (left, right) ints or None — sliding window around the
    (bottom-right aligned) diagonal.
    dropout_p/dropout_seed: attention-probability dropout drawn from the
    in-kernel PRNG; seed is an int32 [1] array (required when p > 0).

    The primal (inference) path skips the logsumexp residual entirely — no
    extra HBM traffic; it is produced only when jax needs the vjp."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    scale = float(sm_scale) if sm_scale is not None else 1.0 / math.sqrt(d)
    has_extras = (bias is not None or q_segment_ids is not None
                  or startend_row_indices is not None or dropout_p > 0)
    target = _block_target(has_extras)
    bq = block_q or _pick_block(sq, target)
    bk = block_k or _pick_block(sk, target)
    if bias is not None or q_segment_ids is not None \
            or startend_row_indices is not None:
        # bias/segment/flashmask BlockSpecs put the block size in the lane
        # dim, so Mosaic needs 128-multiples there (seqs are already %128)
        bq = bq if bq % 128 == 0 else 128
        bk = bk if bk % 128 == 0 else 128
    if window is not None:
        left, right = window
        window = (None if left is None or left < 0 else int(left),
                  None if right is None or right < 0 else int(right))
        if window == (None, None):
            window = None
    enforce(not (dropout_p > 0 and dropout_seed is None),
            "dropout_p > 0 requires dropout_seed", op="flash_attention")
    if q_segment_ids is not None:
        q_segment_ids = q_segment_ids.astype(jnp.int32)
        kv_segment_ids = kv_segment_ids.astype(jnp.int32)
    packed_bias = bias
    if startend_row_indices is not None:
        fm_start, fm_end = startend_row_indices
        packed_bias = (bias, fm_start.astype(jnp.int32),
                       fm_end.astype(jnp.int32))
    return _flash(query, key, value, packed_bias, q_segment_ids,
                  kv_segment_ids, dropout_seed, bool(causal), scale, bq, bk,
                  window, float(dropout_p), bool(bias_grad))
