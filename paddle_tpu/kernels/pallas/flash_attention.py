"""Tiled flash attention for TPU (Pallas).

TPU-native replacement for the reference's CUDA FlashAttention-2 integration
(reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu and
third_party/flashattn; Python surface python/paddle/nn/functional/
flash_attention.py:242).

Design (FlashAttention-2 style, mapped onto the TPU memory hierarchy):
  * grid = (batch*heads, q_blocks, k_blocks); the k axis is innermost so the
    online-softmax state (m, l, acc) carries across k steps in VMEM scratch —
    the scores matrix never exists in HBM (O(S) memory instead of O(S^2)).
  * QK^T and PV run on the MXU with fp32 accumulation
    (preferred_element_type); rescaling on the VPU.
  * causal masking skips whole blocks above the diagonal (predicated with
    pl.when) and applies an iota mask only on diagonal-straddling blocks.
  * backward = two kernels (dkv with q innermost; dq with k innermost) using
    the saved logsumexp and a precomputed delta = rowsum(dO * O), per the
    FlashAttention-2 backward recurrence.

Layouts: public API takes paddle convention [B, S, H, D]; kernels run on
[B*H, S, D].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import LANES as _LANES
from ._common import interpret as _interpret

__all__ = ["flash_attention", "supported"]

_NEG_INF = -1e30


def _pick_block(s: int, target: int = 1024) -> int:
    """Largest power-of-two-ish divisor of s up to `target`. Measured on
    v5e (GPT-268M, seq 1024): 1024 > 512 > 256 (47.4k vs 43.3k vs 34.2k
    tok/s end-to-end) — bigger q/k tiles amortize the softmax rescale; the
    fp32 scores tile at 1024x1024 (4 MB) still fits VMEM comfortably."""
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def supported(query, key, value, attn_mask=None, dropout_p=0.0,
              is_causal=False, *args, **kwargs) -> bool:
    """Gate for registry dispatch: the tiled kernel handles dense/causal
    attention without dropout or ad-hoc masks; anything else falls back to
    the XLA-composed reference op."""
    if attn_mask is not None or dropout_p > 0.0:
        return False
    if query.ndim != 4 or key.ndim != 4 or value.ndim != 4:
        return False
    b, sq, h, d = query.shape
    sk = key.shape[1]
    if key.shape != (b, sk, h, d):  # GQA handled by the caller via head repeat
        return False
    if tuple(value.shape) != tuple(key.shape):
        return False
    if is_causal and sq != sk:
        return False
    if d > 256:
        return False
    # blocks must tile the sequence exactly at lane granularity
    # (pad upstream otherwise)
    return sq % 128 == 0 and sk % 128 == 0


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, sm_scale, causal, block_q, block_k,
                num_k):
    """lse_ref is None on the inference path (no residual HBM write)."""
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # causal: block (i, j) contributes iff some k pos <= some q pos
    run = True
    if causal:
        run = j * block_k <= i * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            # mask only matters on diagonal-straddling blocks, but applying
            # it unconditionally inside the predicated body is branch-free
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        m_prev = m_sc[:, 0]                      # [bq]
        m_cur = jnp.max(s, axis=1)               # [bq]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)          # [bq]
        p = jnp.exp(s - m_new[:, None])          # [bq, bk] f32
        l_sc[:] = (l_sc[:] * alpha[:, None]
                   + jnp.sum(p, axis=1)[:, None])
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + pv

    if causal:
        j_last = jnp.minimum(num_k - 1,
                             (i * block_q + block_q - 1) // block_k)
    else:
        j_last = num_k - 1

    @pl.when(j == j_last)
    def _finalize():
        l = l_sc[:, 0]
        inv = jnp.where(l == 0.0, 0.0, 1.0 / l)
        o_ref[0] = (acc_sc[:] * inv[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = m_sc[:, 0] + jnp.log(jnp.maximum(l, 1e-37))
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, save_lse=True):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    grid = (bh, nq, nk)
    base = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=nk)
    ospec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    lspec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    if save_lse:
        kernel = base
        out_specs = [ospec, lspec]
        out_shape = [jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                     jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32)]
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, acc_sc, m_sc, l_sc):
            base(q_ref, k_ref, v_ref, o_ref, None, acc_sc, m_sc, l_sc)
        out_specs = ospec
        out_shape = jax.ShapeDtypeStruct((bh, sq, d), q.dtype)
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    if save_lse:
        out, lse = res
        return out, lse[:, :, 0]
    return res, None


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, sm_scale, causal,
                block_q, block_k, num_q):
    j = pl.program_id(1)  # k block
    i = pl.program_id(2)  # q block (innermost: carry dk/dv across q)

    @pl.when(i == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    run = True
    if causal:
        run = i * block_q + block_q - 1 >= j * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        kk = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]      # [bq]
        delta = delta_ref[0][:, 0]  # [bq]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # [bq, bk]
        # dv += P^T dO
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dP = dO V^T ; dS = P*(dP - delta)*scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_sc, *, sm_scale, causal, block_q, block_k, num_k):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (innermost: carry dq)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    run = True
    if causal:
        run = j * block_k <= i * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        kk = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        j_last = jnp.minimum(num_k - 1,
                             (i * block_q + block_q - 1) // block_k)
    else:
        j_last = num_k - 1

    @pl.when(j == j_last)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_impl(q, k, v, out, lse, do, sm_scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [bh, sq]
    lse_r = jnp.broadcast_to(lse[:, :, None], (bh, sq, _LANES))
    delta_r = jnp.broadcast_to(delta[:, :, None], (bh, sq, _LANES))

    qspec = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    rspec = pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=nq),
        grid=(bh, nk, nq),
        in_specs=[qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse_r, delta_r)

    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    rspec2 = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k=nk),
        grid=(bh, nq, nk),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rspec2, rspec2],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse_r, delta_r)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API ([B, S, H, D] layout, custom_vjp)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(query, key, value, causal=False, sm_scale=None,
                    block_q=None, block_k=None):
    """Fused attention. query/key/value: [B, S, H, D] → [B, S, H, D].

    The primal (inference) path skips the logsumexp residual entirely — no
    extra HBM traffic; it is produced only when jax needs the vjp."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    bq = block_q or _pick_block(sq)
    bk = block_k or _pick_block(sk)
    out, _ = _fwd(_prep(query), _prep(key), _prep(value), scale, causal,
                  bq, bk, save_lse=False)
    return _unprep(out, b, h)


def _prep(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _unprep(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


def _flash_fwd(query, key, value, causal, sm_scale, block_q, block_k):
    b, sq, h, d = query.shape
    sk = key.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    bq = block_q or _pick_block(sq)
    bk = block_k or _pick_block(sk)
    q, k, v = _prep(query), _prep(key), _prep(value)
    out, lse = _fwd(q, k, v, scale, causal, bq, bk)
    return _unprep(out, b, h), (q, k, v, out, lse, b, h, scale)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out, lse, b, h, scale = res
    sq, sk = q.shape[1], k.shape[1]
    bq = block_q or _pick_block(sq)
    bk = block_k or _pick_block(sk)
    do = _prep(g)
    dq, dk, dv = _bwd_impl(q, k, v, out, lse, do, scale, causal, bq, bk)
    return _unprep(dq, b, h), _unprep(dk, b, h), _unprep(dv, b, h)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
