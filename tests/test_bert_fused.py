"""BERT family + fused_attention/fused_feedforward tests (reference
analogs: test_fused_attention_op.py, test_fused_feedforward_op.py, and
the BERT pretraining baseline config)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.incubate.nn.functional import (fused_attention,
                                               fused_feedforward)
from paddle_tpu.models import bert as B


CFG = B.BertConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                   intermediate_size=64, max_position_embeddings=32,
                   hidden_dropout=0.0)


def test_bert_forward_shapes():
    model = B.BertModel(CFG)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    seq, pooled = model(ids)
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)


def test_bert_padding_mask_changes_output():
    model = B.BertModel(CFG)
    model.eval()
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (1, 8)))
    full, _ = model(ids, attention_mask=jnp.ones((1, 8)))
    half_mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]])
    masked, _ = model(ids, attention_mask=half_mask)
    # visible positions change when later tokens are masked out
    assert not np.allclose(np.asarray(full[:, 0]), np.asarray(masked[:, 0]))


def test_bert_pretraining_loss_decreases():
    model = B.BertForPretraining(CFG)
    model.train()
    from paddle_tpu.nn import functional_call, functional_train_graph
    params, _, buffers = functional_train_graph(model)
    opt = paddle.optimizer.AdamW(1e-3)
    state = jax.jit(opt.init_state)(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 128, (4, 16)))
    mlm_labels = jnp.asarray(np.where(rng.rand(4, 16) < 0.15,
                                      np.asarray(ids), -100))
    nsp_labels = jnp.asarray(rng.randint(0, 2, (4,)))

    @jax.jit
    def step(p, s):
        def loss_fn(p):
            (mlm, nsp), _ = functional_call(model, p, buffers, ids)
            return B.bert_pretrain_loss(mlm, nsp, mlm_labels, nsp_labels)
        l, g = jax.value_and_grad(loss_fn)(p)
        p, s = opt.apply(p, g, s, 1e-3)
        return p, s, l

    losses = []
    for _ in range(10):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_fused_attention_matches_composition():
    rng = np.random.RandomState(2)
    B_, S, H, heads = 2, 8, 16, 4
    hd = H // heads
    x = jnp.asarray(rng.randn(B_, S, H).astype(np.float32))
    qkv_w = jnp.asarray(rng.randn(3, heads, hd, H).astype(np.float32) * 0.2)
    qkv_b = jnp.asarray(rng.randn(3, heads, hd).astype(np.float32) * 0.1)
    lin_w = jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.2)
    lin_b = jnp.asarray(rng.randn(H).astype(np.float32) * 0.1)
    ln_s = jnp.ones(H); ln_b = jnp.zeros(H)

    out = fused_attention(x, qkv_w, lin_w, pre_layer_norm=False,
                          ln_scale=ln_s, ln_bias=ln_b, qkv_bias=qkv_b,
                          linear_bias=lin_b, training=False)

    # reference composition
    w2 = qkv_w.reshape(3 * H, H).T
    qkv = (x @ w2 + qkv_b.reshape(-1)).reshape(B_, S, 3, heads, hd)
    attn = F.scaled_dot_product_attention(qkv[:, :, 0], qkv[:, :, 1],
                                          qkv[:, :, 2], training=False)
    ref = x + attn.reshape(B_, S, H) @ lin_w + lin_b
    ref = F.layer_norm(ref, (H,), ln_s, ln_b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fused_attention_pre_ln():
    rng = np.random.RandomState(3)
    B_, S, H, heads = 1, 4, 8, 2
    x = jnp.asarray(rng.randn(B_, S, H).astype(np.float32))
    qkv_w = jnp.asarray(rng.randn(3, heads, H // heads, H)
                        .astype(np.float32) * 0.2)
    lin_w = jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.2)
    out = fused_attention(x, qkv_w, lin_w, pre_layer_norm=True,
                          pre_ln_scale=jnp.ones(H),
                          pre_ln_bias=jnp.zeros(H), training=False)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_fused_feedforward_matches_composition():
    rng = np.random.RandomState(4)
    B_, S, H, FF = 2, 6, 12, 24
    x = jnp.asarray(rng.randn(B_, S, H).astype(np.float32))
    w1 = jnp.asarray(rng.randn(H, FF).astype(np.float32) * 0.3)
    b1 = jnp.asarray(rng.randn(FF).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(FF, H).astype(np.float32) * 0.3)
    b2 = jnp.asarray(rng.randn(H).astype(np.float32) * 0.1)
    ln_s, ln_b = jnp.ones(H), jnp.zeros(H)
    out = fused_feedforward(x, w1, w2, b1, b2, ln2_scale=ln_s, ln2_bias=ln_b,
                            dropout1_rate=0.0, dropout2_rate=0.0,
                            activation="gelu", training=False)
    ref = x + (F.gelu(x @ w1 + b1) @ w2 + b2)
    ref = F.layer_norm(ref, (H,), ln_s, ln_b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

def test_fused_multi_transformer_prefill_decode_parity():
    """Cached decode reproduces the prefill stack position-by-position
    (reference: test_fused_multi_transformer_op.py parity pattern)."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    import jax

    H, heads, FF, L = 16, 4, 32, 2
    m = FusedMultiTransformer(H, heads, FF, num_layers=L)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 6, H).astype(np.float32) * 0.5)

    full = m(x)  # prefill / training path (flash attention)
    assert full.shape == (2, 6, H)

    # decode token-by-token against the full forward
    cache = m.gen_cache(batch=2, max_len=6)
    outs = []
    for t in range(6):
        o, cache = m(x[:, t:t + 1], caches=cache, time_step=t)
        outs.append(o)
    dec = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full), atol=1e-4)


def test_fused_multi_transformer_jits_and_grads():
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.nn import functional_call, functional_train_graph
    import jax

    m = FusedMultiTransformer(8, 2, 16, num_layers=2)
    params, _, buffers = functional_train_graph(m)
    x = jnp.ones((1, 4, 8))

    @jax.jit
    def loss(p):
        out, _ = functional_call(m, p, buffers, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(v)).all()
               for v in jax.tree.leaves(g))


def test_fused_multi_transformer_prefill_into_cache_then_decode():
    """Reference usage: one prefill call fills the cache, then decode
    continues from it."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    H, heads, FF, L = 16, 4, 32, 2
    m = FusedMultiTransformer(H, heads, FF, num_layers=L)
    m.eval()
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(2, 8, H).astype(np.float32) * 0.5)

    full = m(x)  # reference: all 8 positions, no cache
    cache = m.gen_cache(batch=2, max_len=8)
    pre, cache = m(x[:, :6], caches=cache, time_step=0)   # prefill 6
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :6]),
                               atol=1e-4)
    o6, cache = m(x[:, 6:7], caches=cache, time_step=6)   # decode 7th
    o7, cache = m(x[:, 7:8], caches=cache, time_step=7)   # decode 8th
    np.testing.assert_allclose(np.asarray(o6), np.asarray(full[:, 6:7]),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(o7), np.asarray(full[:, 7:8]),
                               atol=1e-4)


def test_fused_multi_transformer_dropout_active_in_train():
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    m = FusedMultiTransformer(8, 2, 16, num_layers=1, dropout_rate=0.5)
    # random input: an all-constant row is a pre-LN fixed point (LN maps
    # it to zero) and would make the whole stack the identity
    x = jnp.asarray(np.random.RandomState(6).randn(1, 4, 8)
                    .astype(np.float32))
    m.eval()
    a, b = np.asarray(m(x)), np.asarray(m(x))
    np.testing.assert_array_equal(a, b)  # eval: deterministic
    m.train()
    c, d = np.asarray(m(x)), np.asarray(m(x))
    assert not np.array_equal(c, d)  # train: dropout noise present


def test_bert_packed_matches_unpacked():
    """Two sequences packed into one row (in-kernel segment masking +
    restarting position ids) must produce the same per-token encodings as
    running each sequence in its own row."""
    cfg = B.BertConfig(vocab_size=97, hidden_size=32, num_layers=2,
                       num_heads=4, intermediate_size=64,
                       max_position_embeddings=16, hidden_dropout=0.0)
    model = B.BertModel(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    seq_a = rng.randint(0, 97, (9,))
    seq_b = rng.randint(0, 97, (5,))

    ids, seg, pos, row_of, off_of = B.pack_sequences([seq_a, seq_b], 16)
    assert ids.shape == (1, 16) and row_of == [0, 0] and off_of == [0, 9]
    packed, _ = model(jnp.asarray(ids), pack_segment_ids=jnp.asarray(seg),
                      position_ids=jnp.asarray(pos))

    for s, off in ((seq_a, 0), (seq_b, 9)):
        L = len(s)
        solo_ids = np.zeros((1, 16), np.int32)
        solo_ids[0, :L] = s
        mask = np.zeros((1, 16), np.int64)
        mask[0, :L] = 1
        solo, _ = model(jnp.asarray(solo_ids),
                        attention_mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(packed[0, off:off + L]),
                                   np.asarray(solo[0, :L]),
                                   atol=2e-5, rtol=1e-5)


def test_pack_sequences_first_fit():
    ids, seg, pos, row_of, off_of = B.pack_sequences(
        [np.arange(1, 7), np.arange(1, 6), np.arange(1, 4)], 8, pad_id=0)
    # 6+5 > 8 -> seq1 opens row 1; seq2 (len 3) fits after seq1 (5+3=8)
    assert ids.shape == (2, 8)
    assert row_of == [0, 1, 1] and off_of == [0, 0, 5]
    assert list(seg[0][:6]) == [0] * 6 and list(seg[0][6:]) == [-1] * 2
    assert list(pos[0][:6]) == [0, 1, 2, 3, 4, 5]
    assert list(seg[1]) == [0] * 5 + [1] * 3
    assert list(pos[1]) == [0, 1, 2, 3, 4, 0, 1, 2]
