"""Parameter-server mode (reference: paddle/fluid/distributed/ps/ — brpc
services + tables; python/paddle/distributed/ps/the_one_ps.py runtime;
fleet PS mode via ``fleet.init(role_maker)`` non-collective).

The rec-sys workload class: embedding tables too large for chip memory
live server-side (host RAM), TPU workers pull touched rows / push grads.
See table.py and service.py for the split mirroring the reference's
table/accessor vs brpc service layers.
"""

from __future__ import annotations
from ...enforce import PreconditionNotMetError, enforce

from typing import List, Optional

from .service import PsClient, PsServer, TableConfig
from .table import (AdaGradRule, AdamRule, DenseTable, SGDRule, SparseTable,
                    make_rule)

__all__ = ["TableConfig", "PsServer", "PsClient", "DenseTable", "SparseTable",
           "SGDRule", "AdamRule", "AdaGradRule", "make_rule", "TheOnePs",
           "PsRole"]


class PsRole:
    SERVER = "server"
    WORKER = "worker"


class TheOnePs:
    """(reference: python/paddle/distributed/ps/the_one_ps.py TheOnePSRuntime)
    role-driven runtime facade: servers build and serve tables, workers get
    a connected client."""

    def __init__(self, role: str, configs: Optional[List[TableConfig]] = None,
                 endpoint: Optional[str] = None, client_id: int = 0):
        self.role = role
        self.server: Optional[PsServer] = None
        self.client: Optional[PsClient] = None
        if role == PsRole.SERVER:
            enforce(configs is not None,
                    "server role needs table configs", op="ps.init",
                    error=PreconditionNotMetError)
            self.server = PsServer(configs)
            self.endpoint = self.server.endpoint
        else:
            enforce(endpoint is not None,
                    "worker role needs the server endpoint", op="ps.init",
                    error=PreconditionNotMetError)
            self.client = PsClient(endpoint, client_id=client_id)
            self.endpoint = endpoint

    def stop(self):
        if self.client is not None:
            self.client.close()
        if self.server is not None:
            self.server.stop()
