"""Decode-path tests: KV-cache generation parity vs full re-forward,
masked/paged attention correctness, sampling (reference analogs:
test_fused_multi_transformer_op.py, test_block_multihead_attention.py,
test_masked_multihead_attention_op.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import gpt as G
from paddle_tpu.models import llama as L
from paddle_tpu.models import generation as gen


GCFG = G.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                   max_seq_len=64, dtype=jnp.float32)
LCFG = L.LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=48, max_seq_len=64,
                     dtype=jnp.float32)


def ref_greedy(dense_forward, params, cfg, prompt, n):
    """Reference: recompute the full forward over the whole prefix each
    step, take argmax — no cache."""
    toks = np.asarray(prompt)
    for _ in range(n):
        logits = np.asarray(dense_forward(params, jnp.asarray(toks), cfg,
                                          remat=False))
        nxt = logits[:, -1].argmax(-1)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_gpt_generate_matches_full_reforward():
    params = G.init_hybrid_params(GCFG, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 5)))
    out = gen.gpt_generate(params, GCFG, prompt, max_new_tokens=6,
                           temperature=0.0)
    ref = ref_greedy(G.dense_forward, params, GCFG, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_llama_generate_matches_full_reforward():
    params = L.init_hybrid_params(LCFG, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 4)))
    out = gen.llama_generate(params, LCFG, prompt, max_new_tokens=5,
                             temperature=0.0)
    ref = ref_greedy(L.dense_forward, params, LCFG, prompt, 5)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_generate_under_jit():
    params = G.init_hybrid_params(GCFG, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(2).randint(0, 64, (1, 3)))
    f = jax.jit(lambda p, t: gen.gpt_generate(p, GCFG, t, max_new_tokens=4))
    out = f(params, prompt)
    assert out.shape == (1, 7)


def test_masked_mha_matches_causal_slice():
    """Decode attention at position t == row t of full causal attention."""
    rng = np.random.RandomState(3)
    B, T, h, D = 2, 8, 4, 6
    k = jnp.asarray(rng.randn(B, T, h, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, h, D).astype(np.float32))
    q_all = jnp.asarray(rng.randn(B, T, h, D).astype(np.float32))
    full = L._gqa_attention(q_all, k, v)  # causal full attention
    for t in (0, 3, 7):
        out = gen.masked_multihead_attention(q_all[:, t:t + 1], k, v, t + 1)
        np.testing.assert_allclose(np.asarray(out[:, 0]),
                                   np.asarray(full[:, t]), atol=1e-5)


def test_masked_mha_gqa_grouping():
    rng = np.random.RandomState(4)
    B, T, hq, hkv, D = 1, 5, 4, 2, 4
    q = jnp.asarray(rng.randn(B, 1, hq, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, hkv, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, hkv, D).astype(np.float32))
    out = gen.masked_multihead_attention(q, k, v, T)
    kf = jnp.repeat(k, hq // hkv, axis=2)
    vf = jnp.repeat(v, hq // hkv, axis=2)
    ref = gen.masked_multihead_attention(q, kf, vf, T)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_paged_attention_matches_contiguous():
    rng = np.random.RandomState(5)
    B, h, D, bs = 2, 2, 4, 4
    lens = [6, 3]
    cache = gen.PagedKVCache.create(num_blocks=8, block_size=bs,
                                    num_kv_heads=h, head_dim=D, batch=B,
                                    max_blocks_per_seq=2, dtype=jnp.float32)
    # non-trivial block assignment: seq0 -> blocks [5, 1], seq1 -> [3, 0]
    cache.block_tables = jnp.asarray([[5, 1], [3, 0]], jnp.int32)
    contig_k = np.zeros((B, 8, h, D), np.float32)
    contig_v = np.zeros((B, 8, h, D), np.float32)
    for b in range(B):
        for t in range(lens[b]):
            kk = rng.randn(h, D).astype(np.float32)
            vv = rng.randn(h, D).astype(np.float32)
            contig_k[b, t], contig_v[b, t] = kk, vv
            cache = cache.write(b, jnp.asarray(kk), jnp.asarray(vv))
    np.testing.assert_array_equal(np.asarray(cache.seq_lens), lens)
    q = jnp.asarray(rng.randn(B, 1, h, D).astype(np.float32))
    out = gen.block_multihead_attention(q, cache)
    ref = gen.masked_multihead_attention(
        q, jnp.asarray(contig_k), jnp.asarray(contig_v),
        jnp.asarray(lens))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_paged_write_capacity_check():
    cache = gen.PagedKVCache.create(num_blocks=4, block_size=2,
                                    num_kv_heads=1, head_dim=2, batch=1,
                                    max_blocks_per_seq=2, dtype=jnp.float32)
    cache.block_tables = jnp.asarray([[0, 1]], jnp.int32)
    k = jnp.ones((1, 2))
    for _ in range(4):
        cache = cache.write(0, k, k)
    with pytest.raises(ValueError, match="full"):
        cache.write(0, k, k)


def test_generate_single_token():
    params = G.init_hybrid_params(GCFG, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(6).randint(0, 64, (2, 4)))
    out = gen.gpt_generate(params, GCFG, prompt, max_new_tokens=1,
                           temperature=0.0)
    ref = ref_greedy(G.dense_forward, params, GCFG, prompt, 1)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_sampling_top_k_and_temperature():
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
    # greedy
    assert int(gen.sample_token(logits, jax.random.PRNGKey(0), 0.0)[0]) == 3
    # top-1 sampling == greedy regardless of temperature
    for s in range(5):
        t = gen.sample_token(logits, jax.random.PRNGKey(s), 1.0, top_k=1)
        assert int(t[0]) == 3
    # top-2 never samples outside the top 2
    for s in range(20):
        t = gen.sample_token(logits, jax.random.PRNGKey(s), 1.0, top_k=2)
        assert int(t[0]) in (2, 3)
    # top-p tight: p below top prob -> argmax only
    for s in range(5):
        t = gen.sample_token(logits, jax.random.PRNGKey(s), 1.0, top_p=0.3)
        assert int(t[0]) == 3

def test_generate_zero_tokens_returns_prompt():
    params = G.init_hybrid_params(GCFG, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.RandomState(7).randint(0, 64, (2, 4)))
    out = gen.gpt_generate(params, GCFG, prompt, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_paged_attention_ragged_gqa_and_prefill():
    """Paged kernel over a ragged batch with GQA + vectorized prefill:
    matches the gather reference; empty sequences emit zeros."""
    rng = np.random.RandomState(9)
    B, hq, hkv, D, bs = 3, 4, 2, 16, 4
    cache = gen.PagedKVCache.create(num_blocks=12, block_size=bs,
                                    num_kv_heads=hkv, head_dim=D, batch=B,
                                    max_blocks_per_seq=3, dtype=jnp.float32)
    cache.block_tables = jnp.asarray(
        [[7, 2, 9], [4, 0, 1], [5, 6, 8]], jnp.int32)
    # seq0: prefill 10 tokens at once; seq1: 3 single writes; seq2: empty
    k0 = jnp.asarray(rng.randn(10, hkv, D), jnp.float32)
    v0 = jnp.asarray(rng.randn(10, hkv, D), jnp.float32)
    cache = cache.prefill(0, k0, v0)
    for _ in range(3):
        cache = cache.write(1, jnp.asarray(rng.randn(hkv, D), jnp.float32),
                            jnp.asarray(rng.randn(hkv, D), jnp.float32))
    np.testing.assert_array_equal(np.asarray(cache.seq_lens), [10, 3, 0])
    q = jnp.asarray(rng.randn(B, 1, hq, D), jnp.float32)
    out = gen.block_multihead_attention(q, cache)
    ref = gen._paged_gather_reference(q, cache)
    np.testing.assert_allclose(np.asarray(out[:2]), np.asarray(ref[:2]),
                               rtol=1e-5, atol=1e-5)
    assert float(jnp.abs(out[2]).max()) == 0.0  # empty sequence → zeros


def test_paged_prefill_then_write_continuity():
    """prefill() and write() fill the same slots a contiguous cache would."""
    rng = np.random.RandomState(11)
    h, D, bs = 2, 8, 4
    cache = gen.PagedKVCache.create(num_blocks=6, block_size=bs,
                                    num_kv_heads=h, head_dim=D, batch=1,
                                    max_blocks_per_seq=3, dtype=jnp.float32)
    cache.block_tables = jnp.asarray([[4, 1, 3]], jnp.int32)
    ks = jnp.asarray(rng.randn(7, h, D), jnp.float32)
    vs = jnp.asarray(rng.randn(7, h, D), jnp.float32)
    cache = cache.prefill(0, ks[:5], vs[:5])
    for t in range(5, 7):
        cache = cache.write(0, ks[t], vs[t])
    # slot-by-slot: token t lives at pool[:, table[t//bs], t%bs]
    for t in range(7):
        blk = int(cache.block_tables[0, t // bs])
        np.testing.assert_allclose(np.asarray(cache.k_pool[:, blk, t % bs]),
                                   np.asarray(ks[t]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(cache.v_pool[:, blk, t % bs]),
                                   np.asarray(vs[t]), rtol=1e-6)
    with pytest.raises(ValueError, match="full"):
        cache.prefill(0, ks, vs)  # 7 + 7 > 12
