"""Norm layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm note: running stats are Layer *buffers*; in training mode the
functional batch_norm returns updated stats and the layer writes them back to
its buffer slots. Under `functional_call` tracing those writes are captured
as explicit state outputs (see layers.py), keeping the jitted step pure.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "GroupNorm",
           "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
           "LocalResponseNorm", "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        training = self.training and not (self.use_global_stats is True)
        out = F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                           training=training, momentum=self.momentum,
                           epsilon=self.epsilon, data_format=self.data_format,
                           use_global_stats=self.use_global_stats)
        if isinstance(out, tuple):
            out, new_mean, new_var = out
            self._buffers["_mean"] = new_mean
            self._buffers["_variance"] = new_var
        return out


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm (reference: nn/layer/norm.py SyncBatchNorm →
    sync_batch_norm CUDA kernel w/ NCCL allreduce of stats).

    TPU design: when called inside shard_map/pjit over a mesh with a data
    axis, stats are all-reduced over that axis with lax.pmean; outside a mesh
    it degrades to plain BatchNorm.
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format)
        self._axis_name = None

    def set_mesh_axis(self, axis_name):
        self._axis_name = axis_name

    def forward(self, x):
        from jax import lax
        if not self.training or self._axis_name is None:
            return super().forward(x)
        channels_last = self.data_format.endswith("C") and self.data_format != "NC"
        c_axis = x.ndim - 1 if channels_last else 1
        red = tuple(i for i in range(x.ndim) if i != c_axis)
        xf = x.astype(jnp.float32)
        mean = lax.pmean(jnp.mean(xf, axis=red), self._axis_name)
        mean2 = lax.pmean(jnp.mean(jnp.square(xf), axis=red), self._axis_name)
        var = mean2 - jnp.square(mean)
        self._buffers["_mean"] = self.momentum * self._buffers["_mean"] + (1 - self.momentum) * mean
        self._buffers["_variance"] = self.momentum * self._buffers["_variance"] + (1 - self.momentum) * var
        shape = [1] * x.ndim
        shape[c_axis] = x.shape[c_axis]
        out = (xf - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + self.epsilon)
        out = out.astype(x.dtype)
        if self.weight is not None:
            out = out * self.weight.value.reshape(shape)
        if self.bias is not None:
            out = out + self.bias.value.reshape(shape)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight.value)
            if layer.bias is not None:
                new.bias.set_value(layer.bias.value)
            new._buffers["_mean"] = layer._buffers["_mean"]
            new._buffers["_variance"] = layer._buffers["_variance"]
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    """RMSNorm layer (reference fused op:
    python/paddle/incubate/nn/functional/fused_rms_norm.py)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = (self.create_parameter((num_channels,), attr=weight_attr,
                                             default_initializer=Constant(1.0))
                       if weight_attr is not False else None)
        self.bias = (self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = (self.create_parameter((num_features,), attr=weight_attr,
                                             default_initializer=Constant(1.0))
                       if weight_attr is not False else None)
        self.bias = (self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization of a weight (power iteration)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        self.axis, self.power_iters, self.epsilon = axis, power_iters, epsilon
        h = weight_shape[axis]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != axis:
                w *= s
        from ..initializer import Normal
        self.weight_u = self.create_parameter((h,), default_initializer=Normal(0, 1))
        self.weight_v = self.create_parameter((w,), default_initializer=Normal(0, 1))
        self.weight_u.trainable = False
        self.weight_v.trainable = False

    def forward(self, weight):
        w = jnp.moveaxis(jnp.asarray(weight), self.axis, 0)
        mat = w.reshape(w.shape[0], -1)
        u, v = self.weight_u.value, self.weight_v.value
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        sigma = u @ mat @ v
        return jnp.asarray(weight) / sigma
