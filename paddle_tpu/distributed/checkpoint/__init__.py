"""Distributed (sharding-aware) checkpoint with reshard-on-load
(reference: python/paddle/distributed/checkpoint/ — SURVEY §2.9)."""

from .load_state_dict import (load_full_state_dict, load_metadata,
                              load_state_dict)
from .metadata import (LocalTensorIndex, LocalTensorMetadata, Metadata,
                       SavedLayout)
from .pp_adaptor import pp_relayout_state_dict
from .reshard import layout_mismatch, load_resharded
from .save_state_dict import build_layout, save_state_dict, wait_async_save
from .utils import flatten_state_dict, unflatten_state_dict
from . import pp_adaptor

__all__ = [
    "save_state_dict", "load_state_dict", "load_full_state_dict",
    "wait_async_save", "load_metadata",
    "Metadata", "LocalTensorMetadata", "LocalTensorIndex", "SavedLayout",
    "build_layout", "layout_mismatch", "load_resharded",
    "flatten_state_dict", "unflatten_state_dict",
    "pp_adaptor", "pp_relayout_state_dict",
]
