"""paddle.incubate equivalent namespace (fused-op API surface)."""

from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
