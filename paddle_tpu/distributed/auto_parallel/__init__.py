from .api import (ShardingStage1, ShardingStage2, ShardingStage3,
                  dtensor_from_local, dtensor_to_local, get_placements,
                  reshard, shard_layer, shard_optimizer, shard_tensor,
                  unshard_dtensor)
from .dist_model import DistModel, to_static
from .placement_type import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = [
    "ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "shard_layer", "dtensor_from_local",
    "dtensor_to_local", "unshard_dtensor", "shard_optimizer", "get_placements",
    "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "DistModel", "to_static",
]
