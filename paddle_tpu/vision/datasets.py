"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: dataset classes read local files (same on-disk
formats as the reference: CIFAR pickle batches, MNIST idx). FakeImageDataset
generates deterministic synthetic data for benchmarks and CI.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np
from ..enforce import enforce_eq

from ..io import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FakeImageDataset"]


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification data (CI/bench stand-in
    for downloads; reference tests use similar fakes, SURVEY §4.6)."""

    def __init__(self, num_samples=1024, image_shape=(3, 32, 32),
                 num_classes=10, transform: Optional[Callable] = None,
                 dtype="float32", seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype
        self._rng_seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState((self._rng_seed * 1000003 + idx) % (2**31))
        img = rng.randn(*self.image_shape).astype(self.dtype)
        label = rng.randint(0, self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


class Cifar10(Dataset):
    """CIFAR-10 from a local archive (reference:
    python/paddle/vision/datasets/cifar.py; same pickle-batch format)."""

    def __init__(self, data_file: Optional[str] = None, mode="train",
                 transform=None, download=False, backend="cv2"):
        del download, backend
        self.mode = mode
        self.transform = transform
        self.data = []
        self.labels = []
        if data_file is not None and os.path.exists(data_file):
            self._load_archive(data_file)
        else:
            raise FileNotFoundError(
                "Cifar10 requires a local data_file (no network access); "
                "use vision.datasets.FakeImageDataset for synthetic data")

    def _load_archive(self, path):
        names = ([f"data_batch_{i}" for i in range(1, 6)]
                 if self.mode == "train" else ["test_batch"])
        with tarfile.open(path, "r:*") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    self.data.append(d[b"data"])
                    self.labels.extend(d[b"labels"])
        self.data = np.concatenate(self.data).reshape(-1, 3, 32, 32)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.data[idx].astype("float32") / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


class Cifar100(Cifar10):
    def _load_archive(self, path):
        names = ["train"] if self.mode == "train" else ["test"]
        with tarfile.open(path, "r:*") as tf:
            for m in tf.getmembers():
                if os.path.basename(m.name) in names:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    self.data.append(d[b"data"])
                    self.labels.extend(d[b"fine_labels"])
        self.data = np.concatenate(self.data).reshape(-1, 3, 32, 32)


class MNIST(Dataset):
    """MNIST from local idx files (reference:
    python/paddle/vision/datasets/mnist.py)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        del download, backend, mode
        self.transform = transform
        if image_path is None or label_path is None:
            raise FileNotFoundError(
                "MNIST requires local image_path/label_path (no network)")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(p):
        return gzip.open(p, "rb") if p.endswith(".gz") else open(p, "rb")

    def _read_images(self, p):
        with self._open(p) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            enforce_eq(magic, 2051, "bad MNIST image-file magic",
                       op="vision.datasets.MNIST")
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(n, 1, rows, cols)

    def _read_labels(self, p):
        with self._open(p) as f:
            magic, n = struct.unpack(">II", f.read(8))
            enforce_eq(magic, 2049, "bad MNIST label-file magic",
                       op="vision.datasets.MNIST")
            return np.frombuffer(f.read(), dtype=np.uint8)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32") / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


def _default_loader(path: str):
    """cv2-first image loading like the reference's folder datasets
    (vision/datasets/folder.py default backends); .npy arrays load
    directly so pipelines can stay image-library-free."""
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"))
    except ImportError:
        import cv2

        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:  # cv2's documented failure mode: no exception
            from ..enforce import UnavailableError
            raise UnavailableError(f"cv2 could not read image: {path}",
                                   op="DatasetFolder.loader")
        return img[:, :, ::-1]  # BGR -> RGB


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


class DatasetFolder(Dataset):
    """Class-per-subdirectory dataset (reference:
    vision/datasets/folder.py DatasetFolder): root/<class_x>/xxx.ext.
    Yields (sample, class_index); `classes`/`class_to_idx` expose the
    discovered taxonomy."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform: Optional[Callable] = None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            from ..enforce import NotFoundError
            raise NotFoundError(f"no class folders under {root}",
                                op="DatasetFolder")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        valid = (is_valid_file if is_valid_file is not None
                 else lambda p: p.lower().endswith(tuple(extensions)))
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    p = os.path.join(dirpath, f)
                    if valid(p):
                        self.samples.append((p, self.class_to_idx[c]))
        self.targets = [t for _, t in self.samples]

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(Dataset):
    """Unlabeled image-folder dataset (reference: folder.py ImageFolder):
    every valid file under root, returned as [sample]."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform: Optional[Callable] = None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        valid = (is_valid_file if is_valid_file is not None
                 else lambda p: p.lower().endswith(tuple(extensions)))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                p = os.path.join(dirpath, f)
                if valid(p):
                    self.samples.append(p)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


class FashionMNIST(MNIST):
    """Fashion-MNIST (reference: vision/datasets/mnist.py FashionMNIST) —
    identical idx file format and loader, different corpus files (point
    image_path/label_path at the fashion idx files)."""


class Flowers(Dataset):
    """Flowers102 (reference: vision/datasets/flowers.py — same archive
    layout and the reference's swapped trnid/tstid convention): data_file
    = 102flowers tgz (jpg/image_%05d.jpg), label_file = imagelabels.mat,
    setid_file = setid.mat. Local files only (no egress)."""

    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend="pil"):
        import os
        import tarfile

        from ..enforce import UnavailableError, enforce, enforce_in
        mode = mode.lower()
        enforce_in(mode, ("train", "test", "valid"), op="Flowers",
                   mode=mode)
        enforce_in(backend, ("pil", "cv2"), op="Flowers", backend=backend)
        enforce(data_file and label_file and setid_file,
                "Flowers: no network egress in this build — pass "
                "data_file/label_file/setid_file pointing at local copies "
                "of 102flowers.tgz / imagelabels.mat / setid.mat",
                error=UnavailableError, op="Flowers", download=download)
        self.backend = backend
        self.transform = transform
        self.flag = self.MODE_FLAG_MAP[mode]

        data_tar = tarfile.open(data_file)
        self.data_path = data_file.replace(".tgz", "/")
        if not os.path.exists(os.path.join(self.data_path, "jpg")):
            os.makedirs(self.data_path, exist_ok=True)
            data_tar.extractall(self.data_path)
        import scipy.io as scio
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self.flag][0]

    def __getitem__(self, idx):
        import os

        from PIL import Image
        index = int(self.indexes[idx])
        label = np.array([int(self.labels[index - 1])])
        path = os.path.join(self.data_path, "jpg",
                            "image_%05d.jpg" % index)
        image = Image.open(path)
        if self.backend == "cv2":
            image = np.array(image)
        if self.transform is not None:
            image = self.transform(image)
        return image, label.astype(np.int64)

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation (reference: vision/datasets/voc2012.py — same
    VOCtrainval tar layout): (image, label-mask) pairs listed by
    ImageSets/Segmentation/{train,val,trainval}.txt. Local tar only."""

    SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    MODE_FLAG_MAP = {"train": "train", "test": "val", "valid": "val",
                     "trainval": "trainval"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="pil"):
        import tarfile

        from ..enforce import UnavailableError, enforce, enforce_in
        mode = mode.lower()
        enforce_in(mode, tuple(self.MODE_FLAG_MAP), op="VOC2012",
                   mode=mode)
        enforce_in(backend, ("pil", "cv2"), op="VOC2012", backend=backend)
        enforce(data_file,
                "VOC2012: no network egress in this build — pass "
                "data_file= pointing at a local VOCtrainval tar",
                error=UnavailableError, op="VOC2012", download=download)
        self.backend = backend
        self.transform = transform
        self.flag = self.MODE_FLAG_MAP[mode]
        self.data_tar = tarfile.open(data_file)
        self.name2mem = {m.name: m for m in self.data_tar.getmembers()}
        sets = self.data_tar.extractfile(
            self.name2mem[self.SET_FILE.format(self.flag)])
        self.data, self.labels = [], []
        for line in sets:
            name = line.decode("utf-8").strip()
            if not name:
                continue
            self.data.append(self.DATA_FILE.format(name))
            self.labels.append(self.LABEL_FILE.format(name))

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image
        data = self.data_tar.extractfile(
            self.name2mem[self.data[idx]]).read()
        label = self.data_tar.extractfile(
            self.name2mem[self.labels[idx]]).read()
        data = Image.open(_io.BytesIO(data))
        label = Image.open(_io.BytesIO(label))
        if self.backend == "cv2":
            data, label = np.array(data), np.array(label)
        if self.transform is not None:
            data = self.transform(data)
        return data, label

    def __len__(self):
        return len(self.data)


__all__ += ["Flowers", "VOC2012"]

__all__ += ["DatasetFolder", "ImageFolder", "FashionMNIST",
            "IMG_EXTENSIONS"]
