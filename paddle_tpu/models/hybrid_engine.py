"""Generic hybrid-parallel train-step builder shared by the model families.

Compiles ONE program containing: forward (vocab-parallel embed, pipelined
blocks, TP collectives), backward, dp gradient pmean, and the optimizer
update — the TPU-native equivalent of the reference's per-strategy wrapper
stack (fleet/meta_parallel/*). Model files supply a per-device loss_fn and a
PartitionSpec tree; XLA schedules every collective over ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import shard_map as _shard_map

__all__ = ["build_train_step", "state_specs_for"]


def state_specs_for(optimizer, specs, example_params=None):
    """Sharding specs for the optimizer state pytree: every array that
    mirrors a parameter (slots, accumulators — found by matching the
    parameter's key path inside the state leaf's path) inherits that
    parameter's spec; everything else (step counters, scalars) replicates.
    This is what makes ZeRO composition free — sharding the state tree IS
    sharding the optimizer — and it works for ANY wrapper structure
    (gradient merge, multi_precision master slots, nested inners).

    Without example_params a synthetic fp32 example is derived from the
    spec tree — exact for any wrapper STRUCTURE, but dtype-conditional
    slots (multi_precision master weights) need the real example."""
    is_spec = lambda x: isinstance(x, P)
    if example_params is None:
        example_params = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((1,) * max(len(s), 1),
                                           jnp.float32),
            specs, is_leaf=is_spec)

    def path_keys(path):
        return tuple(str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path)

    spec_paths = {}
    for path, spec in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=is_spec)[0]:
        spec_paths[path_keys(path)] = spec
    lens = sorted({len(k) for k in spec_paths}, reverse=True)

    state_shape = jax.eval_shape(optimizer.init_state, example_params)

    def spec_for(path, leaf):
        keys = path_keys(path)
        for plen in lens:  # longest param-path embedded in the state path
            for i in range(len(keys) - plen + 1):
                cand = spec_paths.get(keys[i:i + plen])
                if cand is not None and len(cand) <= leaf.ndim:
                    return cand
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, state_shape)


def build_train_step(loss_fn: Callable, specs: Dict[str, Any], mesh: Mesh,
                     optimizer, data_spec: P = None, dp_axis: str = "dp",
                     extra_grad_axes=(), example_params=None,
                     grad_reduce_dtype="auto"):
    """loss_fn(params, tokens, labels) -> scalar, running per-device inside
    shard_map. Returns (jitted_step, shard_params, init_state).

    grad_reduce_dtype: cast gradients to this dtype for the dp reduction
    and back (the reference's fp16_allreduce meta-optimizer,
    fleet/meta_optimizers/fp16_allreduce_optimizer.py — halves the
    ICI/DCN bytes of the gradient all-reduce; bf16 recommended on TPU).
    The default "auto" reads the active fleet strategy, so the reference
    flow `strategy.fp16_allreduce = True; fleet.init(strategy=s)` engages
    with no extra plumbing; pass None to force fp32 reduction. Optimizers
    that manage their own synchronization (LocalSGD/DGC — attribute
    `_skips_grad_sync`) receive dp-UNreduced local gradients."""
    if grad_reduce_dtype == "auto":
        from ..distributed.fleet.fleet import fleet as _fleet
        grad_reduce_dtype = _fleet.grad_reduce_dtype()
    data_spec = P(dp_axis) if data_spec is None else data_spec
    sspec = state_specs_for(optimizer, specs, example_params)

    def shard_params(params):
        return jax.tree.map(
            lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
            params, specs)

    def init_state(params):
        # zeros_like under jit preserves input shardings
        return jax.jit(optimizer.init_state)(params)

    def local_step(params, opt_state, tokens, labels, lr):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, labels))(params)
        # dp gradient reduction (the EagerReducer equivalent — one pmean,
        # fused and overlapped by XLA). Self-synchronizing optimizers
        # (LocalSGD/DGC: _skips_grad_sync) own the dp axis but NOT the
        # extra axes (sep/context-parallel partial grads must always be
        # combined — skipping them would train on wrong gradients).
        skips_dp = getattr(optimizer, "_skips_grad_sync", False)
        dp_axes = () if skips_dp else (dp_axis,)
        extra_axes = tuple(extra_grad_axes)
        if dp_axes or extra_axes:
            def reduce_one(g):
                # extra axes (sep/context-parallel) combine genuinely
                # PARTIAL gradients — always in the grad's own dtype; the
                # reduced-dtype compression applies only to the dp
                # all-reduce of identical replicas, matching the reference
                # fp16_allreduce scope (dp grad allreduce only).
                if extra_axes:
                    g = lax.pmean(g, extra_axes)
                if dp_axes:
                    if grad_reduce_dtype is not None:
                        return lax.pmean(g.astype(grad_reduce_dtype),
                                         dp_axes).astype(g.dtype)
                    return lax.pmean(g, dp_axes)
                return g

            grads = jax.tree.map(reduce_one, grads)
        new_params, new_state = optimizer.apply(params, grads, opt_state, lr)
        return new_params, new_state, loss

    step = _shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, sspec, data_spec, data_spec, P()),
        out_specs=(specs, sspec, P()))
    return jax.jit(step), shard_params, init_state
