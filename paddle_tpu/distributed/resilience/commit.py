"""Crash-safe two-phase checkpoint commit on top of save/load_state_dict.

The sharded checkpoint writer (`checkpoint/save_state_dict.py`) makes each
individual file durable-or-absent (atomic_write), but a multi-file
checkpoint needs a directory-level commit point: a crash between the chunk
writes and the metadata — or between metadata and "done" — must never leave
a directory that `latest_checkpoint` could hand back to a resuming job.

Protocol (reference analog: the side-process save + barrier in
python/paddle/distributed/checkpoint/save_state_dict.py:291, hardened to
the Orbax/TensorStore commit discipline):

1. every process writes its files into a staging dir ``step_N.tmp/``;
2. a barrier (TCP store, or jax's coordination service) confirms ALL
   writers finished — no rank may observe a commit for data that another
   rank has not finished writing;
3. the coordinator fsyncs the staging tree, ``os.replace``-renames it to
   ``step_N/``, and writes a ``COMMITTED`` marker file LAST (itself via
   temp-file + rename + dir fsync);
4. ``latest_checkpoint`` only ever returns marker-bearing directories,
   garbage-collects uncommitted stragglers, and retention keeps the newest
   ``FLAGS_ckpt_keep_n`` committed steps.

A crash at ANY instant therefore yields one of: old committed set intact
(steps 1-3 before marker), or new step committed (after marker) — never a
half-checkpoint with a plausible-looking layout.
"""

from __future__ import annotations

import logging
import os
import re
import shutil
from typing import Dict, Optional

import jax

__all__ = ["commit_checkpoint", "latest_checkpoint", "checkpoint_step",
           "is_committed", "COMMIT_MARKER"]

logger = logging.getLogger("paddle_tpu")


def _emit(event: str, **fields) -> None:
    from ...observability import emit_event
    emit_event(event, **fields)

COMMIT_MARKER = "COMMITTED"
_STEP_RE = re.compile(r"^step_(\d+)$")

_COMMIT_SEQ = [0]  # per-process commit-attempt counter; equal across ranks
#                    because every rank calls commit_checkpoint in lockstep
#                    (same idiom as save_state_dict._SAVE_SEQ) — it keeps a
#                    RETRIED commit of the same step from sailing through
#                    the previous attempt's stale barrier keys


def step_path(root: str, step: int) -> str:
    return os.path.join(root, f"step_{int(step):08d}")


def checkpoint_step(path: str) -> int:
    """Parse the step number out of a committed checkpoint path."""
    m = _STEP_RE.match(os.path.basename(os.path.normpath(path)))
    if not m:
        raise ValueError(f"not a checkpoint step dir: {path!r}")
    return int(m.group(1))


def is_committed(path: str) -> bool:
    return os.path.isfile(os.path.join(path, COMMIT_MARKER))


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _barrier(store, nproc: int, coordinator_rank: int, name: str,
             timeout: Optional[float] = None) -> None:
    """All `nproc` processes arrive before any leaves. Store-based when a
    TCP store is available (pure host-side — safe while devices compute);
    otherwise jax's coordination service."""
    if nproc <= 1:
        return
    if store is not None:
        proc = jax.process_index()
        store.set(f"{name}/r{proc}", b"1")
        if proc == coordinator_rank:
            for r in range(nproc):
                store.wait([f"{name}/r{r}"], timeout)
            store.set(f"{name}/go", b"1")
        else:
            store.wait([f"{name}/go"], timeout)
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def _prune(root: str, keep_n: int) -> None:
    """Retention: delete all but the newest keep_n COMMITTED steps. The
    marker is unlinked first so a crash mid-delete downgrades the victim to
    an uncommitted straggler (GC'd later) instead of a corrupt 'committed'
    directory."""
    if keep_n <= 0:
        return
    committed = sorted(
        (int(m.group(1)), os.path.join(root, name))
        for name in os.listdir(root)
        for m in [_STEP_RE.match(name)]
        if m and is_committed(os.path.join(root, name)))
    for _step, path in committed[:-keep_n]:
        try:
            os.unlink(os.path.join(path, COMMIT_MARKER))
            shutil.rmtree(path, ignore_errors=True)
        except OSError:
            pass  # best-effort: retention must never fail a commit


def commit_checkpoint(state_dict: Dict, root: str, step: int, *,
                      store=None, coordinator_rank: int = 0,
                      async_save: bool = False,
                      keep_n: Optional[int] = None,
                      barrier_timeout: Optional[float] = None,
                      layout_extra: Optional[Dict] = None) -> str:
    """Atomically commit `state_dict` as checkpoint `step` under `root`.

    Returns the final committed directory. Idempotent: recommitting an
    already-committed step is a no-op (the resilient driver's preemption
    path may race a cadence checkpoint at the same boundary). Synchronous
    at the commit point even with async_save=True — the rename only happens
    once every byte is on disk.

    layout_extra: model-level topology hints (pp/vpp layout, comm plan,
    carry policies) recorded into the schema-v2 SavedLayout when
    FLAGS_ckpt_reshard is on — what elastic resume reshards by.
    """
    from ..checkpoint import save_state_dict, wait_async_save
    from . import faults

    if keep_n is None:
        from ...flags import flag
        keep_n = int(flag("ckpt_keep_n"))

    final = step_path(root, step)
    if is_committed(final):
        return final
    proc, nproc = jax.process_index(), jax.process_count()
    os.makedirs(root, exist_ok=True)
    staging = final + ".tmp"
    _COMMIT_SEQ[0] += 1
    tag = (f"resil/{os.environ.get('PADDLE_RESTART_COUNT', '0')}"
           f"/{_COMMIT_SEQ[0]}/{root}/{step}")

    if proc == coordinator_rank:
        # stale leftovers from a previous crashed incarnation of this step
        if os.path.isdir(final):
            shutil.rmtree(final)
        if os.path.isdir(staging):
            shutil.rmtree(staging)
    _barrier(store, nproc, coordinator_rank, f"{tag}/clean", barrier_timeout)

    save_state_dict(state_dict, staging, coordinator_rank=coordinator_rank,
                    async_save=async_save, store=store,
                    layout_extra=layout_extra)
    if async_save:
        wait_async_save()
    faults.maybe_fail("ckpt/before_commit")
    # phase 1 done: every writer's files are in staging
    _barrier(store, nproc, coordinator_rank, f"{tag}/staged", barrier_timeout)

    if proc == coordinator_rank:
        # every file in staging is already durable (atomic_write fsyncs the
        # file AND the staging dir entry on each write — re-fsyncing multi-GB
        # chunk files here would double the commit window); only the rename
        # itself still needs the parent dir fsynced
        _fsync_dir(staging)
        os.replace(staging, final)
        _fsync_dir(root)
        faults.maybe_fail("ckpt/after_rename")
        # marker LAST: its presence is the single bit that makes the
        # checkpoint discoverable
        from ..checkpoint.utils import atomic_write
        with atomic_write(os.path.join(final, COMMIT_MARKER)) as f:
            f.write(b"1")
        _prune(root, keep_n)
    _barrier(store, nproc, coordinator_rank, f"{tag}/committed",
             barrier_timeout)
    return final


def _loadable(path: str):
    """Cheap integrity check of a COMMITTED checkpoint dir before handing
    it to a resuming job: the metadata must unpickle and every data file
    it references must exist non-empty. Returns ``(failure_reason, md)``
    — reason None when the directory looks loadable, with the decoded
    Metadata riding along so the caller never pays the (MB-scale for 1B
    checkpoints) unpickle twice. Deliberately does NOT read tensor bytes
    — discovery must stay O(metadata)."""
    try:
        from ..checkpoint import load_metadata
        md = load_metadata(path)
    except FileNotFoundError:
        return "missing 0.metadata", None
    except Exception as e:  # truncated/corrupt pickle, foreign bytes, ...
        return f"unreadable metadata ({type(e).__name__}: {e})", None
    for fname in set(md.storage_metadata.values()):
        f = os.path.join(path, fname)
        if not os.path.isfile(f):
            return f"missing data file {fname}", None
        if os.path.getsize(f) == 0:
            return f"empty data file {fname}", None
    return None, md


def latest_checkpoint(root: str, *, gc: bool = True,
                      validate: bool = True,
                      with_metadata: bool = False):
    """Newest loadable COMMITTED checkpoint directory under `root`, or None.

    With gc=True (the restart path — any in-flight writer is dead by
    definition), uncommitted stragglers are deleted: ``*.tmp`` staging dirs
    and ``step_*`` dirs missing the COMMITTED marker. Pass gc=False to
    inspect a directory a live job may still be writing to.

    Hardened against a dirty checkpoint root: FOREIGN entries (dirs/files
    whose names are not step_N or step_N.tmp) are skipped with a warning —
    never deleted, they are not ours; a committed dir whose metadata is
    unreadable or whose referenced data files are missing is skipped with
    a warning event and discovery FALLS BACK to the previous committed
    step instead of handing a resuming job an unloadable directory
    (validate=False restores the pure marker check).

    with_metadata=True returns ``(path, md)`` instead — the Metadata the
    validation already decoded (None when validate=False or nothing is
    committed), so the resume path never unpickles it a second time."""
    if not os.path.isdir(root):
        return (None, None) if with_metadata else None
    committed = []
    stragglers = []
    foreign = []
    for name in os.listdir(root):
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            foreign.append(name)
            continue
        if name.endswith(".tmp") and _STEP_RE.match(name[:-len(".tmp")]):
            stragglers.append(path)
            continue
        m = _STEP_RE.match(name)
        if not m:
            foreign.append(name)
            continue
        if is_committed(path):
            committed.append((int(m.group(1)), path))
        else:
            stragglers.append(path)
    if foreign:
        logger.warning(
            "checkpoint root %s holds %d foreign entrie(s) %s — skipped, "
            "not garbage-collected (only step_N/step_N.tmp dirs are ours)",
            root, len(foreign), sorted(foreign)[:8])
        _emit("ckpt_root_foreign_entries", root=root,
              entries=sorted(foreign)[:8], count=len(foreign))
    if gc and jax.process_index() == 0:
        for path in stragglers:
            shutil.rmtree(path, ignore_errors=True)
    for _step, path in sorted(committed, reverse=True):
        md = None
        if validate:
            reason, md = _loadable(path)
            if reason is not None:
                logger.warning(
                    "committed checkpoint %s is not loadable (%s) — "
                    "falling back to the previous committed step",
                    path, reason)
                _emit("ckpt_unloadable_skipped", path=path, reason=reason)
                continue
        return (path, md) if with_metadata else path
    return (None, None) if with_metadata else None
